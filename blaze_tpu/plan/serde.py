"""Encode/decode between the protobuf wire schema (plan.proto) and the
engine's operator/expression objects.

Mirror of the reference's two-sided serde: the Scala builders
(NativeConverters.scala convertExpr / Native*Exec proto emission) and the
Rust decoder (`TryInto<Arc<dyn ExecutionPlan>>`, from_proto.rs:162-560) -
here both directions live in one module since both ends are ours.
"""

from __future__ import annotations

from typing import List, Optional

from blaze_tpu.types import DataType, Field, Schema, TypeId
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr, AggFn, Op
from blaze_tpu.plan import plan_pb2 as pb
from blaze_tpu.ops import (
    DebugExec,
    EmptyPartitionsExec,
    FilterExec,
    HashAggregateExec,
    AggMode,
    HashJoinExec,
    IpcReaderExec,
    IpcReadMode,
    IpcWriterExec,
    JoinType,
    LimitExec,
    ProjectExec,
    RenameColumnsExec,
    ShuffleWriterExec,
    SortExec,
    SortKey,
    SortMergeJoinExec,
    UnionExec,
)
from blaze_tpu.ops.streaming_smj import StreamingSortMergeJoinExec
from blaze_tpu.ops.base import PhysicalOp
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec

# ---------------------------------------------------------------------------
# types
# ---------------------------------------------------------------------------

_TID_TO_PB = {
    TypeId.NULL: pb.NULL,
    TypeId.BOOL: pb.BOOL,
    TypeId.INT8: pb.INT8,
    TypeId.INT16: pb.INT16,
    TypeId.INT32: pb.INT32,
    TypeId.INT64: pb.INT64,
    TypeId.FLOAT32: pb.FLOAT32,
    TypeId.FLOAT64: pb.FLOAT64,
    TypeId.UTF8: pb.UTF8,
    TypeId.BINARY: pb.BINARY,
    TypeId.DATE32: pb.DATE32,
    TypeId.TIMESTAMP_US: pb.TIMESTAMP_US,
    TypeId.DECIMAL: pb.DECIMAL,
}
_PB_TO_TID = {v: k for k, v in _TID_TO_PB.items()}


def dtype_to_proto(dt: DataType) -> pb.DataTypeProto:
    return pb.DataTypeProto(
        id=_TID_TO_PB[dt.id], precision=dt.precision, scale=dt.scale
    )


def dtype_from_proto(p: pb.DataTypeProto) -> DataType:
    return DataType(_PB_TO_TID[p.id], p.precision, p.scale)


def schema_to_proto(s: Schema) -> pb.SchemaProto:
    return pb.SchemaProto(
        fields=[
            pb.FieldProto(
                name=f.name, dtype=dtype_to_proto(f.dtype),
                nullable=f.nullable,
            )
            for f in s
        ]
    )


def schema_from_proto(p: pb.SchemaProto) -> Schema:
    return Schema(
        [
            Field(f.name, dtype_from_proto(f.dtype), f.nullable)
            for f in p.fields
        ]
    )


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

_OP_TO_PB = {
    Op.ADD: pb.ADD, Op.SUB: pb.SUB, Op.MUL: pb.MUL, Op.DIV: pb.DIV,
    Op.MOD: pb.MOD, Op.EQ: pb.EQ, Op.NEQ: pb.NEQ, Op.LT: pb.LT,
    Op.LTE: pb.LTE, Op.GT: pb.GT, Op.GTE: pb.GTE, Op.AND: pb.AND,
    Op.OR: pb.OR, Op.BITAND: pb.BITAND, Op.BITOR: pb.BITOR,
    Op.BITXOR: pb.BITXOR, Op.SHL: pb.SHL, Op.SHR: pb.SHR,
}
_PB_TO_OP = {v: k for k, v in _OP_TO_PB.items()}

_AGG_TO_PB = {
    AggFn.MIN: pb.MIN, AggFn.MAX: pb.MAX, AggFn.SUM: pb.SUM,
    AggFn.AVG: pb.AVG, AggFn.COUNT: pb.COUNT,
    AggFn.COUNT_STAR: pb.COUNT_STAR, AggFn.VAR_SAMP: pb.VAR_SAMP,
    AggFn.VAR_POP: pb.VAR_POP, AggFn.STDDEV_SAMP: pb.STDDEV_SAMP,
    AggFn.STDDEV_POP: pb.STDDEV_POP, AggFn.FIRST: pb.FIRST,
    AggFn.LAST: pb.LAST,
}
_PB_TO_AGG = {v: k for k, v in _AGG_TO_PB.items()}

_INT_LIKE = {
    TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
    TypeId.DATE32, TypeId.TIMESTAMP_US, TypeId.DECIMAL,
}


def expr_to_proto(e: ir.Expr) -> pb.ExprProto:
    p = pb.ExprProto()
    if isinstance(e, ir.Col):
        p.column = e.name
    elif isinstance(e, ir.BoundCol):
        p.bound_column = e.index
        p.bound_dtype.CopyFrom(dtype_to_proto(e.dtype))
    elif isinstance(e, ir.Literal):
        lit = p.literal
        lit.dtype.CopyFrom(dtype_to_proto(e.dtype))
        if e.value is None:
            lit.is_null = True
        elif e.dtype.id is TypeId.BOOL:
            lit.bool_value = bool(e.value)
        elif e.dtype.id in _INT_LIKE:
            lit.int_value = int(e.value)
        elif e.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            lit.float_value = float(e.value)
        elif e.dtype.id is TypeId.UTF8:
            lit.string_value = e.value
        elif e.dtype.id is TypeId.BINARY:
            lit.bytes_value = e.value
        else:
            raise NotImplementedError(f"literal {e.dtype}")
    elif isinstance(e, ir.Cast):
        p.cast.child.CopyFrom(expr_to_proto(e.child))
        p.cast.to.CopyFrom(dtype_to_proto(e.to))
    elif isinstance(e, ir.BinaryOp):
        p.binary.op = _OP_TO_PB[e.op]
        p.binary.left.CopyFrom(expr_to_proto(e.left))
        p.binary.right.CopyFrom(expr_to_proto(e.right))
    elif isinstance(e, ir.Not):
        p.logical_not.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, ir.Negate):
        p.negate.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, ir.IsNull):
        p.is_null.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, ir.IsNotNull):
        p.is_not_null.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, ir.InList):
        p.in_list.child.CopyFrom(expr_to_proto(e.child))
        for v in e.values:
            p.in_list.values.append(expr_to_proto(v))
        p.in_list.negated = e.negated
    elif isinstance(e, ir.If):
        p.if_.cond.CopyFrom(expr_to_proto(e.cond))
        p.if_.then.CopyFrom(expr_to_proto(e.then))
        p.if_.otherwise.CopyFrom(expr_to_proto(e.otherwise))
    elif isinstance(e, ir.CaseWhen):
        for c, r in e.branches:
            b = p.case_.branches.add()
            b.cond.CopyFrom(expr_to_proto(c))
            b.result.CopyFrom(expr_to_proto(r))
        if e.otherwise is not None:
            p.case_.otherwise.CopyFrom(expr_to_proto(e.otherwise))
    elif isinstance(e, ir.ScalarFn):
        p.scalar_fn.name = e.name
        for a in e.args:
            p.scalar_fn.args.append(expr_to_proto(a))
    elif isinstance(e, ir.Coalesce):
        for a in e.args:
            p.coalesce.args.append(expr_to_proto(a))
    elif isinstance(e, ir.AggExpr):
        p.agg.fn = _AGG_TO_PB[e.fn]
        if e.child is not None:
            p.agg.child.CopyFrom(expr_to_proto(e.child))
    else:
        raise NotImplementedError(type(e))
    return p


def expr_from_proto(p: pb.ExprProto) -> ir.Expr:
    kind = p.WhichOneof("kind")
    if kind == "column":
        return ir.Col(p.column)
    if kind == "bound_column":
        return ir.BoundCol(p.bound_column, dtype_from_proto(p.bound_dtype))
    if kind == "literal":
        lit = p.literal
        dt = dtype_from_proto(lit.dtype)
        if lit.is_null:
            return ir.Literal(None, dt)
        which = lit.WhichOneof("value")
        v = getattr(lit, which)
        return ir.Literal(v, dt)
    if kind == "cast":
        return ir.Cast(
            expr_from_proto(p.cast.child), dtype_from_proto(p.cast.to)
        )
    if kind == "binary":
        return ir.BinaryOp(
            _PB_TO_OP[p.binary.op],
            expr_from_proto(p.binary.left),
            expr_from_proto(p.binary.right),
        )
    if kind == "logical_not":
        return ir.Not(expr_from_proto(p.logical_not))
    if kind == "negate":
        return ir.Negate(expr_from_proto(p.negate))
    if kind == "is_null":
        return ir.IsNull(expr_from_proto(p.is_null))
    if kind == "is_not_null":
        return ir.IsNotNull(expr_from_proto(p.is_not_null))
    if kind == "in_list":
        return ir.InList(
            expr_from_proto(p.in_list.child),
            tuple(expr_from_proto(v) for v in p.in_list.values),
            p.in_list.negated,
        )
    if kind == "if_":
        return ir.If(
            expr_from_proto(p.if_.cond),
            expr_from_proto(p.if_.then),
            expr_from_proto(p.if_.otherwise),
        )
    if kind == "case_":
        return ir.CaseWhen(
            tuple(
                (expr_from_proto(b.cond), expr_from_proto(b.result))
                for b in p.case_.branches
            ),
            expr_from_proto(p.case_.otherwise)
            if p.case_.HasField("otherwise")
            else None,
        )
    if kind == "scalar_fn":
        return ir.ScalarFn(
            p.scalar_fn.name,
            tuple(expr_from_proto(a) for a in p.scalar_fn.args),
        )
    if kind == "coalesce":
        return ir.Coalesce(
            tuple(expr_from_proto(a) for a in p.coalesce.args)
        )
    if kind == "agg":
        return ir.AggExpr(
            _PB_TO_AGG[p.agg.fn],
            expr_from_proto(p.agg.child)
            if p.agg.HasField("child")
            else None,
        )
    raise NotImplementedError(kind)


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------

_JT_TO_PB = {
    JoinType.INNER: pb.INNER, JoinType.LEFT: pb.LEFT,
    JoinType.RIGHT: pb.RIGHT, JoinType.FULL: pb.FULL,
    JoinType.LEFT_SEMI: pb.LEFT_SEMI, JoinType.LEFT_ANTI: pb.LEFT_ANTI,
    JoinType.LEFT_ANTI_NULL_AWARE: pb.LEFT_ANTI_NULL_AWARE,
}
_PB_TO_JT = {v: k for k, v in _JT_TO_PB.items()}

_MODE_TO_PB = {
    AggMode.PARTIAL: pb.PARTIAL, AggMode.FINAL: pb.FINAL,
    AggMode.COMPLETE: pb.COMPLETE,
}
_PB_TO_MODE = {v: k for k, v in _MODE_TO_PB.items()}

_IPC_TO_PB = {
    IpcReadMode.CHANNEL: pb.CHANNEL,
    IpcReadMode.CHANNEL_UNCOMPRESSED: pb.CHANNEL_UNCOMPRESSED,
    IpcReadMode.CHANNEL_AND_FILE_SEGMENT: pb.CHANNEL_AND_FILE_SEGMENT,
}
_PB_TO_IPC = {v: k for k, v in _IPC_TO_PB.items()}


def plan_from_proto(p: pb.PlanProto) -> PhysicalOp:
    kind = p.WhichOneof("kind")
    if kind == "parquet_scan":
        ps = p.parquet_scan
        groups = [
            [FileRange(fr.path, fr.start, fr.length) for fr in g.files]
            for g in ps.file_groups
        ]
        schema = (
            schema_from_proto(ps.schema) if ps.schema.fields else None
        )
        projection = (
            [schema.fields[i].name for i in ps.projection]
            if ps.projection and schema
            else (schema.names() if schema else None)
        )
        pruning = (
            expr_from_proto(ps.pruning_predicate)
            if ps.HasField("pruning_predicate")
            else None
        )
        return ParquetScanExec(groups, schema, projection, pruning)
    if kind == "ipc_reader":
        r = p.ipc_reader
        return IpcReaderExec(
            r.resource_id, schema_from_proto(r.schema),
            r.num_partitions, _PB_TO_IPC[r.mode],
        )
    if kind == "empty_partitions":
        return EmptyPartitionsExec(
            schema_from_proto(p.empty_partitions.schema),
            p.empty_partitions.num_partitions,
        )
    if kind == "project":
        return ProjectExec(
            plan_from_proto(p.project.input),
            [
                (expr_from_proto(ne.expr), ne.name)
                for ne in p.project.exprs
            ],
        )
    if kind == "filter":
        return FilterExec(
            plan_from_proto(p.filter.input),
            expr_from_proto(p.filter.predicate),
        )
    if kind == "sort":
        return SortExec(
            plan_from_proto(p.sort.input),
            [
                SortKey(
                    expr_from_proto(k.expr), k.ascending, k.nulls_first
                )
                for k in p.sort.keys
            ],
            fetch=None if p.sort.fetch < 0 else p.sort.fetch,
        )
    if kind == "union":
        return UnionExec([plan_from_proto(i) for i in p.union.inputs])
    if kind == "limit":
        return LimitExec(plan_from_proto(p.limit.input), p.limit.limit)
    if kind == "hash_aggregate":
        h = p.hash_aggregate
        return HashAggregateExec(
            plan_from_proto(h.input),
            keys=[(expr_from_proto(k.expr), k.name) for k in h.keys],
            aggs=[(expr_from_proto(a.expr), a.name) for a in h.aggs],
            mode=_PB_TO_MODE[h.mode],
        )
    if kind == "hash_join":
        h = p.hash_join
        return HashJoinExec(
            plan_from_proto(h.left), plan_from_proto(h.right),
            list(h.left_keys), list(h.right_keys),
            _PB_TO_JT[h.join_type],
        )
    if kind == "sort_merge_join":
        h = p.sort_merge_join
        left = plan_from_proto(h.left)
        right = plan_from_proto(h.right)
        if h.streaming:
            try:
                return StreamingSortMergeJoinExec(
                    left, right, list(h.left_keys),
                    list(h.right_keys), _PB_TO_JT[h.join_type],
                )
            except NotImplementedError:
                pass  # string keys: materializing core below
        return SortMergeJoinExec(
            left, right,
            list(h.left_keys), list(h.right_keys),
            _PB_TO_JT[h.join_type],
        )
    if kind == "shuffle_writer":
        s = p.shuffle_writer
        mode = {pb.HASH: "hash", pb.SINGLE: "single",
                pb.ROUND_ROBIN: "round_robin",
                pb.RANGE: "range"}[s.mode]
        bounds = []
        for row in s.range_bounds:
            vals = []
            for lp in row.values:
                wrap = pb.ExprProto()
                wrap.literal.CopyFrom(lp)
                vals.append(expr_from_proto(wrap).value)
            bounds.append(tuple(vals))
        return ShuffleWriterExec(
            plan_from_proto(s.input),
            [expr_from_proto(k) for k in s.keys],
            s.num_partitions, s.data_file, s.index_file, mode,
            range_bounds=bounds or None,
            sort_ascending=list(s.sort_ascending) or None,
        )
    if kind == "ipc_writer":
        return IpcWriterExec(
            plan_from_proto(p.ipc_writer.input),
            p.ipc_writer.resource_id,
        )
    if kind == "rename_columns":
        return RenameColumnsExec(
            plan_from_proto(p.rename_columns.input),
            list(p.rename_columns.names),
        )
    if kind == "debug":
        return DebugExec(
            plan_from_proto(p.debug.input), p.debug.debug_id
        )
    if kind == "window":
        from blaze_tpu.ops.window import WindowExec, WindowFn

        w = p.window
        return WindowExec(
            plan_from_proto(w.input),
            partition_by=[expr_from_proto(e) for e in w.partition_by],
            order_by=[
                SortKey(expr_from_proto(k.expr), k.ascending,
                        k.nulls_first)
                for k in w.order_by
            ],
            functions=[
                WindowFn(
                    f.kind,
                    expr_from_proto(f.source)
                    if f.HasField("source") else None,
                    f.output,
                    # offset is encoded biased by +1 so proto3's 0
                    # default means "unset -> 1" while lag(v, 0) stays
                    # representable
                    (f.offset - 1) if f.offset else 1,
                    (
                        (
                            f.frame,
                            None if f.frame_lo < 0 else f.frame_lo,
                            None if f.frame_hi < 0 else f.frame_hi,
                        )
                        if f.frame else None
                    ),
                )
                for f in w.functions
            ],
        )
    raise NotImplementedError(kind)


def plan_to_proto(op: PhysicalOp) -> pb.PlanProto:
    p = pb.PlanProto()
    if isinstance(op, ParquetScanExec):
        ps = p.parquet_scan
        for g in op.file_groups:
            gp = ps.file_groups.add()
            for fr in g:
                gp.files.add(path=fr.path, start=fr.start,
                             length=fr.length)
        ps.schema.CopyFrom(schema_to_proto(op.schema))
        if op.pruning_predicate is not None:
            ps.pruning_predicate.CopyFrom(
                expr_to_proto(op.pruning_predicate)
            )
    elif isinstance(op, IpcReaderExec):
        p.ipc_reader.resource_id = op.resource_id
        p.ipc_reader.schema.CopyFrom(schema_to_proto(op.schema))
        p.ipc_reader.num_partitions = op.partition_count
        p.ipc_reader.mode = _IPC_TO_PB[op.mode]
    elif isinstance(op, EmptyPartitionsExec):
        p.empty_partitions.schema.CopyFrom(schema_to_proto(op.schema))
        p.empty_partitions.num_partitions = op.partition_count
    elif isinstance(op, ProjectExec):
        p.project.input.CopyFrom(plan_to_proto(op.children[0]))
        for e, name in op.exprs:
            p.project.exprs.add(expr=expr_to_proto(e), name=name)
    elif isinstance(op, FilterExec):
        p.filter.input.CopyFrom(plan_to_proto(op.children[0]))
        p.filter.predicate.CopyFrom(expr_to_proto(op.predicate))
    elif isinstance(op, SortExec):
        p.sort.input.CopyFrom(plan_to_proto(op.children[0]))
        for k in op.keys:
            p.sort.keys.add(
                expr=expr_to_proto(k.expr), ascending=k.ascending,
                nulls_first=k.nulls_first,
            )
        p.sort.fetch = op.fetch if op.fetch is not None else -1
    elif isinstance(op, UnionExec):
        for c in op.children:
            p.union.inputs.add().CopyFrom(plan_to_proto(c))
    elif isinstance(op, LimitExec):
        p.limit.input.CopyFrom(plan_to_proto(op.children[0]))
        p.limit.limit = op.limit
    elif isinstance(op, HashAggregateExec):
        h = p.hash_aggregate
        h.input.CopyFrom(plan_to_proto(op.children[0]))
        for e, name in op.keys:
            h.keys.add(expr=expr_to_proto(e), name=name)
        for a, name in op.aggs:
            h.aggs.add(expr=expr_to_proto(a), name=name)
        h.mode = _MODE_TO_PB[op.mode]
    elif isinstance(op, HashJoinExec):
        h = p.hash_join
        h.left.CopyFrom(plan_to_proto(op.children[0]))
        h.right.CopyFrom(plan_to_proto(op.children[1]))
        h.left_keys.extend(
            op.children[0].schema.fields[i].name for i in op.left_keys
        )
        h.right_keys.extend(
            op.children[1].schema.fields[i].name for i in op.right_keys
        )
        h.join_type = _JT_TO_PB[op.join_type]
    elif isinstance(op, (SortMergeJoinExec, StreamingSortMergeJoinExec)):
        h = p.sort_merge_join
        h.left.CopyFrom(plan_to_proto(op.children[0]))
        h.right.CopyFrom(plan_to_proto(op.children[1]))
        h.left_keys.extend(
            op.children[0].schema.fields[i].name for i in op.left_keys
        )
        h.right_keys.extend(
            op.children[1].schema.fields[i].name for i in op.right_keys
        )
        h.join_type = _JT_TO_PB[op.join_type]
        h.streaming = isinstance(op, StreamingSortMergeJoinExec)
    elif isinstance(op, ShuffleWriterExec):
        s = p.shuffle_writer
        s.input.CopyFrom(plan_to_proto(op.children[0]))
        for k in op.key_exprs:
            s.keys.add().CopyFrom(expr_to_proto(k))
        s.num_partitions = op.num_partitions
        s.data_file = op.data_file
        s.index_file = op.index_file
        s.mode = {"hash": pb.HASH, "single": pb.SINGLE,
                  "round_robin": pb.ROUND_ROBIN,
                  "range": pb.RANGE}[op.mode]
        if op.mode == "range":
            from blaze_tpu.exprs.typing import infer_dtype

            s.sort_ascending.extend(op.sort_ascending)
            key_dtypes = [
                infer_dtype(e, op.children[0].schema)
                for e in op.key_exprs
            ]
            for bound in op.range_bounds:
                row = s.range_bounds.add()
                for v, dt in zip(bound, key_dtypes):
                    row.values.add().CopyFrom(
                        expr_to_proto(ir.Literal(v, dt)).literal
                    )
    elif isinstance(op, IpcWriterExec):
        p.ipc_writer.input.CopyFrom(plan_to_proto(op.children[0]))
        p.ipc_writer.resource_id = op.resource_id
    elif isinstance(op, RenameColumnsExec):
        p.rename_columns.input.CopyFrom(plan_to_proto(op.children[0]))
        p.rename_columns.names.extend(op.names)
    elif isinstance(op, DebugExec):
        p.debug.input.CopyFrom(plan_to_proto(op.children[0]))
        p.debug.debug_id = op.debug_id
    elif type(op).__name__ == "WindowExec":
        w = p.window
        w.input.CopyFrom(plan_to_proto(op.children[0]))
        for e in op.partition_by:
            w.partition_by.add().CopyFrom(expr_to_proto(e))
        for k in op.order_by:
            w.order_by.add(
                expr=expr_to_proto(k.expr), ascending=k.ascending,
                nulls_first=k.nulls_first,
            )
        for f in op.functions:
            fp = w.functions.add(kind=f.kind, output=f.output)
            if f.source is not None:
                fp.source.CopyFrom(expr_to_proto(f.source))
            fp.offset = f.offset + 1  # +1 bias: see decode side
            if f.frame is not None:
                fp.frame = f.frame[0]
                fp.frame_lo = -1 if f.frame[1] is None else f.frame[1]
                fp.frame_hi = -1 if f.frame[2] is None else f.frame[2]
    else:
        raise NotImplementedError(type(op))
    return p


def task_to_proto(op: PhysicalOp, partition: int,
                  task_id: str = "task",
                  file_resources=None) -> bytes:
    """`file_resources`: {resource_id: [FileSegment | RemoteSegment,
    ...]} shipped with the task so IpcReader leaves resolve without an
    in-process registry (cross-process/host execution). List order is
    preserved on the wire - it IS the read order."""
    from blaze_tpu.runtime.transport import RemoteSegment

    t = pb.TaskDefinitionProto(partition=partition, task_id=task_id)
    t.plan.CopyFrom(plan_to_proto(op))
    for rid, segments in (file_resources or {}).items():
        rp = t.file_resources.add(resource_id=rid)
        for seg in segments:
            o = rp.ordered.add()
            if isinstance(seg, RemoteSegment):
                o.remote.host = seg.host
                o.remote.port = seg.port
                o.remote.path = seg.path
                o.remote.start = seg.offset
                o.remote.length = seg.length
            else:
                o.local.path = seg.path
                o.local.start = seg.offset
                o.local.length = seg.length
    return t.SerializeToString()


def task_from_proto(data: bytes):
    from blaze_tpu.ops.ipc_reader import FileSegment
    from blaze_tpu.runtime.transport import RemoteSegment

    t = pb.TaskDefinitionProto()
    t.ParseFromString(data)
    resources = {}
    for rp in t.file_resources:
        # legacy local-only field first, then the ordered mixed list
        segs = [
            FileSegment(s.path, s.start, s.length) for s in rp.segments
        ]
        for o in rp.ordered:
            if o.WhichOneof("kind") == "remote":
                r = o.remote
                segs.append(
                    RemoteSegment(r.host, r.port, r.path, r.start,
                                  r.length)
                )
            else:
                segs.append(
                    FileSegment(o.local.path, o.local.start,
                                o.local.length)
                )
        resources[rp.resource_id] = (lambda ss: (lambda p: ss))(segs)
    return plan_from_proto(t.plan), t.partition, t.task_id, resources
