"""Fingerprint-keyed mesh program cache (ISSUE 20 tentpole d).

MESHATTR_r01 measured the problem this kills: a FRESH lowering of a
plan already traced in the process re-pays the full trace+compile
(~10 s at 8 devices) because the traced program lived on the mesh OP
INSTANCE - object identity, not program identity. The mesh program
holders (parallel/sharded.DistributedGroupBy / DistributedBroadcastJoin
/ DistributedRepartition, and the pipeline/sort program bundles in
parallel/mesh_exec) already carry their own signature-keyed trace state
(`prepare()` returns True only when a trace actually ran); caching the
HOLDER by structural program identity + mesh shape makes a re-lowered
plan - a second QueryService in the same process, a repeat of the same
plan after the op was discarded - hit the existing trace: `prepare()`
sees a known signature, no retrace, `mesh_trace` ~ 0.

Key = (kind, structural-key, mesh-key). The structural key is the same
expression-repr material the ops already feed `meshprof.note_trace`
(bound IR dataclasses repr structurally), WITHOUT the argument
signature - argument shapes are the holder's own business. The mesh
key pins device identity and axis layout: a program traced for one
mesh must never run on another.

Thread-safe bounded LRU. Entries are live program holders holding
compiled executables; the bound is a safety valve, not a memory model
(the jit cache underneath is the real residency).
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Hashable, Tuple

_DEFAULT_CAPACITY = 64


def mesh_cache_key(mesh) -> Tuple:
    """Device identity + axis layout: the part of program identity the
    plan structure does not carry."""
    return (
        tuple(d.id for d in mesh.devices.flat),
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
    )


class ProgramCache:
    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Hashable, object]" = (
            collections.OrderedDict()
        )
        self.hits = 0
        self.misses = 0

    def get_or_build(self, key: Hashable,
                     builder: Callable[[], object]) -> object:
        """Return the cached holder for `key`, building (OUTSIDE the
        lock - builders construct pjit programs) and inserting on a
        miss. A racing double-build keeps the first-inserted holder so
        every caller converges on one program."""
        from blaze_tpu.obs.metrics import REGISTRY

        with self._lock:
            holder = self._entries.get(key)
            if holder is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                REGISTRY.inc("blaze_mesh_program_cache_hits_total")
                return holder
        built = builder()
        with self._lock:
            holder = self._entries.get(key)
            if holder is not None:
                self.hits += 1
                REGISTRY.inc("blaze_mesh_program_cache_hits_total")
                return holder
            self.misses += 1
            REGISTRY.inc("blaze_mesh_program_cache_misses_total")
            self._entries[key] = built
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
        return built

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "capacity": self.capacity,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# process-wide: program identity is structural, so sharing across
# QueryService instances is the whole point (satellite: retrace delta 0
# across two services in one process)
PROGRAM_CACHE = ProgramCache()


def _reset_for_tests() -> None:
    PROGRAM_CACHE.clear()
    PROGRAM_CACHE.hits = 0
    PROGRAM_CACHE.misses = 0
