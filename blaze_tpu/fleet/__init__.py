"""Fleet mesh tier (ISSUE 20): hybrid ICI x DCN multi-host execution.

One large query executes across N `serve` hosts forming a hybrid
ICI x DCN mesh - each host runs whole per-host stages on its local
device mesh (the PR 7 operators), stage boundaries move between hosts
over the `MESH_EXCHANGE` wire verb as the same framed Arrow-IPC
segments every other data path uses - while the router keeps treating
each host as an independent replica for small queries.

Modules (imported lazily to keep this package cheap for the many
callers that only need one piece):

  program_cache  fingerprint-keyed cache of lowered mesh programs
                 (plan structure + mesh shape, NOT op identity) - a
                 fresh QueryService re-lowering the same plan reuses
                 the traced program instead of re-paying the ~10 s
                 trace MESHATTR_r01 flagged
  claims         FleetDeviceLedger: mesh queries reserve DEVICES
                 across hosts (claim/release, per-tenant caps,
                 DRAINING-shaped exhaustion) so fleet mesh composes
                 with tenant budgets and DRR fairness
  exchange       the serve-side MESH_EXCHANGE handler: remote stage
                 specs in, framed Arrow-IPC segments out (the DCN
                 exchange plane)
  exec           FleetMeshExec - the coordinator op driving per-host
                 ICI stages joined by DCN exchanges, with the
                 `fleet.exchange` chaos seam and the degrade ladder
                 (fleet -> single-host mesh -> single-device)
"""
