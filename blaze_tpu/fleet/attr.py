"""Fleet stage anatomy probe: the `mesh-attr --fleet` child half.

Runs the fleet grouped-agg shape against a REAL in-process peer (a
second QueryService behind a wire listener - the same two-hosts-in-
one-process emulation the fleet tests use) and attributes the stage
wall across the sub-phases, `mesh_dcn` (the DCN exchange rounds)
sitting next to the six single-host phases. The parent asserts the
attribution covers >= 0.95 of the measured stage wall - the fleet
tier earns its keep only if we can SAY where the DCN time goes.

Expects the process device count to already match `n_dev` (the
parent forces it via XLA_FLAGS before any backend init).
"""

from __future__ import annotations

import tempfile
import time
from typing import Any, Dict

OP_KEY = "fleet.groupby"


def run_fleet_attr_probe(n_dev: int, rows: int = 1 << 18,
                         iters: int = 4) -> Dict[str, Any]:
    import numpy as np
    import pyarrow as pa

    import jax

    from blaze_tpu.batch import ColumnBatch
    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.fleet.exec import FleetContext, FleetMeshExec
    from blaze_tpu.obs import meshprof
    from blaze_tpu.ops import (
        AggMode,
        HashAggregateExec,
        MemoryScanExec,
    )
    from blaze_tpu.planner.distribute import (
        insert_exchanges,
        lower_plan_to_fleet,
    )
    from blaze_tpu.runtime.executor import run_plan
    from blaze_tpu.runtime.gateway import TaskGatewayServer
    from blaze_tpu.service import QueryService

    assert len(jax.devices()) == n_dev, (
        f"expected {n_dev} devices, saw {len(jax.devices())} "
        "(the device count freezes at first backend init - run the "
        "probe in a fresh subprocess)"
    )
    n_parts = 8
    per = max(1, rows // n_parts)
    rng = np.random.default_rng(17)
    parts, schema = [], None
    for _ in range(n_parts):
        k = rng.integers(0, 4096, per).astype(np.int64)
        v = rng.integers(0, 1000, per).astype(np.int64)
        cb = ColumnBatch.from_arrow(pa.record_batch({"k": k, "v": v}))
        schema = cb.schema
        parts.append([cb])
    shuffle_dir = tempfile.mkdtemp(prefix="blaze_fleet_attr_")

    def sandwich():
        return insert_exchanges(
            HashAggregateExec(
                MemoryScanExec(parts, schema),
                keys=[(Col("k"), "k")],
                aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                      (AggExpr(AggFn.COUNT_STAR, None), "n")],
                mode=AggMode.COMPLETE,
            ),
            n_parts, shuffle_dir=shuffle_dir,
        )

    doc: Dict[str, Any] = {
        "n_devices": n_dev, "rows": per * n_parts, "iters": iters,
        "hosts": 2,
    }
    peer = QueryService(enable_cache=False, enable_trace=False,
                        mesh_mode="on")
    srv = TaskGatewayServer(service=peer)
    srv.__enter__()
    try:
        host, port = srv.address
        fleet = FleetContext([f"{host}:{port}"])
        lowered = lower_plan_to_fleet(sandwich(), fleet, mode="on")
        fleet_lowered = isinstance(lowered, FleetMeshExec)
        doc["fleet_lowered"] = fleet_lowered
        if not fleet_lowered:
            return doc

        def run_once():
            lowered._result = None  # fresh execution, warm programs
            return run_plan(lowered)

        with meshprof.capture() as cold_rollup:
            t0 = time.perf_counter()
            run_once()  # cold: pays the peer's trace+compile too
            cold_wall = time.perf_counter() - t0
        assert not lowered._use_fallback, "fleet path degraded"
        cold_snap = cold_rollup.snapshot().get(OP_KEY, {})
        doc["cold"] = {
            "wall": round(cold_wall, 4),
            "subphases": {
                name: st["p50"] for name, st in
                (cold_snap.get("subphases") or {}).items()
            },
        }
        walls = []
        with meshprof.capture() as rol:
            for _ in range(max(1, iters)):
                t0 = time.perf_counter()
                run_once()
                walls.append(time.perf_counter() - t0)
        assert not lowered._use_fallback, "fleet path degraded"
    finally:
        srv.__exit__(None, None, None)
        peer.close()
    walls.sort()
    median = walls[len(walls) // 2]
    doc["wall"] = {
        "median": round(median, 4),
        "spread": round(
            (walls[-1] - walls[0]) / median, 3
        ) if median > 0 else 0.0,
        "k": len(walls),
    }
    snap = rol.snapshot().get(OP_KEY) or {}
    doc["subphases"] = snap.get("subphases") or {}
    doc["bytes_staged"] = snap.get("bytes_staged", 0)
    wall_stat = snap.get("stage_wall") or {}
    wall_p50 = wall_stat.get("p50", 0.0)
    sub_sum = sum(
        doc["subphases"].get(n, {}).get("p50", 0.0)
        for n in meshprof.STAGE_SUBPHASES
    )
    doc["reconcile"] = {
        "wall_p50": round(wall_p50, 6),
        "subphase_sum": round(sub_sum, 6),
        "coverage": round(sub_sum / wall_p50, 4)
        if wall_p50 > 0 else 0.0,
    }
    dcn = doc["subphases"].get("mesh_dcn", {}).get("p50", 0.0)
    doc["dcn_share"] = round(dcn / wall_p50, 4) if wall_p50 else 0.0
    return doc
