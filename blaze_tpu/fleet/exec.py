"""Fleet mesh coordinator: one query across N serve hosts.

`FleetMeshExec` is the hybrid ICI x DCN tier's root operator: a
grouped aggregation whose input partitions are split across the fleet,
partially aggregated on each host's OWN device mesh (the existing ICI
tier, lowered per host by fleet/exchange's stage handler), exchanged
between hosts by key-hash bucket over the MESH_EXCHANGE wire verb (the
DCN plane), and final-merged on the bucket owners. The coordinator is
host 0: its stages run in-process (no wire hop for co-located data),
peers are driven over ServiceClient.mesh_exchange on concurrent
threads (star topology - the coordinator mediates both rounds).

Failure policy - DELIBERATELY different from the single-host mesh
ladder (parallel/mesh_exec.degrade_or_raise): a dead peer is not
transient from this query's point of view (re-running the fleet stage
against the same dead host cannot help), so ConnectionError/OSError
from the DCN plane DEGRADES to the single-host fallback instead of
propagating to the task-retry tier. Only cancellation propagates. The
degradation target is the single-host mesh lowering of the same plan,
which itself degrades device-ineligible inputs to single-device - the
full ladder the ISSUE names: fleet -> single-host mesh ->
single-device, zero client-visible failures.

Chaos seam: `fleet.exchange` fires before every peer round trip
(STALL under injected latency, degrade under injected faults), the
fleet twin of `mesh.exchange`.

Admission: the stage claims devices fleet-wide (fleet/claims, routed
through the router when one is configured) before any work moves; a
denied claim degrades exactly like a dead peer. `BLAZE_FLEET_TEST_
DELAY_S` holds the coordinator between the claim and the first DCN
round - the deterministic mid-stage window the SIGKILL test needs.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Iterator, List, Optional, Sequence, Tuple

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.errors import ErrorClass, classify
from blaze_tpu.fleet.claims import FleetClaimDenied, FleetDeviceLedger
from blaze_tpu.io.ipc import decode_ipc_parts, encode_ipc_segment
from blaze_tpu.obs import contention as obs_contention
from blaze_tpu.obs import meshprof
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.testing import chaos
from blaze_tpu.types import Schema

log = logging.getLogger("blaze.fleet")

# COUNT partials merge by SUM; AVG never ships (fleet/exchange.MERGE_FN)
_MERGE_FN_NAME = {
    "sum": "sum", "count": "sum", "count_star": "sum",
    "min": "min", "max": "max",
}


def _parse_addr(p) -> Tuple[str, int]:
    if isinstance(p, (tuple, list)):
        return str(p[0]), int(p[1])
    host, _, port = str(p).rpartition(":")
    return (host or "127.0.0.1"), int(port)


class FleetContext:
    """The fleet a serve host sees: its peers (DCN-reachable serve
    hosts), the claim authority (the router when configured, a local
    ledger otherwise), and the wire budget for peer round trips."""

    def __init__(self, peers: Sequence, devices: Optional[int] = None,
                 router=None, tenant_config: Optional[dict] = None,
                 timeout_s: float = 60.0,
                 claim_timeout_s: float = 2.0):
        self.peers = [_parse_addr(p) for p in (peers or [])]
        self.router = _parse_addr(router) if router else None
        self.timeout_s = float(timeout_s)
        self.claim_timeout_s = float(claim_timeout_s)
        self._devices = int(devices) if devices else None
        self._tenant_config = tenant_config
        self._ledger: Optional[FleetDeviceLedger] = None
        self._ledger_lock = threading.Lock()

    def width(self) -> int:
        return 1 + len(self.peers)

    def devices_per_host(self) -> int:
        if self._devices is None:
            import jax

            self._devices = int(jax.local_device_count())
        return self._devices

    def total_devices(self) -> int:
        return self.width() * self.devices_per_host()

    @property
    def ledger(self) -> FleetDeviceLedger:
        with self._ledger_lock:
            if self._ledger is None:
                self._ledger = FleetDeviceLedger(
                    self.total_devices(), self._tenant_config
                )
            return self._ledger

    def claim(self, tenant: str,
              devices: Optional[int] = None) -> str:
        n = int(devices or self.total_devices())
        if self.router is not None:
            from blaze_tpu.service.wire import ServiceClient

            host, port = self.router
            with ServiceClient(
                host, port, timeout=self.claim_timeout_s + 10.0,
                reconnect_attempts=1,
            ) as c:
                resp, _ = c.mesh_exchange({
                    "op": "claim", "tenant": str(tenant),
                    "devices": n,
                    "timeout_s": self.claim_timeout_s,
                })
            if resp.get("error"):
                raise FleetClaimDenied(str(resp["error"]))
            return str(resp.get("token", ""))
        return self.ledger.claim(
            tenant, n, timeout_s=self.claim_timeout_s
        )

    def release(self, token: str) -> None:
        if not token:
            return
        if self.router is not None:
            from blaze_tpu.service.wire import ServiceClient

            host, port = self.router
            try:
                with ServiceClient(
                    host, port, timeout=10.0, reconnect_attempts=0
                ) as c:
                    c.mesh_exchange(
                        {"op": "release", "token": token}
                    )
            except Exception:  # noqa: BLE001 - release best-effort:
                # the router's ledger self-heals on resize/restart
                log.warning("fleet claim release failed", exc_info=True)
            return
        self.ledger.release(token)


def fleet_chaos(peer: str, round_name: str, ctx: ExecContext) -> None:
    """The `fleet.exchange` chaos seam: fires before every peer round
    trip, the DCN twin of mesh_exec.mesh_chaos."""
    if chaos.ACTIVE:
        chaos.fire(
            "fleet.exchange", peer=peer, round=round_name,
            task_id=ctx.task_id,
        )


def fleet_degrade_or_raise(op: PhysicalOp, ctx: ExecContext,
                           e: BaseException) -> None:
    """The fleet failure ladder: everything except cancellation
    degrades to the single-host fallback (see module docstring for
    why TRANSIENT does not propagate here)."""
    if getattr(op, "fallback", None) is None:
        raise e
    if not isinstance(
        e, (NotImplementedError, AssertionError, FleetClaimDenied)
    ):
        if classify(e) is ErrorClass.CANCELLED:
            raise e
    op._use_fallback = True
    op._result = None
    ctx.metrics.add("fleet.degraded", 1)
    # query-visible degradation flag: the service folds this into
    # q.degraded at terminal accounting (a degraded fleet run is
    # correct but did not measure the fleet plan)
    ctx.fleet_degraded = True
    REGISTRY.inc("blaze_fleet_degraded_total")
    if obs_trace.ACTIVE:
        obs_trace.event(
            "fleet.degraded", op=type(op).__name__,
            error=str(e)[:200],
        )
    log.warning(
        "%s degrading to single-host fallback: %s",
        type(op).__name__, e,
    )


class FleetMeshExec(PhysicalOp):
    """Grouped aggregation across the fleet; one output partition per
    host. Built only by planner/distribute.lower_plan_to_fleet, which
    owns the eligibility gates (fleet-safe agg set, bindable keys,
    cost guard) and supplies the single-host fallback."""

    def __init__(self, child: PhysicalOp,
                 kspec: Sequence[Tuple[int, str]],
                 aspec: Sequence[Tuple[str, Optional[int], str]],
                 fleet: FleetContext,
                 schema: Schema,
                 fallback: Optional[PhysicalOp] = None,
                 mesh_mode: str = "auto"):
        self.children = [child]
        self.kspec = [(int(i), str(n)) for i, n in kspec]
        self.aspec = [
            (str(fn), None if i is None else int(i), str(n))
            for fn, i, n in aspec
        ]
        self.fleet = fleet
        self.fallback = fallback
        self._use_fallback = False
        self._schema = schema
        self.mesh_mode = str(mesh_mode)
        self._result: Optional[List[List]] = None
        self._lock = obs_contention.TimedLock("fleet_mesh")

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return self.fleet.width()

    # -- stage plumbing -------------------------------------------------

    def _stage_in(self, ctx: ExecContext, H: int
                  ) -> Tuple[List[List[bytes]], int]:
        """Pull + encode the child's partitions, round-robin across
        hosts. Host h's share ships over DCN; host 0's stays local."""
        child = self.children[0]
        host_parts: List[List[bytes]] = [[] for _ in range(H)]
        nbytes = 0
        for p in range(child.partition_count):
            for cb in child.execute(p, ctx):
                seg = encode_ipc_segment(cb.to_arrow())
                if seg:
                    host_parts[p % H].append(seg)
                    nbytes += len(seg)
        return host_parts, nbytes

    def _peer_round(self, ctx: ExecContext, round_name: str,
                    payloads: dict, parts_by_host: dict) -> dict:
        """One DCN round: drive every peer concurrently, return
        {host_index: (resp, out_parts)}. The chaos seam fires on the
        coordinator thread (deterministic injection); peer errors are
        re-raised here so the degrade ladder sees the first one."""
        from blaze_tpu.service.wire import ServiceClient

        results: dict = {}
        errors: dict = {}

        def drive(h: int) -> None:
            host, port = self.fleet.peers[h - 1]
            try:
                with ServiceClient(
                    host, port, timeout=self.fleet.timeout_s,
                    reconnect_attempts=0,
                ) as c:
                    results[h] = c.mesh_exchange(
                        payloads[h], parts_by_host[h]
                    )
            except Exception as e:  # noqa: BLE001 - collected below
                errors[h] = e

        threads = []
        for h in payloads:
            fleet_chaos(
                f"{self.fleet.peers[h - 1][0]}:"
                f"{self.fleet.peers[h - 1][1]}",
                round_name, ctx,
            )
            th = threading.Thread(
                target=drive, args=(h,), daemon=True,
                name=f"blaze-fleet-dcn-{round_name}-{h}",
            )
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        for h in sorted(errors):
            raise errors[h]
        for h, (resp, _) in sorted(results.items()):
            if "error" in resp:
                raise RuntimeError(
                    f"fleet peer {h} {round_name}: {resp['error']}"
                )
        return results

    @staticmethod
    def _split_buckets(resp: dict, parts: List[bytes],
                       H: int) -> List[List[bytes]]:
        """Un-flatten a partial_group reply by its bucket_parts
        counts (empty buckets ship zero parts, never empty frames)."""
        counts = list(resp.get("bucket_parts") or [])
        counts += [0] * (H - len(counts))
        out: List[List[bytes]] = []
        pos = 0
        for b in range(H):
            n = int(counts[b])
            out.append(parts[pos:pos + n])
            pos += n
        return out

    def _run(self, ctx: ExecContext) -> List[List]:
        from blaze_tpu.fleet.exchange import run_stage
        from blaze_tpu.runtime import dispatch

        with self._lock:
            if self._result is not None:
                return self._result
            H = self.fleet.width()
            tenant = str(getattr(ctx, "tenant", None) or "default")
            token = self.fleet.claim(tenant)
            st = meshprof.stage(
                "fleet.groupby", self.fleet.total_devices(),
                lower_window=getattr(self, "_mesh_lower", None),
            )
            try:
                with st.phase("mesh_stage_in"):
                    host_parts, nbytes = self._stage_in(ctx, H)
                    st.add_bytes(nbytes)
                partial_spec = {
                    "kind": "partial_group",
                    "keys": [[i, n] for i, n in self.kspec],
                    "aggs": [[fn, i, n] for fn, i, n in self.aspec],
                    "n_buckets": H,
                    "mesh_mode": self.mesh_mode,
                }
                merge_spec = {
                    "kind": "final_merge",
                    "keys": [n for _, n in self.kspec],
                    "aggs": [
                        [_MERGE_FN_NAME[fn], n, n]
                        for fn, _, n in self.aspec
                    ],
                }
                # deterministic mid-stage window for the SIGKILL
                # failover test: hold between claim and first DCN call
                delay = float(
                    os.environ.get("BLAZE_FLEET_TEST_DELAY_S", "0")
                    or 0.0
                )
                if delay > 0:
                    time.sleep(delay)
                dispatch.record("dispatches")
                dispatch.record("fleet_dispatches")
                r1_payload = {
                    h: {"op": "run_stage", "stage": partial_spec}
                    for h in range(1, H)
                }
                r1: dict = {}
                r1_err: List[BaseException] = []

                def _round1():
                    try:
                        r1.update(self._peer_round(
                            ctx, "partial_group", r1_payload,
                            {h: host_parts[h] for h in range(1, H)},
                        ))
                    except BaseException as e:  # noqa: BLE001
                        r1_err.append(e)

                r1_thread = None
                if H > 1:
                    # round 1 overlaps the local partial stage; the
                    # join (and any peer error) lands in mesh_dcn
                    r1_thread = threading.Thread(
                        target=_round1, daemon=True,
                        name="blaze-fleet-round1",
                    )
                    r1_thread.start()
                with st.phase("mesh_launch"):
                    local_resp, local_parts = run_stage(
                        partial_spec, host_parts[0]
                    )
                with st.phase("mesh_dcn"):
                    if r1_thread is not None:
                        r1_thread.join()
                        if r1_err:
                            raise r1_err[0]
                    buckets = {
                        0: self._split_buckets(
                            local_resp, local_parts, H
                        ),
                    }
                    for h, (resp, parts) in r1.items():
                        buckets[h] = self._split_buckets(
                            resp, parts, H
                        )
                    dcn_bytes = sum(
                        len(p)
                        for h in range(1, H)
                        for p in host_parts[h]
                    ) + sum(
                        len(p)
                        for h, (_, parts) in r1.items()
                        for p in parts
                    )
                    # bucket d's partials from every host -> host d
                    dest_parts = {
                        d: [
                            p
                            for h in range(H)
                            for p in buckets[h][d]
                        ]
                        for d in range(H)
                    }
                    r2_payload = {
                        h: {"op": "run_stage", "stage": merge_spec}
                        for h in range(1, H)
                    }
                    dcn_bytes += sum(
                        len(p)
                        for h in range(1, H)
                        for p in dest_parts[h]
                    )
                    merged: List[Optional[Tuple[dict, list]]] = (
                        [None] * H
                    )

                    def _local_merge():
                        merged[0] = run_stage(
                            merge_spec, dest_parts[0]
                        )

                    lm = threading.Thread(
                        target=_local_merge, daemon=True,
                        name="blaze-fleet-merge0",
                    )
                    lm.start()
                    if H > 1:
                        r2 = self._peer_round(
                            ctx, "final_merge", r2_payload,
                            {h: dest_parts[h]
                             for h in range(1, H)},
                        )
                        for h, res in r2.items():
                            merged[h] = res
                    lm.join()
                with st.phase("mesh_gather"):
                    result: List[List] = []
                    for h in range(H):
                        resp, parts = merged[h]
                        result.append([
                            rb
                            for p in parts
                            for rb in decode_ipc_parts(p)
                            if rb.num_rows
                        ])
                st.finish()
                ctx.metrics.add("fleet.exchange.dcn_bytes",
                                dcn_bytes)
                ctx.metrics.add("fleet.hosts", H)
                REGISTRY.inc("blaze_fleet_stages_total")
                REGISTRY.inc("blaze_fleet_dcn_bytes_total",
                             n=dcn_bytes)
                self._result = result
                return result
            finally:
                self.fleet.release(token)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if self.fallback is not None and not self._use_fallback:
            try:
                self._run(ctx)
            except Exception as e:  # noqa: BLE001 - fleet ladder
                fleet_degrade_or_raise(self, ctx, e)
        if self._use_fallback:
            if partition < self.fallback.partition_count:
                yield from self.fallback.execute(partition, ctx)
            return
        for rb in self._run(ctx)[partition]:
            yield ColumnBatch.from_arrow(rb)
