"""Fleet DCN exchange plane: the serve-side MESH_EXCHANGE handlers.

The wire verb (service/wire.VERB_MESH_EXCHANGE) moves stage
boundaries between fleet hosts as the SAME framed Arrow-IPC segments
the shuffle tier and streamed FETCH already speak (io/ipc.py) - one
control JSON plus u64-framed encoded parts each way. This module is
the request side of that verb on a serve host:

  * ``{"op": "ping"}``                liveness + advertised devices
  * ``{"op": "run_stage", "stage"}``  run one fleet stage over the
    shipped partitions and answer with the stage's output segments

Two stage kinds mirror parallel/exchange.py's repartition-by-key
semantics, lifted to hosts:

  * ``partial_group`` - locally aggregate the shipped partitions (the
    plan is rebuilt as the standard PARTIAL -> hash-exchange -> FINAL
    sandwich and mesh-lowered, so each host's stage IS the ICI tier
    with the file-shuffle fallback intact), then hash-partition the
    grouped rows into ``n_buckets`` host buckets. Empty buckets
    encode to zero parts, so the reply JSON carries ``bucket_parts``
    (parts-per-bucket counts) to keep bucket boundaries unambiguous.
  * ``final_merge`` - merge partial groups for the buckets this host
    owns (COUNT partials merge by SUM, the rest by their own fn) in
    one single-partition COMPLETE aggregate.

Bucket routing uses `bucket_hash` - a plain deterministic numpy hash.
Only determinism matters: the coordinator's local stage and every
peer run this same code, so a group's rows always meet on one host.
"""

from __future__ import annotations

import tempfile
from typing import List, Sequence, Tuple

import numpy as np
import pyarrow as pa

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr, AggFn
from blaze_tpu.io.ipc import decode_ipc_parts, encode_ipc_segment
from blaze_tpu.ops import AggMode, HashAggregateExec, MemoryScanExec

# partial-state merge: how a finalized partial aggregate combines with
# its siblings from other hosts. AVG is deliberately absent - a naive
# merge of finalized averages is WRONG (it loses the weights), so the
# fleet planner never ships AVG (it stays on the single-host mesh).
MERGE_FN = {
    AggFn.SUM: AggFn.SUM,
    AggFn.COUNT: AggFn.SUM,
    AggFn.COUNT_STAR: AggFn.SUM,
    AggFn.MIN: AggFn.MIN,
    AggFn.MAX: AggFn.MAX,
}


def bucket_hash(columns: Sequence[np.ndarray]) -> np.ndarray:
    """Deterministic per-row u64 hash over fixed-width key columns
    (FNV-1a style with a finalizer mix). Both exchange ends run this
    exact code - the only contract is determinism."""
    n = len(columns[0])
    h = np.full(n, 14695981039346656037, dtype=np.uint64)
    for col in columns:
        v = np.asarray(col)
        if v.dtype.kind == "f":
            b = v.astype(np.float64).view(np.uint64)
        elif v.dtype.kind == "b":
            b = v.astype(np.uint64)
        else:
            b = v.astype(np.int64).view(np.uint64)
        h = (h ^ b) * np.uint64(1099511628211)
        h = h ^ (h >> np.uint64(33))
    return h


def _decode_batches(parts: Sequence[bytes]) -> List[pa.RecordBatch]:
    out: List[pa.RecordBatch] = []
    for p in parts:
        for rb in decode_ipc_parts(p):
            if rb.num_rows:
                out.append(rb)
    return out


def _encode_table(table: pa.Table) -> List[bytes]:
    segs = []
    for rb in table.combine_chunks().to_batches():
        seg = encode_ipc_segment(rb)
        if seg:
            segs.append(seg)
    return segs


def _key_arrays(table: pa.Table, names: Sequence[str]
                ) -> List[np.ndarray]:
    return [
        np.asarray(
            table.column(n).combine_chunks()
            .to_numpy(zero_copy_only=False)
        )
        for n in names
    ]


def _run_partial_group(spec: dict, parts: Sequence[bytes]
                       ) -> Tuple[dict, List[bytes]]:
    from blaze_tpu.planner.distribute import (
        insert_exchanges,
        lower_plan_to_mesh,
    )
    from blaze_tpu.runtime.executor import run_plan

    n_buckets = max(1, int(spec.get("n_buckets", 1)))
    batches = _decode_batches(parts)
    if not batches:
        return {"ok": True, "rows": 0,
                "bucket_parts": [0] * n_buckets}, []
    cbs = [ColumnBatch.from_arrow(rb) for rb in batches]
    # one partition per shipped batch: partition grouping carries no
    # meaning for a partial aggregation, and per-batch partitions are
    # what the mesh stages over devices
    scan = MemoryScanExec([[cb] for cb in cbs], cbs[0].schema)
    keys = [
        (ir.Col(scan.schema.fields[int(i)].name), str(n))
        for i, n in spec["keys"]
    ]
    aggs = []
    for fn, i, n in spec["aggs"]:
        child = (
            ir.Col(scan.schema.fields[int(i)].name)
            if i is not None else None
        )
        aggs.append((AggExpr(AggFn(fn), child), str(n)))
    plan = HashAggregateExec(
        scan, keys=keys, aggs=aggs, mode=AggMode.COMPLETE
    )
    plan = insert_exchanges(
        plan, min(8, max(2, len(cbs))),
        shuffle_dir=tempfile.mkdtemp(prefix="blaze-fleet-"),
    )
    plan = lower_plan_to_mesh(
        plan, mode=str(spec.get("mesh_mode") or "auto")
    )
    table = run_plan(plan)
    if table.num_rows == 0:
        return {"ok": True, "rows": 0,
                "bucket_parts": [0] * n_buckets}, []
    key_names = [str(n) for _, n in spec["keys"]]
    bucket = bucket_hash(_key_arrays(table, key_names)) \
        % np.uint64(n_buckets)
    counts: List[int] = []
    out_parts: List[bytes] = []
    for b in range(n_buckets):
        mask = bucket == np.uint64(b)
        if not mask.any():
            counts.append(0)
            continue
        segs = _encode_table(table.filter(pa.array(mask)))
        counts.append(len(segs))
        out_parts.extend(segs)
    return {"ok": True, "rows": int(table.num_rows),
            "bucket_parts": counts}, out_parts


def _run_final_merge(spec: dict, parts: Sequence[bytes]
                     ) -> Tuple[dict, List[bytes]]:
    from blaze_tpu.runtime.executor import run_plan

    batches = _decode_batches(parts)
    if not batches:
        return {"ok": True, "rows": 0, "bucket_parts": [0]}, []
    cbs = [ColumnBatch.from_arrow(rb) for rb in batches]
    # ONE partition: the merge must be global over every host's
    # partials for the buckets this host owns (grouped rows are small
    # - host-side COMPLETE is the right tier here)
    scan = MemoryScanExec([cbs], cbs[0].schema)
    keys = [(ir.Col(str(n)), str(n)) for n in spec["keys"]]
    aggs = []
    for fn, in_name, out_name in spec["aggs"]:
        aggs.append((
            AggExpr(AggFn(fn), ir.Col(str(in_name))),
            str(out_name),
        ))
    plan = HashAggregateExec(
        scan, keys=keys, aggs=aggs, mode=AggMode.COMPLETE
    )
    table = run_plan(plan)
    segs = _encode_table(table)
    return {"ok": True, "rows": int(table.num_rows),
            "bucket_parts": [len(segs)]}, segs


def run_stage(spec: dict, parts: Sequence[bytes]
              ) -> Tuple[dict, List[bytes]]:
    kind = spec.get("kind")
    if kind == "partial_group":
        return _run_partial_group(spec, parts)
    if kind == "final_merge":
        return _run_final_merge(spec, parts)
    return {"error": f"mesh_exchange: unknown stage kind {kind!r}"}, []


def handle_mesh_exchange(service, payload: dict,
                         parts: Sequence[bytes]
                         ) -> Tuple[dict, List[bytes]]:
    """Serve-tier MESH_EXCHANGE dispatch (ServiceVerbBackend). Claim /
    release ops belong to the router tier (router/proxy); a serve host
    answers them with an in-band error the same way a serve host
    answers MEMBER."""
    op = str(payload.get("op", ""))
    if op == "ping":
        import jax

        return {"ok": True, "devices": jax.local_device_count()}, []
    if op == "run_stage":
        return run_stage(dict(payload.get("stage") or {}), parts)
    return {"error": f"mesh_exchange: unknown op {op!r}"}, []
