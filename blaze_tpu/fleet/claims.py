"""Mesh-aware fleet admission: a device ledger for cross-host claims.

A fleet-mesh query occupies devices on EVERY participating host for the
length of a stage, so admission cannot stay per-replica: two coordinators
each seeing "my local devices are free" would oversubscribe the shared
peers. The ledger is the router-coordinated truth (one instance rides
the router's membership state, claims arrive over MESH_EXCHANGE
{"op": "claim"}); a serve host with no router configured runs a local
ledger over its own devices so the single-host path needs no wire hop.

Composes with the tenancy tier the same way queue admission does
(service/admission.TenantBudgets): the `max_fleet_devices` cap key - a
per-tenant ceiling on concurrently claimed fleet devices - merges
through the same {"tenant": {...}, "*": {...}} config. A tenant-budget
denial is REJECTED_TENANT_BUDGET-shaped and a capacity denial is
DRAINING-shaped, so the existing client retry/spill contracts (bounded
backoff, zero router breaker strikes) apply unchanged.

Denial is never failure: the fleet executor degrades a denied claim to
the single-host mesh tier - admission controls WHERE work runs, not
whether it completes.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, Optional, Tuple


class FleetClaimDenied(RuntimeError):
    """Claim refusal; str(exc) carries the wire-shaped error prefix
    (REJECTED_TENANT_BUDGET: / DRAINING:) so callers can forward it
    in-band unchanged."""


class FleetDeviceLedger:
    """Counting ledger over a fleet's device pool.

    claim() blocks up to `timeout_s` for capacity (a released claim
    wakes waiters via the condition), but a tenant-budget violation
    rejects immediately - waiting cannot fix a per-tenant ceiling the
    tenant itself is holding."""

    def __init__(self, total_devices: int,
                 tenant_config: Optional[dict] = None):
        from blaze_tpu.service.admission import TenantBudgets

        self.total = max(0, int(total_devices))
        self.budgets = TenantBudgets(tenant_config)
        self._cond = threading.Condition()
        self._seq = itertools.count(1)
        # token -> (tenant, devices)
        self._claims: Dict[str, Tuple[str, int]] = {}
        self._used = 0
        self._by_tenant: Dict[str, int] = {}
        self.counters = {
            "claims": 0,
            "released": 0,
            "denied_budget": 0,
            "denied_capacity": 0,
        }

    def _tenant_cap(self, tenant: str) -> Optional[int]:
        v = self.budgets.for_tenant(tenant).get("max_fleet_devices")
        return int(v) if v is not None else None

    def resize(self, total_devices: int) -> None:
        """Membership changes move the pool size (join adds devices,
        drain/death removes them); outstanding claims keep their
        grants - the pool can run transiently oversubscribed until
        they release."""
        with self._cond:
            self.total = max(0, int(total_devices))
            self._cond.notify_all()

    def claim(self, tenant: str, devices: int,
              timeout_s: float = 0.0) -> str:
        tenant = str(tenant or "default")
        n = max(1, int(devices))
        from blaze_tpu.obs.metrics import REGISTRY

        cap = self._tenant_cap(tenant)
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        with self._cond:
            if cap is not None \
                    and self._by_tenant.get(tenant, 0) + n > cap:
                self.counters["denied_budget"] += 1
                REGISTRY.inc("blaze_fleet_claims_denied_total",
                             reason="tenant_budget")
                raise FleetClaimDenied(
                    "REJECTED_TENANT_BUDGET: tenant "
                    f"{tenant!r} fleet-device cap {cap} "
                    f"(holding {self._by_tenant.get(tenant, 0)}, "
                    f"asked {n})"
                )
            while self._used + n > self.total:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or n > self.total:
                    self.counters["denied_capacity"] += 1
                    REGISTRY.inc("blaze_fleet_claims_denied_total",
                                 reason="capacity")
                    raise FleetClaimDenied(
                        "DRAINING: fleet devices exhausted "
                        f"({self._used}/{self.total} claimed, "
                        f"asked {n})"
                    )
                self._cond.wait(timeout=remaining)
            token = f"claim-{next(self._seq)}"
            self._claims[token] = (tenant, n)
            self._used += n
            self._by_tenant[tenant] = (
                self._by_tenant.get(tenant, 0) + n
            )
            self.counters["claims"] += 1
            REGISTRY.inc("blaze_fleet_claims_total")
            return token

    def release(self, token: str) -> bool:
        with self._cond:
            entry = self._claims.pop(str(token), None)
            if entry is None:
                return False
            tenant, n = entry
            self._used -= n
            left = self._by_tenant.get(tenant, 0) - n
            if left > 0:
                self._by_tenant[tenant] = left
            else:
                self._by_tenant.pop(tenant, None)
            self.counters["released"] += 1
            self._cond.notify_all()
            return True

    def stats(self) -> dict:
        with self._cond:
            return {
                "total_devices": self.total,
                "claimed_devices": self._used,
                "outstanding": len(self._claims),
                "by_tenant": dict(self._by_tenant),
                **self.counters,
            }
