"""Elastic fleet membership: the replica side of JOIN/LEAVE.

The router's registry (router/registry.py) owns the authoritative
fleet view; this module is what a `python -m blaze_tpu serve` replica
runs to participate in it:

  * JOIN - announced over the MEMBER wire verb (service/wire.py) as
    soon as the replica's listener is up, and RE-announced every
    `interval_s` from a background thread. Re-announcement is the
    whole re-registration story: JOIN is idempotent at the router, so
    a restarted router (empty registry) re-learns the fleet within one
    announce interval with no replica-side state machine. A router
    that is down or unreachable costs one failed connect per tick -
    the loop IS the retry.
  * LEAVE - sent once by the drain path (SIGTERM -> QueryService.drain
    -> LEAVE -> exit) on a dedicated short-timeout connection, so a
    cleanly departing replica is removed from placement immediately
    instead of aging into a heartbeat death. Open STREAMS are live
    work to the drain: QueryService.drain counts a query with an
    attached fetcher as in flight and holds the process up to the
    grace budget while the consumer finishes pulling parts (bounded -
    a stalled consumer is aborted by the stream stall budget, never by
    the drain). A stream the grace window cuts off is not lost: the
    router's routing journal + mid-stream failover re-place the query
    and resume from the last delivered part on a surviving replica
    (docs/ROUTER.md, "streaming relay").

The router-side counterpart (Router.membership) fires the
`router.membership` chaos seam on every frame, so dropped JOINs and
LEAVE races are exercised by the chaos suite like every other failure
path (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Tuple

from blaze_tpu.router.registry import parse_replica

log = logging.getLogger("blaze_tpu.router")


class MembershipAnnouncer:
    """Background JOIN announcer + one-shot LEAVE for a serve replica.

    `advertise` is the address OTHER processes can reach this replica
    at (defaults to the listener's bound address - override it when
    the bind address is 0.0.0.0 or NAT-ed)."""

    def __init__(
        self,
        router_spec,
        advertise,
        interval_s: float = 2.0,
        timeout_s: float = 5.0,
        devices: Optional[int] = None,
    ):
        self.router_host, self.router_port = parse_replica(router_spec)
        self.host, self.port = parse_replica(advertise)
        self.replica_id = f"{self.host}:{self.port}"
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        # advertised accelerator count: sizes this replica's share of
        # the router's fleet-mesh device ledger (None = advertise 1)
        self.devices = max(1, int(devices)) if devices else 1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._left = False
        # serializes MEMBER round trips: leave() must not overtake an
        # in-flight JOIN (a slow router could otherwise process the
        # LEAVE first, then the stalled JOIN would resurrect a
        # membership record for a process about to exit)
        self._member_lock = threading.Lock()
        self.joins_acked = 0   # successful JOIN round trips
        self.join_failures = 0

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "MembershipAnnouncer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._announce_loop, daemon=True,
                name=f"blaze-member-announce-{self.replica_id}",
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- protocol --------------------------------------------------------
    def _member(self, payload: dict) -> dict:
        """One MEMBER round trip on a fresh short-timeout connection.
        Never reuses a socket across ticks: the announcer must observe
        a restarted router as a clean reconnect, not a half-dead
        session."""
        from blaze_tpu.service.wire import ServiceClient

        with self._member_lock:
            with ServiceClient(
                self.router_host, self.router_port,
                timeout=self.timeout_s, reconnect_attempts=0,
            ) as c:
                return c.member(payload)

    def announce_now(self) -> bool:
        """One synchronous JOIN (tests and the startup path). True on
        an acked JOIN."""
        try:
            resp = self._member({
                "op": "join", "host": self.host, "port": self.port,
                "devices": self.devices,
            })
        except Exception as e:  # noqa: BLE001 - the loop is the retry
            self.join_failures += 1
            log.debug("JOIN %s -> %s:%d failed: %r", self.replica_id,
                      self.router_host, self.router_port, e)
            return False
        if resp.get("error"):
            self.join_failures += 1
            log.warning("JOIN %s rejected: %s", self.replica_id,
                        resp["error"])
            return False
        self.joins_acked += 1
        return True

    def leave(self, reason: str = "drained") -> bool:
        """One best-effort LEAVE. Further JOIN announcements stop
        first, and the MEMBER round-trip lock below means any
        already-in-flight JOIN completes (ack received) before the
        LEAVE is even SENT - the router processes them in that order,
        so a leave->announce race cannot resurrect membership."""
        self._left = True
        try:
            resp = self._member({
                "op": "leave", "host": self.host, "port": self.port,
                "reason": reason,
            })
        except Exception as e:  # noqa: BLE001 - the heartbeat death
            # path covers an unreachable router; leaving is advisory
            log.warning("LEAVE %s failed (%r); router will detect "
                        "departure by heartbeat", self.replica_id, e)
            return False
        return not resp.get("error")

    def _announce_loop(self) -> None:
        while not self._stop.is_set():
            if not self._left:
                self.announce_now()
            if self._stop.wait(self.interval_s):
                return


def parse_advertise(advertise: Optional[str],
                    bound_address: Tuple[str, int]) -> str:
    """The address a replica announces: an explicit --advertise wins;
    otherwise the listener's actual bound (host, port)."""
    if advertise:
        return advertise
    return "%s:%d" % bound_address
