"""Placement policy: which replica gets the next query.

Decision ladder (first rung that applies wins; every decision is
counted so STATS explains the mix):

  1. AFFINITY - a stable-fingerprint plan the router has seen before
     goes back to the replica that last completed it: that replica's
     ResultCache most plausibly holds the materialized result, and a
     cache hit costs zero kernel dispatches (the serving tier's
     acceptance pin). The router learns fingerprints from submit
     responses (Query.status carries `fingerprint` for stable plans),
     so no plan decoding happens at the routing tier - the affinity
     key for a not-yet-learned blob is its raw-byte digest.
  2. HEADROOM-FITS-ESTIMATED-COST - among replicas with a fresh STATS
     snapshot (bounded staleness), keep those whose reported admission
     headroom fits the query's estimated device bytes, then pick the
     one with the smallest estimated queue-drain: load (queued +
     running + router-tracked in-flight) weighted by the replica's
     runtime-history p50 for this fingerprint when it has one (a
     replica that historically runs this plan fast drains sooner than
     raw queue depth suggests).
  3. LEAST-LOADED fallback - when every snapshot is stale (a poll gap,
     startup), place by the router's own in-flight counts: still
     load-aware, never blocked on a poll.

Ties on rungs 2 and 3 break by RENDEZVOUS HASH of (affinity key,
replica), not by a fixed replica order: under equal load, DISTINCT
plans spread uniformly across the fleet instead of piling onto the
lexicographically-first replica, while repeats of the SAME plan keep
landing on one replica - so concurrent first submissions of a plan
converge on a single cache/coalescing point even before the affinity
map has learned it from a response.

Quarantined and heartbeat-dead replicas are invisible to every rung.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

from blaze_tpu.router.registry import Replica, ReplicaRegistry
from blaze_tpu.zerocopy.plan_cache import plan_digest


def rendezvous_rank(key: str, replica_id: str) -> int:
    """Highest-random-weight rank for tie-breaking: deterministic per
    (key, replica) pair, uniform across replicas per key."""
    h = hashlib.blake2b(digest_size=8)
    h.update(key.encode("utf-8"))
    h.update(b"|")
    h.update(replica_id.encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


def affinity_key(task_bytes: bytes, is_ref: bool) -> str:
    """Routing key for a raw SUBMIT blob: identical submissions digest
    identically, so repeats route together even before the true plan
    fingerprint is learned from the first response. One digest, two
    caches: the same key addresses the service tier's decoded-plan
    cache (zerocopy/plan_cache.py), so the router forwards it in the
    SUBMIT meta and a routed repeat skips the replica's protobuf
    decode."""
    return plan_digest(task_bytes, is_ref)


class AffinityMap:
    """Bounded LRU: affinity key -> (replica_id, learned fingerprint).

    Two joinable identities per entry: the blob digest (known before
    the first submit) and the content-addressed plan fingerprint
    (learned from the first submit's response, also keyed here so two
    byte-different encodings of the same plan converge)."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._map: "collections.OrderedDict[str, Tuple[str, Optional[str]]]" = (
            collections.OrderedDict()
        )

    def lookup(self, key: str) -> Tuple[Optional[str], Optional[str]]:
        """-> (replica_id, fingerprint) or (None, None)."""
        with self._lock:
            v = self._map.get(key)
            if v is None:
                return None, None
            self._map.move_to_end(key)
            return v

    def record(self, key: str, replica_id: str,
               fingerprint: Optional[str] = None) -> None:
        with self._lock:
            for k in (key, fingerprint):
                if not k:
                    continue
                self._map[k] = (replica_id, fingerprint)
                self._map.move_to_end(k)
            while len(self._map) > self.max_entries:
                self._map.popitem(last=False)

    def evict_replica(self, replica_id: str) -> int:
        """Eager departure eviction (LEAVE / heartbeat death): drop
        every entry pointing at the departed replica NOW, instead of
        letting each one decay into a failed placement + failover.
        Entries for other replicas are untouched (a flapping replica
        must not thrash the whole fleet's affinity). Returns the
        eviction count."""
        with self._lock:
            dead = [
                k for k, (rid, _fp) in self._map.items()
                if rid == replica_id
            ]
            for k in dead:
                del self._map[k]
        return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


class PlacementDecision:
    __slots__ = ("replica", "reason")

    def __init__(self, replica: Replica, reason: str):
        self.replica = replica
        self.reason = reason


def choose_replica(
    registry: ReplicaRegistry,
    affinity: AffinityMap,
    key: str,
    *,
    estimated_bytes: Optional[int] = None,
    fingerprint: Optional[str] = None,
    stats_stale_s: float = 10.0,
    exclude: Optional[set] = None,
    use_affinity: bool = True,
) -> Optional[PlacementDecision]:
    """Pick a routable replica for one query, or None when the fleet
    has no routable member. `exclude` drops replicas the caller
    already failed against in this placement attempt."""
    exclude = exclude or set()
    candidates = [
        r for r in registry.routable()
        if r.replica_id not in exclude
    ]
    if not candidates:
        return None

    # rung 1: fingerprint affinity. The blob digest is tried first;
    # a caller-known fingerprint (failover/resubmit re-placement, or
    # a byte-different encoding of a learned plan) joins through the
    # fingerprint-keyed entries the AffinityMap also records.
    if use_affinity:
        target, learned_fp = affinity.lookup(key)
        if fingerprint is None:
            fingerprint = learned_fp
        if target is None and fingerprint:
            target, _ = affinity.lookup(fingerprint)
        if target is not None:
            for r in candidates:
                if r.replica_id == target:
                    return PlacementDecision(r, "affinity")

    # rung 2: fresh-snapshot headroom + estimated queue-drain
    fresh = [
        r for r in candidates if r.stats_age_s() <= stats_stale_s
    ]
    if fresh:
        est = int(estimated_bytes or 0)
        fits = [
            r for r in fresh
            if (r.effective_headroom() is None
                or est <= r.effective_headroom()
                or r.load() == 0)  # an idle device admits anything
        ] or fresh  # nobody fits: queue on the least-drained anyway

        def drain_estimate(r: Replica) -> float:
            p50 = (
                r.fingerprint_p50(fingerprint)
                if fingerprint else None
            )
            # per-query cost unknown -> unit cost; known -> weight the
            # queue by how long THIS plan historically takes there
            return r.load() * (p50 if p50 is not None else 1.0) \
                + (p50 or 0.0)

        best = min(
            fits,
            key=lambda r: (drain_estimate(r),
                           -(r.effective_headroom() or 0),
                           -rendezvous_rank(key, r.replica_id)),
        )
        return PlacementDecision(best, "headroom")

    # rung 3: bounded-staleness fallback - router-local load only
    best = min(
        candidates,
        key=lambda r: (r.in_flight,
                       -rendezvous_rank(key, r.replica_id)),
    )
    return PlacementDecision(best, "least_loaded")


def random_replica(
    registry: ReplicaRegistry,
    seq: int,
    exclude: Optional[set] = None,
) -> Optional[PlacementDecision]:
    """Round-robin-ish baseline placement (bench `random` mode): the
    counter-driven pick is deterministic per submission sequence, which
    keeps the bench comparison reproducible."""
    exclude = exclude or set()
    candidates = sorted(
        (r for r in registry.routable()
         if r.replica_id not in exclude),
        key=lambda r: r.replica_id,
    )
    if not candidates:
        return None
    return PlacementDecision(
        candidates[seq % len(candidates)], "random"
    )
