"""Replicated hot results: a replica death must not cold-start the
working set.

Affinity placement (router/placement.py) concentrates repeats of a
plan on ONE replica so its ResultCache answers them with zero kernel
dispatches - which also concentrates the blast radius: kill that
replica and every repeat of its hot plans re-executes cold elsewhere.
This module closes the gap by DOUBLE-PLACING the hottest fingerprints:

  rank     the per-fingerprint sample counts + p50s the registry
           already polls off every replica's STATS (`runtime_history.
           top`, obs/history.py) are summed fleet-wide; the top-K by
           (samples x p50) - the re-execution cost a death would
           charge - are "hot".
  warm     for each hot fingerprint whose payload the router has seen
           (it keeps the raw SUBMIT blob per routed query), submit the
           SAME task bytes to a SECOND replica (use_cache=True,
           detach=True, straight down the pooled verb client - never
           through the routing table) and confirm it reached DONE:
           the secondary's ResultCache now holds the same
           (fingerprint, partition) entries.
  promote  on the home replica's departure (LEAVE or heartbeat death,
           after the eager AffinityMap eviction) the confirmed
           secondary is recorded as the NEW affinity home, so the next
           repeat is a warm cache hit on the survivor - 0 dispatches -
           instead of a cold re-execution.

Everything is bounded: at most `max_entries` tracked payloads (LRU),
`top_k` fingerprints replicated, one replication in flight at a time
(the tick runs on the router's background thread). Replication is an
OPTIMIZATION layered on the existing failover story - losing both
copies still just re-executes; correctness never depends on it.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.router.placement import choose_replica

log = logging.getLogger("blaze_tpu.router")


class _HotEntry:
    """One stable-fingerprint plan the router can re-place: the raw
    submit payload plus where its result lives."""

    __slots__ = ("key", "task_bytes", "is_ref", "manifest_bytes",
                 "home", "secondary")

    def __init__(self, key: str, task_bytes: bytes, is_ref: bool,
                 manifest_bytes: Optional[bytes], home: str):
        self.key = key
        self.task_bytes = task_bytes
        self.is_ref = is_ref
        self.manifest_bytes = manifest_bytes
        self.home = home
        self.secondary: Optional[str] = None  # CONFIRMED copy holder


class HotReplicator:
    """Top-K hot-fingerprint double-placement for a Router."""

    def __init__(self, router, top_k: int = 4, max_entries: int = 128,
                 min_samples: int = 2, confirm_timeout_s: float = 30.0):
        self.router = router
        self.top_k = int(top_k)
        self.max_entries = int(max_entries)
        self.min_samples = int(min_samples)
        self.confirm_timeout_s = float(confirm_timeout_s)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, _HotEntry]" = (
            collections.OrderedDict()
        )
        self.counters = {
            "replicated": 0,    # confirmed secondary placements
            "promoted": 0,      # secondary -> affinity home on death
            "failures": 0,      # replication submits that went wrong
        }

    # -- payload capture -------------------------------------------------
    def note_submit(self, key: str, fingerprint: Optional[str],
                    task_bytes: bytes, is_ref: bool,
                    manifest_bytes: Optional[bytes],
                    replica_id: str) -> None:
        """Called by the router after every successful placement of a
        stable-fingerprint plan: remember the payload + home so a hot
        fingerprint can be re-placed without any client involvement."""
        if not fingerprint:
            return
        with self._lock:
            ent = self._entries.get(fingerprint)
            if ent is None:
                ent = _HotEntry(key, task_bytes, is_ref,
                                manifest_bytes, replica_id)
                self._entries[fingerprint] = ent
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
            else:
                ent.key = key
                if ent.home != replica_id:
                    # the fleet moved this plan (spill, failover): if
                    # it landed on the confirmed secondary, the OLD
                    # home becomes the surviving copy - keep it
                    old_home = ent.home
                    ent.home = replica_id
                    ent.secondary = (
                        old_home if replica_id == ent.secondary
                        else None
                    )
            self._entries.move_to_end(fingerprint)

    # -- ranking ---------------------------------------------------------
    def rank_hot(self) -> List[str]:
        """Fleet-wide hotness from the per-replica STATS snapshots the
        registry already polls: sum each fingerprint's lifetime sample
        count per replica, weight by its p50 (frequency x unit cost =
        what a cold re-execution of the working set would charge)."""
        scores: Dict[str, float] = {}
        samples: Dict[str, int] = {}
        for r in list(self.router.registry.replicas.values()):
            if not r.alive or r.stats is None:
                continue
            top = (r.stats.get("runtime_history") or {}).get("top", ())
            for e in top:
                fp = e.get("fp")
                if not fp:
                    continue
                n = int(e.get("samples", e.get("n", 0)) or 0)
                p50 = float(e.get("p50", 0.0) or 0.0)
                samples[fp] = samples.get(fp, 0) + n
                scores[fp] = scores.get(fp, 0.0) \
                    + n * max(p50, 1e-6)
        hot = [
            fp for fp in sorted(scores, key=lambda f: -scores[f])
            if samples.get(fp, 0) >= self.min_samples
        ]
        return hot[:max(0, self.top_k)]

    # -- replication -----------------------------------------------------
    def tick(self) -> int:
        """One replication pass: give every un-replicated hot
        fingerprint a confirmed second copy. Returns how many
        replications were confirmed this pass."""
        if self.top_k <= 0:
            return 0
        done = 0
        for fp in self.rank_hot():
            with self._lock:
                ent = self._entries.get(fp)
            if ent is None:
                continue  # hot, but the payload predates this router
            registry = self.router.registry
            home = registry.get(ent.home)
            if home is None or not home.alive:
                continue  # departure path owns promotion, not tick
            if ent.secondary:
                sec = registry.get(ent.secondary)
                if sec is not None and sec.routable():
                    continue  # already double-placed and healthy
            if self._replicate(fp, ent):
                done += 1
        return done

    def _replicate(self, fp: str, ent: _HotEntry) -> bool:
        """Place one copy of `ent` on a replica other than its home
        and confirm DONE (the secondary's cache now holds the result).
        Never touches the routing table: replication traffic has no
        client handle to track or fail over."""
        decision = choose_replica(
            self.router.registry, self.router.affinity, ent.key,
            fingerprint=fp, exclude={ent.home}, use_affinity=False,
        )
        if decision is None:
            return False  # nobody to replicate to (fleet of one)
        target = decision.replica
        meta = {"use_cache": True, "detach": True}
        try:
            resp = self.router._call(
                target,
                lambda c: c.submit_raw(
                    ent.task_bytes, meta=meta, is_ref=ent.is_ref,
                    manifest_bytes=ent.manifest_bytes,
                ),
            )
            qid = resp.get("query_id")
            if qid is None or resp.get("state") in (
                "REJECTED_OVERLOADED", "FAILED",
            ):
                return False  # busy/draining target: next tick retries
            deadline = time.monotonic() + self.confirm_timeout_s
            while time.monotonic() < deadline:
                st = self.router._call(
                    target, lambda c: c.poll(qid)
                )
                state = st.get("state")
                if state == "DONE":
                    break
                if state in ("FAILED", "CANCELLED", "TIMED_OUT",
                             "REJECTED_OVERLOADED", None):
                    return False
                time.sleep(0.05)
            else:
                return False
        except Exception as e:  # noqa: BLE001 - replication is an
            # optimization: a failing target is the failover tier's
            # problem, never the tick loop's
            with self._lock:
                self.counters["failures"] += 1
            log.warning("hot replication of %s to %s failed: %r",
                        fp[:16], target.replica_id, e)
            return False
        with self._lock:
            # re-read: a concurrent note_submit may have moved home
            cur = self._entries.get(fp)
            if cur is None or cur.home == target.replica_id:
                return False
            cur.secondary = target.replica_id
            self.counters["replicated"] += 1
        REGISTRY.inc("blaze_router_hot_replications_total")
        log.info("hot fingerprint %s replicated %s -> %s",
                 fp[:16], ent.home, target.replica_id)
        return True

    # -- departure -------------------------------------------------------
    def on_replica_gone(self, replica_id: str) -> List[Tuple[str, str]]:
        """Departure hook (run AFTER AffinityMap.evict_replica): every
        hot fingerprint homed on the departed replica with a confirmed
        surviving secondary is re-pointed there - the next repeat hits
        the survivor's warm cache instead of cold-starting. Returns
        [(fingerprint, new_home)]."""
        promoted: List[Tuple[str, str, str]] = []
        with self._lock:
            for fp, ent in self._entries.items():
                if ent.secondary == replica_id:
                    ent.secondary = None
                if ent.home != replica_id:
                    continue
                sec = ent.secondary
                if not sec:
                    continue
                sr = self.router.registry.get(sec)
                if sr is None or not sr.alive:
                    continue
                ent.home, ent.secondary = sec, None
                promoted.append((fp, ent.key, sec))
                self.counters["promoted"] += 1
        out = []
        for fp, key, new_home in promoted:
            self.router.affinity.record(key, new_home, fp)
            REGISTRY.inc("blaze_router_hot_promotions_total")
            log.info("hot fingerprint %s promoted to survivor %s "
                     "after %s departed", fp[:16], new_home,
                     replica_id)
            out.append((fp, new_home))
        return out

    # -- exposition ------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                **self.counters,
                "tracked": len(self._entries),
                "top_k": self.top_k,
                # FULL fingerprints, same lesson as obs/history's `fp`
                # field: content fingerprints share long op-name
                # prefixes, so a truncated list is a colliding
                # constant, not an identifier
                "replicated_fps": sorted(
                    fp for fp, e in self._entries.items()
                    if e.secondary
                ),
            }
