"""Class-aware failover: the PR 3 taxonomy consumed one tier up.

The service already reacts to failure classes INSIDE a replica
(TRANSIENT retries, RESOURCE_EXHAUSTED degrades, PLAN_INVALID fails
fast - errors.retry_action). The router decides what a class means for
the FLEET:

  TRANSIENT           the replica's own retry budget is spent but the
                      fault is still plausibly environmental:
                      re-submit to the SAME replica (bounded, with
                      backoff) - its cache/affinity state is there and
                      the taxonomy says re-running can work.
  PLAN_INVALID        surface as-is, count NOTHING against the
                      replica: the plan is bad; re-routing it would
                      trip every breaker in the fleet in turn.
  CANCELLED           surface as-is (cooperative unwind is not a
                      failure).
  INTERNAL /          surface the failure AND count it against the
  RESOURCE_EXHAUSTED  replica's circuit breaker (errors.
                      FATAL_FOR_REPLICA): enough consecutive ones
                      quarantine the replica, and quarantine (like
                      heartbeat death) re-routes its other in-flight
                      queries to healthy replicas.

Transport-level failures (connection refused/reset while talking to a
replica) count as breaker strikes too - a replica that cannot be
spoken to is suspect exactly like one that fails queries.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from blaze_tpu.errors import ErrorClass, FATAL_FOR_REPLICA
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.router.registry import ReplicaRegistry


def failover_action(error_class: Optional[str]) -> str:
    """'resubmit' | 'surface' | 'breaker' for a terminal FAILED status
    observed through the router."""
    if error_class == ErrorClass.TRANSIENT.value:
        return "resubmit"
    try:
        ec = ErrorClass(error_class) if error_class else None
    except ValueError:
        ec = None
    if ec in FATAL_FOR_REPLICA or ec is None:
        # unclassified failures are INTERNAL by taxonomy convention
        return "breaker"
    return "surface"


class CircuitBreaker:
    """Per-replica consecutive fatal-class strike counter. Tripping
    quarantines the replica through the registry (cool-off +
    half-open there); any success resets the count. Counters ride the
    process metrics registry so the breaker state is scrapeable."""

    def __init__(self, registry: ReplicaRegistry,
                 threshold: int = 3):
        self.registry = registry
        self.threshold = max(1, int(threshold))
        self._strikes: Dict[str, int] = {}
        self._lock = threading.Lock()

    def note_ok(self, replica_id: str) -> None:
        with self._lock:
            self._strikes.pop(replica_id, None)

    def note_fatal(self, replica_id: str,
                   kind: str = "query") -> bool:
        """Record one fatal-class strike; True when this strike opened
        the breaker (the caller then re-routes the replica's in-flight
        queries)."""
        with self._lock:
            n = self._strikes.get(replica_id, 0) + 1
            self._strikes[replica_id] = n
            tripped = n >= self.threshold
            if tripped:
                self._strikes[replica_id] = 0  # re-arm for half-open
        REGISTRY.inc("blaze_router_breaker_strikes_total",
                     replica=replica_id, kind=kind)
        if tripped:
            REGISTRY.inc("blaze_router_breaker_open_total",
                         replica=replica_id)
            self.registry.quarantine(
                replica_id, reason="circuit-open"
            )
        return tripped

    def strikes(self, replica_id: str) -> int:
        with self._lock:
            return self._strikes.get(replica_id, 0)
