"""The router proper: proxy verbs, routed-query table, failover moves.

`RouterServer` speaks the exact service wire protocol (service/wire.py
framing), so a `ServiceClient` pointed at the router behaves as if it
were talking to a single `python -m blaze_tpu serve` instance. Each
client SUBMIT is forwarded to a placed replica (router/placement.py)
with `detach=True` - the ROUTER owns session semantics: downstream
handles must survive the router's own connection churn and re-route
across replicas, which is precisely what the detach + re-attach
machinery (PR 3) provides. Cancel-on-disconnect is re-implemented at
the router tier: a vanished client's non-detached queries are
cancelled on their replicas.

Query ids are rewritten: the client holds a router-scoped id, the
routing table maps it to (replica, replica-local id) and re-points it
on failover - so a re-routed query keeps its handle. FETCH is a raw
byte passthrough of the segmented-IPC parts (never decoded at the
router: the zero-copy path of the wire format survives the extra hop),
with part counting so a mid-stream failover resumes on the new replica
skipping what the client already received.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import itertools
import logging
import os
import queue
import random
import re
import socket
import socketserver
import threading
import time
from functools import partial
from typing import Dict, Iterator, List, Optional

from blaze_tpu.errors import ReplicaUnavailableError
from blaze_tpu.obs import contention as obs_contention
from blaze_tpu.obs import meshprof as obs_meshprof
from blaze_tpu.obs import phases as obs_phases
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.obs.metrics import REGISTRY, merge_expositions
from blaze_tpu.router.failover import CircuitBreaker, failover_action
from blaze_tpu.router.journal import RouterJournal
from blaze_tpu.router.placement import (
    AffinityMap,
    PlacementDecision,
    affinity_key,
    choose_replica,
    random_replica,
)
from blaze_tpu.router.registry import (
    Replica,
    ReplicaRegistry,
    parse_replica,
)
from blaze_tpu.router.replication import HotReplicator
from blaze_tpu.service.wire import (
    _ERR,
    _U32,
    _U64,
    VERB_FETCH,
    ServiceError,
    _is_draining_rejection,
    _is_tenant_budget_rejection,
    _send_err,
)
from blaze_tpu.testing import chaos

log = logging.getLogger("blaze_tpu.router")

_MAX_RETAINED = 1024
_HARD_RETAINED = 4 * _MAX_RETAINED  # even live queries evict past this
_SPLICE_ERR = (
    "FAILED: re-executed result diverged from parts already delivered "
    "(failover across a non-deterministic or degraded re-run); "
    "resubmit the query"
)
_rqid_counter = itertools.count()


class RoutedQuery:
    """One query routed through this router: the client-facing handle
    plus everything needed to re-route it (the original payload)."""

    __slots__ = (
        "external_id", "key", "task_bytes", "is_ref", "manifest_bytes",
        "meta", "replica_id", "internal_id", "fingerprint",
        "generation", "resubmits", "failovers", "finished",
        "cancelled", "last_state", "lock", "delivered_hashes",
        "splice_broken", "tracer", "hop_span", "grafted",
        "recovered", "reconciled",
    )

    def __init__(self, key: str, task_bytes: bytes, is_ref: bool,
                 manifest_bytes: Optional[bytes], meta: dict,
                 external_id: Optional[str] = None):
        # journal replay reconstructs handles under their ORIGINAL id
        # (the client re-attaches by query_id); fresh submissions mint
        # a new one. The pid suffix alone does NOT make restarts
        # collision-free (container pid 1, pid recycling) - journal
        # restore fast-forwards _rqid_counter past every recovered id
        self.external_id = (
            external_id
            or f"rq-{next(_rqid_counter)}-{os.getpid():x}"
        )
        self.key = key
        self.task_bytes = task_bytes
        self.is_ref = is_ref
        self.manifest_bytes = manifest_bytes
        self.meta = meta
        self.replica_id: Optional[str] = None
        self.internal_id: Optional[str] = None
        self.fingerprint: Optional[str] = None
        self.generation = 0   # bumped on every re-route
        self.resubmits = 0    # TRANSIENT same-replica re-submissions
        self.failovers = 0    # cross-replica re-routes
        self.finished = False
        # client cancel: a pending failover must not resurrect this
        self.cancelled = False
        self.last_state: Optional[str] = None
        self.lock = threading.Lock()
        # router-hop tracing (obs/trace.py): the ROUTER's own span
        # tree for this query - placement ladder outcome, each
        # submit/failover attempt, proxy streaming. hop_span is the
        # current generation's successful router_attempt span: the
        # graft point for the replica's span subtree on REPORT.
        # `grafted` guards re-grafting the same downstream execution
        # when REPORT is called twice.
        self.tracer = None
        self.hop_span = None
        self.grafted: set = set()
        # canonical part-content record for FETCH: digest of every
        # part ever delivered to a client, so a re-fetch after
        # failover can PROVE the re-executed result is part-for-part
        # identical to what the client already holds (clients resume
        # by count; a silent splice of two different executions would
        # corrupt their table)
        self.delivered_hashes: List[bytes] = []
        self.splice_broken = False
        # crash recovery (router/journal.py): `recovered` marks a
        # handle rebuilt by journal replay; `reconciled` flips once
        # the recovery pass re-adopted (or re-placed) it against the
        # live fleet - until then, client verbs report a RUNNING
        # placeholder instead of finalizing on stale state
        self.recovered = False
        self.reconciled = False


class Router:
    """Routing table + policy glue over ReplicaRegistry / AffinityMap /
    CircuitBreaker. Thread-safe; one instance fronts many connections."""

    def __init__(
        self,
        replicas,
        *,
        placement: str = "affinity",
        poll_interval_s: float = 0.5,
        heartbeat_timeout_s: float = 3.0,
        quarantine_s: float = 15.0,
        breaker_threshold: int = 3,
        max_resubmits: int = 2,
        resubmit_backoff_s: float = 0.05,
        stats_stale_s: float = 10.0,
        downstream_timeout_s: float = 120.0,
        fetch_block_s: float = 0.5,
        stream_window: int = 4,
        stream_stall_s: float = 30.0,
        stream_total_bytes: int = 256 << 20,
        enable_trace: bool = True,
        conn_pool_size: int = 4,
        replicate_hot_k: int = 4,
        replicate_interval_s: float = 2.0,
        journal_path: Optional[str] = None,
        recover_timeout_s: float = 30.0,
        tenant_rate: float = 0.0,
        tenant_burst: Optional[int] = None,
        tenant_retry_budget: int = 0,
        tenant_retry_window_s: float = 30.0,
        tenant_config: Optional[dict] = None,
        start: bool = True,
    ):
        if placement not in ("affinity", "random"):
            raise ValueError(f"unknown placement mode {placement!r}")
        self.placement_mode = placement
        self.max_resubmits = int(max_resubmits)
        self.resubmit_backoff_s = float(resubmit_backoff_s)
        self.stats_stale_s = float(stats_stale_s)
        self.downstream_timeout_s = float(downstream_timeout_s)
        self.fetch_block_s = float(fetch_block_s)
        # streaming relay flow control: at most stream_window raw
        # parts in flight between the downstream reader and the
        # client-facing writer (credit window - the relay never
        # materializes a result), and a client that accepts no bytes
        # for stream_stall_s gets its relay aborted instead of letting
        # its backpressure pin downstream buffers fleet-wide
        self.stream_window = max(1, int(stream_window))
        self.stream_stall_s = float(stream_stall_s)
        # fleet-wide relay-memory cap: total bytes parked across ALL
        # concurrent relay windows (<= 0 disables). Over-budget
        # streams wait before accounting a new part; a stream with
        # nothing parked always admits one part (progress beats the
        # bound - the StreamBuffer single-oversized-part rule)
        self.stream_total_bytes = int(stream_total_bytes)
        self.recover_timeout_s = float(recover_timeout_s)
        self.registry = ReplicaRegistry(
            replicas,
            poll_interval_s=poll_interval_s,
            heartbeat_timeout_s=heartbeat_timeout_s,
            quarantine_s=quarantine_s,
            on_dead=self._on_replica_departed_async,
            # crash recovery: a replica coming alive (first contact
            # after restart, or a revival) may hold journaled
            # placements waiting to be re-adopted - kick the
            # reconcile pass instead of waiting out its tick
            on_revive=self._on_replica_alive,
        )
        self.affinity = AffinityMap()
        # replicated hot results (router/replication.py): the top-K
        # hot fingerprints get a confirmed second copy, promoted to
        # the affinity home when the first one departs
        self.hot = HotReplicator(self, top_k=replicate_hot_k)
        self.replicate_interval_s = float(replicate_interval_s)
        self.breaker = CircuitBreaker(
            self.registry, threshold=breaker_threshold
        )
        self._queries: Dict[str, RoutedQuery] = {}
        self._order: List[str] = []
        self._lock = obs_contention.TimedLock("router_table")
        self._rr_seq = itertools.count()  # random-mode sequence
        self.counters: Dict[str, int] = {
            "submitted": 0,
            "placed_affinity": 0,
            "placed_headroom": 0,
            "placed_least_loaded": 0,
            "placed_random": 0,
            "resubmits_transient": 0,
            "failovers": 0,
            "overflow_spills": 0,
            "drain_spills": 0,
            "no_replica": 0,
            "stream_stalls": 0,
            "stream_window_waits": 0,
            "stream_total_waits": 0,
            "tenant_rate_limited": 0,
            "tenant_budget_spills": 0,
            "tenant_retry_budget_exhausted": 0,
        }
        # ---- multi-tenant fleet protection --------------------------
        # Two router-tier guards sit ABOVE the replicas' own admission
        # budgets: a token-bucket rate limit on SUBMIT (checked before
        # the query is journaled, so a flooding tenant never bloats the
        # routing table or the journal), and a windowed retry budget
        # that bounds how much failover/retry amplification one
        # tenant's failing plans can inflict on the fleet. Both default
        # OFF (rate <= 0, budget <= 0) - zero-config behavior is
        # byte-identical to a tenant-unaware router. Per-tenant
        # overrides come from tenant_config {tenant: {"rate": qps,
        # "burst": n, "retry_budget": n}, "*": defaults}.
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = (
            None if tenant_burst is None else max(1, int(tenant_burst))
        )
        self.tenant_retry_budget = int(tenant_retry_budget)
        self.tenant_retry_window_s = float(tenant_retry_window_s)
        self.tenant_config = dict(tenant_config or {})
        self._tenant_mu = threading.Lock()
        # token buckets: tenant -> [tokens, last_refill_monotonic]
        self._tenant_buckets: Dict[str, list] = {}
        # retry-budget windows: tenant -> deque of spend timestamps
        self._tenant_retries: Dict[str, collections.deque] = {}
        self._tenant_counters: Dict[str, Dict[str, int]] = {}
        # ---- fleet mesh device ledger -------------------------------
        # the claim authority for cross-host mesh stages (ISSUE 20):
        # a fleet query reserves devices ACROSS hosts before its first
        # DCN round, composing with the same tenant_config the
        # admission budgets read (`max_fleet_devices` cap key). The
        # pool size rides membership: JOINs advertise device counts,
        # joins/leaves resize. Claims arrive over MESH_EXCHANGE.
        from blaze_tpu.fleet.claims import FleetDeviceLedger

        self._fleet_ledger = FleetDeviceLedger(0, self.tenant_config)
        self._fleet_resize()  # static fleets never JOIN
        # fleet-wide relay-window memory: bytes currently parked in
        # the bounded per-stream relay queues of _raw_fetch_windowed,
        # summed across concurrent streams (the
        # blaze_router_stream_buffered_bytes gauge)
        self._stream_buffered = 0
        self._stream_buffered_mu = threading.Lock()
        # per-replica verb-client POOL (ROADMAP item 4's last enabling
        # refactor): up to conn_pool_size concurrent connections per
        # replica, so one slow RPC cannot serialize sibling verbs
        # behind a single socket. `_clients[rid]` holds IDLE clients;
        # `_client_counts[rid]` counts created (idle + checked-out)
        self._pool_size = max(1, int(conn_pool_size))
        self._clients: Dict[str, list] = {}
        self._client_counts: Dict[str, int] = {}
        # per-replica pool EPOCH, bumped when a replica LEAVEs: a
        # client checked out across the leave must not be pooled (or
        # counted) back into the next epoch - a restarted replica at
        # the same address would inherit a socket to the dead process
        self._client_epoch: Dict[str, int] = {}
        self._client_cv: Dict[str, threading.Condition] = {
            rid: threading.Condition(
                obs_contention.TimedLock("conn_pool")
            )
            for rid in self.registry.replicas
        }
        self._collector_key = f"router:{id(self):x}"
        REGISTRY.register_collector(
            self._collector_key, self._collect_metrics
        )
        # router-hop tracing: refcounted for the router's lifetime
        # (same contract as QueryService); `route --no-trace` opts out
        self._trace_enabled = bool(enable_trace)
        if self._trace_enabled:
            obs_trace.enable()
        self._closed = False
        # crash recovery (router/journal.py + docs/ROUTER.md): replay
        # the durable routing journal into the routing table, then
        # reconcile each recovered handle against the live fleet as
        # announcers re-JOIN. `route --journal PATH` opts in.
        self.journal: Optional[RouterJournal] = None
        self._recover_pending: List[str] = []
        self._recover_kick = threading.Event()
        self._recover_deadline = 0.0
        self._recover_trace = None
        self._recover_thread: Optional[threading.Thread] = None
        if journal_path:
            self.journal = RouterJournal(journal_path)
            self._restore_from_journal(self.journal.replayed)
        self._hot_stop = threading.Event()
        self._hot_thread: Optional[threading.Thread] = None
        if start:
            self.registry.start()
            if self.hot.top_k > 0:
                self._hot_thread = threading.Thread(
                    target=self._hot_loop, daemon=True,
                    name="blaze-router-hot-replicate",
                )
                self._hot_thread.start()
            if self._recover_pending:
                self._start_recovery()

    def _hot_loop(self) -> None:
        """Background hot-result replication pass (replication.py).
        Its own thread: a replication submit + DONE confirmation can
        take seconds, and neither the pollers nor client verbs may
        wait on it."""
        while not self._hot_stop.wait(self.replicate_interval_s):
            try:
                self.hot.tick()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("hot replication tick failed")

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._hot_stop.set()
        self._recover_kick.set()
        if self._hot_thread is not None:
            self._hot_thread.join(timeout=5)
            self._hot_thread = None
        if self._recover_thread is not None:
            self._recover_thread.join(timeout=5)
            self._recover_thread = None
        if self.journal is not None:
            self.journal.close()
        REGISTRY.unregister_collector(self._collector_key)
        if self._trace_enabled:
            obs_trace.disable()
        self.registry.close()
        for rid, idle in list(self._clients.items()):
            for c in idle:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 - teardown
                    pass
        self._clients.clear()
        self._client_counts.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- crash recovery (router/journal.py) ------------------------------
    def _journal_submit(self, rq: RoutedQuery) -> None:
        if self.journal is None:
            return
        try:
            self.journal.record_submit(
                rq.external_id, rq.key, rq.meta, rq.task_bytes,
                rq.is_ref, rq.manifest_bytes,
            )
        except Exception:  # noqa: BLE001 - journal loss degrades
            log.exception("journal submit record failed for %s",
                          rq.external_id)  # recovery, never serving

    def _journal_place(self, rq: RoutedQuery) -> None:
        if self.journal is None:
            return
        try:
            with rq.lock:
                rid, iid = rq.replica_id, rq.internal_id
                fp, gen = rq.fingerprint, rq.generation
            if rid and iid:
                self.journal.record_place(rq.external_id, rid, iid,
                                          fp, gen)
        except Exception:  # noqa: BLE001 - journal loss degrades
            log.exception("journal place record failed for %s",
                          rq.external_id)

    def _journal_finish(self, rq: RoutedQuery,
                        state: Optional[str]) -> None:
        if self.journal is None:
            return
        try:
            self.journal.record_finish(rq.external_id,
                                       str(state or "FINISHED"))
        except Exception:  # noqa: BLE001 - journal loss degrades
            log.exception("journal finish record failed for %s",
                          rq.external_id)

    def _restore_from_journal(self, entries) -> None:
        """Rebuild the routing table from replayed journal entries:
        each live entry becomes a RoutedQuery under its ORIGINAL
        external id (clients re-attach by query_id), queued for the
        reconcile pass. Runs in the constructor, before any verb."""
        max_ctr = -1
        for e in entries.values():
            m = re.match(r"rq-(\d+)-", e.external_id)
            if m:
                max_ctr = max(max_ctr, int(m.group(1)))
            rq = RoutedQuery(e.key, e.task_bytes, e.is_ref,
                             e.manifest_bytes, dict(e.meta),
                             external_id=e.external_id)
            rq.recovered = True
            rq.replica_id = e.replica_id
            rq.internal_id = e.internal_id
            rq.fingerprint = e.fingerprint
            rq.generation = max(1, e.generation) if e.placed else 0
            if obs_trace.ACTIVE:
                rq.tracer = obs_trace.begin_trace(
                    rq.external_id, root_name="router_query"
                )
                rq.tracer.root.tag(recovered=True, key=e.key[:16])
            with self._lock:
                self._queries[rq.external_id] = rq
                self._order.append(rq.external_id)
            self._recover_pending.append(rq.external_id)
        if max_ctr >= 0:
            # fast-forward the id counter PAST every recovered id: a
            # restarted router commonly reuses its pid (container
            # pid 1, pid recycling), and a reset counter would then
            # mint a fresh rq-{n}-{pid} that silently overwrites a
            # recovered handle in _register. Never rewinds: the new
            # count starts at max(current, recovered)+1
            global _rqid_counter
            cur = next(_rqid_counter)
            _rqid_counter = itertools.count(max(cur, max_ctr + 1))
        if self._recover_pending:
            log.info("journal replay: %d routed queries to "
                     "reconcile", len(self._recover_pending))

    def _start_recovery(self) -> None:
        self._recover_deadline = (
            time.monotonic() + self.recover_timeout_s
        )
        if obs_trace.ACTIVE and self._recover_trace is None:
            # the recovery pass gets its own span tree - one
            # `recover_query` span per reconciled handle, outcome
            # tagged - so a restart is observable like everything else
            self._recover_trace = obs_trace.begin_trace(
                f"router-recover-{os.getpid():x}",
                root_name="router_recover",
            )
            self._recover_trace.root.tag(
                pending=len(self._recover_pending)
            )
        self._recover_thread = threading.Thread(
            target=self._recover_loop, daemon=True,
            name="blaze-router-recover",
        )
        self._recover_thread.start()

    def _on_replica_alive(self, replica: Replica) -> None:
        """Registry revive callback: a replica JOINing (or coming
        back) may hold journaled placements - reconcile NOW instead
        of on the pass's next tick."""
        self._recover_kick.set()

    def _recover_loop(self) -> None:
        while not self._closed:
            try:
                outstanding = self._recover_tick()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("recovery tick failed")
                outstanding = len(self._recover_pending)
            if not outstanding:
                break
            self._recover_kick.wait(timeout=0.25)
            self._recover_kick.clear()
        if self._recover_trace is not None:
            try:
                self._recover_trace.finish(
                    outstanding=len(self._recover_pending) or None
                )
            except Exception:  # noqa: BLE001 - obs must not raise
                pass

    def _recover_tick(self) -> int:
        """One reconcile pass over the recovered handles; returns how
        many remain unresolved. Exposed for deterministic tests
        (`start=False` routers drive it manually)."""
        force = time.monotonic() >= self._recover_deadline \
            if self._recover_deadline else False
        for qid in list(self._recover_pending):
            rq = self._queries.get(qid)
            if rq is None:
                self._recover_pending.remove(qid)
                continue
            t0 = time.monotonic()
            outcome = self._reconcile_one(rq, force=force)
            if outcome is None:
                continue  # not resolvable yet (replica still absent)
            self._recover_pending.remove(qid)
            REGISTRY.inc("blaze_router_recovered_total",
                         outcome=outcome)
            log.info("recovered query %s: %s (replica %s)",
                     qid, outcome, rq.replica_id)
            if self._recover_trace is not None:
                try:
                    self._recover_trace.record_span(
                        "recover_query", t0, time.monotonic(),
                        query=qid, outcome=outcome,
                        replica=rq.replica_id,
                    )
                except Exception:  # noqa: BLE001 - obs must not raise
                    pass
        return len(self._recover_pending)

    def _reconcile_one(self, rq: RoutedQuery,
                       force: bool = False) -> Optional[str]:
        """Reconcile one recovered handle against the live fleet.
        Returns the outcome label, or None when it cannot be resolved
        yet (journaled replica not back, no routable capacity) and
        the pass should retry. Outcomes (the
        `blaze_router_recovered_total{outcome}` label values):

          adopted_running   journaled placement still executing
          adopted_done      journaled placement already DONE - the
                            result is FETCHable as if nothing happened
          adopted_terminal  journaled placement reached another
                            terminal state; it surfaces classified
          replaced          replica lost the handle (or never came
                            back): re-placed from the journaled
                            SUBMIT bytes through the failover path
          requeued          never placed before the crash: re-entered
                            placement from the journaled bytes
          stranded          unrecoverable (no routable fleet within
                            the recovery window)
        """
        with rq.lock:
            if rq.reconciled:
                return None
            if rq.cancelled or rq.finished:
                rq.reconciled = True
                return "adopted_terminal"
            placed = rq.internal_id is not None
            rid = rq.replica_id
            gen = rq.generation
        if not placed:
            # never placed before the crash: re-enter placement like
            # a fresh submit (the journal already holds its S record)
            if not self.registry.routable():
                return self._maybe_strand(rq, force)
            try:
                resp = self._place_and_submit(rq, exclude=set())
            except ReplicaUnavailableError:
                return self._maybe_strand(rq, force)
            if "query_id" not in resp:
                # in-band replica error (e.g. undecodable plan): the
                # handle ends classified instead of retrying forever
                with rq.lock:
                    rq.reconciled = True
                self._finish(rq, "FAILED")
                return "stranded"
            with rq.lock:
                rq.reconciled = True
            return "requeued"
        replica = self.registry.get(rid or "")
        if replica is None or not replica.alive:
            if not force:
                return None  # the announcer may still re-JOIN it
            return self._replace_or_strand(rq, gen, rid, force)
        if chaos.ACTIVE:
            # DROP = a reconcile POLL that never reaches the replica
            # (the pass retries); STALL = a slow replica under
            # recovery load
            try:
                chaos.fire("router.journal", op="reconcile_poll",
                           replica=rid, query=rq.external_id)
            except ConnectionError:
                return None
        try:
            st = self._call(
                replica, lambda c: c.poll(rq.internal_id)
            )
        except (ConnectionError, OSError, ServiceError):
            # mid-restart replica: retry until the window closes
            return self._replace_or_strand(rq, gen, rid, force) \
                if force else None
        if "query_id" not in st:
            # the replica answered but lost the handle (it restarted
            # too): re-run from the journaled bytes - the normal
            # failover move, cancel-superseded semantics intact.
            # NO exclusion: the replica is alive and routable, and in
            # a single-replica fleet excluding it would strand a
            # perfectly recoverable query (the lost-handle path in
            # _downstream_status re-places with exclude=set() for the
            # same reason)
            return self._replace_or_strand(rq, gen, None, True)
        state = st.get("state")
        with rq.lock:
            if rq.reconciled:
                return None
            rq.reconciled = True
            finished = rq.finished
        if not finished:
            # the handle is live again: balance the in-flight gauge
            # the way a fresh placement would
            replica.note_routed()
        if self.placement_mode == "affinity" and rq.fingerprint:
            # re-learn affinity: repeats keep landing on the replica
            # whose ResultCache holds this plan's result
            self.affinity.record(rq.key, replica.replica_id,
                                 rq.fingerprint)
        if state == "DONE":
            return "adopted_done"
        if state in ("FAILED", "CANCELLED", "TIMED_OUT",
                     "REJECTED_OVERLOADED"):
            return "adopted_terminal"
        return "adopted_running"

    def _replace_or_strand(self, rq: RoutedQuery, gen: int,
                           old_rid: Optional[str],
                           force: bool) -> Optional[str]:
        """Re-place a recovered handle away from its journaled
        replica (lost handle / replica never returned)."""
        exclude = {old_rid} if old_rid else set()
        routable = [
            r for r in self.registry.routable()
            if r.replica_id not in exclude
        ]
        if not routable:
            return self._maybe_strand(rq, force)
        if self._resubmit(rq, gen, same_replica=False,
                          exclude=exclude, counter="failovers"):
            with rq.lock:
                rq.reconciled = True
            return "replaced"
        return self._maybe_strand(rq, force)

    def _maybe_strand(self, rq: RoutedQuery,
                      force: bool) -> Optional[str]:
        if not force:
            return None
        with rq.lock:
            rq.reconciled = True
        self._finish(rq, "REJECTED_OVERLOADED")
        log.warning("recovered query %s stranded: no routable "
                    "replica within the recovery window",
                    rq.external_id)
        return "stranded"

    def _await_reconcile(self, rq: RoutedQuery,
                         poll_s: float = 0.05) -> None:
        """Block a client FETCH of a recovered handle until the
        reconcile pass resolved it (bounded by the recovery window):
        fetching against a stale placement would bounce UNKNOWN off a
        replica that restarted, when one more announcer tick away the
        journaled result is servable."""
        if not rq.recovered or rq.reconciled:
            return
        deadline = max(
            self._recover_deadline,
            time.monotonic() + 1.0,
        ) + 5.0
        while time.monotonic() < deadline:
            if rq.reconciled or rq.finished:
                return
            time.sleep(poll_s)

    # -- downstream client pool -----------------------------------------
    def _call(self, replica: Replica, fn):
        """Run one verb round trip on a client checked out of the
        per-replica connection pool (ServiceClient's reconnect-with-
        backoff heals transient drops underneath). Up to
        `conn_pool_size` verbs run concurrently against one replica;
        a caller that finds every connection busy lands one
        `blaze_router_conn_pool_waits{replica}` count and blocks until
        a sibling checks its client back in. A failing client is
        closed and dropped so the next checkout starts clean."""
        from blaze_tpu.service.wire import ServiceClient

        rid = replica.replica_id
        cv = self._client_cv.setdefault(
            rid,
            threading.Condition(obs_contention.TimedLock("conn_pool")),
        )
        c = None
        counted_wait = False
        with cv:
            while True:
                idle = self._clients.setdefault(rid, [])
                if idle:
                    c = idle.pop()
                    break
                if self._client_counts.get(rid, 0) < self._pool_size:
                    self._client_counts[rid] = (
                        self._client_counts.get(rid, 0) + 1
                    )
                    break  # connect OUTSIDE the pool lock
                if not counted_wait:
                    # one count per wait EPISODE, not per wakeup
                    counted_wait = True
                    REGISTRY.inc("blaze_router_conn_pool_waits",
                                 replica=rid)
                cv.wait(timeout=0.1)
            epoch = self._client_epoch.get(rid, 0)

        def _discard(client) -> None:
            with cv:
                if self._client_epoch.get(rid, 0) == epoch:
                    # only the epoch that counted this client may
                    # un-count it: a post-LEAVE epoch starts from 0
                    # and must not absorb a stale client's release
                    self._client_counts[rid] = max(
                        0, self._client_counts.get(rid, 1) - 1
                    )
                cv.notify()
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

        if c is None:
            try:
                c = ServiceClient(
                    replica.host, replica.port,
                    timeout=self.downstream_timeout_s,
                    reconnect_attempts=1,
                )
            except BaseException:
                _discard(None)  # release the reserved slot
                raise
        try:
            out = fn(c)
        except BaseException:
            # BaseException too (thread-delivered interrupt/exit mid-
            # verb): the slot and the client must never leak - after
            # conn_pool_size leaks every _call would wait forever
            _discard(c)
            raise
        with cv:
            if self._client_epoch.get(rid, 0) != epoch:
                # the replica LEFT while this verb was in flight: the
                # pool purge could not see the checked-out client, so
                # check-in closes it instead of handing a socket to
                # the dead process to whoever re-joins at the address
                c, stale = None, c
            else:
                self._clients.setdefault(rid, []).append(c)
                stale = None
            cv.notify()
        if stale is not None:
            try:
                stale.close()
            except Exception:  # noqa: BLE001 - teardown
                pass
        return out

    # -- bookkeeping -----------------------------------------------------
    def _register(self, rq: RoutedQuery) -> None:
        evicted = []
        with self._lock:
            self._queries[rq.external_id] = rq
            self._order.append(rq.external_id)
            while len(self._order) > _MAX_RETAINED:
                # evict the oldest FINISHED entry wherever it sits: a
                # long-lived live query at the head must not pin
                # thousands of terminal entries (each holding its full
                # task_bytes) behind it
                idx = next(
                    (i for i, qid in enumerate(self._order)
                     if (oq := self._queries.get(qid)) is None
                     or oq.finished),
                    None,
                )
                if idx is None:
                    # everything retained is LIVE: abandon the oldest
                    # only past the hard cap
                    if len(self._order) <= _HARD_RETAINED:
                        break
                    idx = 0
                old = self._order.pop(idx)
                orq = self._queries.pop(old, None)
                if orq is not None and not orq.finished:
                    evicted.append(orq)
        for orq in evicted:
            # an abandoned handle (detached, never drained) must not
            # hold its replica's in-flight slot forever
            self._finish(orq, "ABANDONED")
            # and its downstream run was submitted detach=True, so
            # with the handle gone nothing can ever stop OR fetch it -
            # cancel it like the failover path cancels superseded
            # executions, or it runs to completion holding the
            # replica's admission slot and device reservation
            r = self.registry.get(orq.replica_id or "")
            if r is not None and orq.internal_id:
                self._cancel_superseded(r, orq.internal_id)
            log.warning("evicted live routed query %s (retention "
                        "hard cap %d)", orq.external_id,
                        _HARD_RETAINED)

    def get(self, external_id: str) -> RoutedQuery:
        with self._lock:
            rq = self._queries.get(external_id)
        if rq is None:
            raise KeyError(f"unknown query {external_id}")
        return rq

    def _finish(self, rq: RoutedQuery, state: Optional[str]) -> bool:
        """Idempotent terminal bookkeeping: in-flight gauge + breaker
        reset on success. Returns True only for the caller that WON
        the finalization (test-and-set under the handle lock), so
        concurrent observers of one failure - two pollers, or a poll
        racing the FETCH error path - agree on exactly one winner and
        the same event is never double-counted downstream."""
        with rq.lock:
            rq.last_state = state
            if rq.finished:
                return False
            rq.finished = True
        # terminal = journal truncation marker: replay drops the
        # entry, compaction reclaims its bytes
        self._journal_finish(rq, state)
        r = self.registry.get(rq.replica_id or "")
        if r is not None:
            r.note_unrouted()
        if state == "DONE" and rq.replica_id:
            self.breaker.note_ok(rq.replica_id)
        if rq.tracer is not None:
            # the finalization winner closes the router root span and
            # folds this query's router overhead (placement ladder +
            # submit hops, NOT downstream execution) into the
            # per-phase rollup the regress CLI diffs
            try:
                rq.tracer.finish(
                    state=state, replica=rq.replica_id,
                    failovers=rq.failovers or None,
                    resubmits=rq.resubmits or None,
                )
                overhead = rq.tracer.phase_totals(
                    obs_phases.SPAN_PHASE
                ).get("router")
                if overhead is not None:
                    obs_phases.ROLLUP.observe(
                        "router", overhead,
                        klass=obs_phases.class_key(rq.fingerprint),
                    )
            except Exception:  # noqa: BLE001 - obs must not raise
                log.exception("router trace finish failed for %s",
                              rq.external_id)
        return True

    def _rewrite(self, status: dict, rq: RoutedQuery) -> dict:
        out = dict(status)
        out["query_id"] = rq.external_id
        out["replica"] = rq.replica_id
        if rq.resubmits or rq.failovers:
            out["router_resubmits"] = rq.resubmits
            out["router_failovers"] = rq.failovers
        if out.get("state") in (
            "DONE", "FAILED", "CANCELLED", "TIMED_OUT",
            "REJECTED_OVERLOADED",
        ):
            self._finish(rq, out.get("state"))
        return out

    # -- multi-tenant fleet protection -----------------------------------
    def _tenant_cfg(self, tenant: str, key: str, default):
        """Per-tenant override from tenant_config, with "*" as the
        config-level default tier and the constructor knob below it."""
        for scope in (tenant, "*"):
            ent = self.tenant_config.get(scope)
            if isinstance(ent, dict) and key in ent:
                return ent[key]
        return default

    def _tenant_count(self, tenant: str, key: str, n: int = 1) -> None:
        with self._tenant_mu:
            c = self._tenant_counters.setdefault(tenant, {
                "submitted": 0,
                "rate_limited": 0,
                "budget_spills": 0,
                "retry_budget_spent": 0,
                "retry_budget_exhausted": 0,
            })
            c[key] = c.get(key, 0) + n

    def _tenant_allow(self, tenant: str) -> bool:
        """Token-bucket admission for one SUBMIT. rate <= 0 = no limit
        for this tenant (the zero-config identity path)."""
        rate = float(self._tenant_cfg(tenant, "rate", self.tenant_rate))
        if rate <= 0:
            return True
        burst = self._tenant_cfg(tenant, "burst", self.tenant_burst)
        burst = max(1.0, float(burst) if burst is not None
                    else max(1.0, 2.0 * rate))
        now = time.monotonic()
        with self._tenant_mu:
            tokens, last = self._tenant_buckets.get(tenant,
                                                    (burst, now))
            tokens = min(burst, tokens + (now - last) * rate)
            if tokens >= 1.0:
                self._tenant_buckets[tenant] = [tokens - 1.0, now]
                return True
            self._tenant_buckets[tenant] = [tokens, now]
            return False

    def _retry_spend(self, tenant: str) -> bool:
        """Spend one unit of the tenant's windowed retry budget.
        Returns False (and counts the exhaustion) when the budget for
        the trailing window is gone: the caller must then surface the
        ORIGINAL error instead of amplifying the failure with another
        fleet-wide re-submit. budget <= 0 = unlimited (default).

        Crash-recovery re-adoption paths deliberately do NOT call
        this: a restarted router replaying its journal is recovering
        in-flight work, not observing new tenant load, and must not
        charge (or exhaust) anyone's budget for queries it merely
        re-polls."""
        budget = int(self._tenant_cfg(
            tenant, "retry_budget", self.tenant_retry_budget
        ))
        if budget <= 0:
            return True
        now = time.monotonic()
        with self._tenant_mu:
            dq = self._tenant_retries.setdefault(
                tenant, collections.deque()
            )
            while dq and now - dq[0] > self.tenant_retry_window_s:
                dq.popleft()
            if len(dq) >= budget:
                self.counters["tenant_retry_budget_exhausted"] += 1
                c = self._tenant_counters.setdefault(tenant, {})
                c["retry_budget_exhausted"] = \
                    c.get("retry_budget_exhausted", 0) + 1
                return False
            dq.append(now)
        self._tenant_count(tenant, "retry_budget_spent")
        REGISTRY.inc("blaze_tenant_retry_budget_spent_total",
                     tenant=tenant)
        return True

    # -- submit ----------------------------------------------------------
    def submit(self, meta: dict, task_bytes: bytes, *,
               is_ref: bool = False,
               manifest_bytes: Optional[bytes] = None) -> dict:
        with self._lock:
            self.counters["submitted"] += 1
        tenant = str(meta.get("tenant") or "default")
        self._tenant_count(tenant, "submitted")
        if not self._tenant_allow(tenant):
            # fleet-level rate limit: reject BEFORE journaling or
            # registering anything - a flooding tenant must not bloat
            # the routing table, the journal, or recovery replay. Same
            # wire shape as a replica-side budget rejection (the
            # REJECTED_TENANT_BUDGET marker), so ServiceClient
            # classifies it TenantBudgetError and backs off; zero
            # breaker involvement
            with self._lock:
                self.counters["tenant_rate_limited"] += 1
            self._tenant_count(tenant, "rate_limited")
            REGISTRY.inc("blaze_tenant_rate_limited_total",
                         tenant=tenant)
            return {
                "state": "REJECTED_OVERLOADED",
                "error": (
                    f"REJECTED_TENANT_BUDGET: tenant {tenant!r} is "
                    "over its router rate limit; retry with backoff"
                ),
                "error_class": "TRANSIENT",
            }
        key = affinity_key(task_bytes, is_ref)
        rq = RoutedQuery(key, task_bytes, is_ref, manifest_bytes,
                         dict(meta))
        if obs_trace.ACTIVE:
            # the router's OWN span tree for this query: the tier the
            # replica's trace cannot see (placement, failover, proxy
            # streaming). REPORT grafts the replica subtree under the
            # current hop span so `trace <qid>` through the router
            # renders client->router->replica->worker as ONE document
            rq.tracer = obs_trace.begin_trace(
                rq.external_id, root_name="router_query"
            )
            rq.tracer.root.tag(key=key[:16],
                               placement=self.placement_mode)
        # journal the SUBMIT bytes BEFORE placement: a crash between
        # admission and placement leaves a never-placed entry that
        # recovery re-enters into placement
        self._journal_submit(rq)
        try:
            resp = self._place_and_submit(rq, exclude=set())
        except ReplicaUnavailableError as e:
            with self._lock:
                self.counters["no_replica"] += 1
            self._finish(rq, "REJECTED_OVERLOADED")
            self._register(rq)
            return {
                "query_id": rq.external_id,
                "state": "REJECTED_OVERLOADED",
                "error": str(e),
                "error_class": "TRANSIENT",
            }
        if "query_id" not in resp:
            # in-band protocol error: the replica answered but could
            # not create the query, so there is no downstream handle to
            # track. Surface it exactly as a single serve instance
            # would - registering rq here would leave a never-finished
            # entry pinning its task_bytes in the routing table forever.
            # The journal needs the truncation marker though: without
            # it the S record stays live, compaction preserves it, and
            # the next restart would resurrect the known-bad plan as a
            # never-placed query and re-submit it to the fleet
            self._journal_finish(rq, resp.get("state") or "FAILED")
            return resp
        self._register(rq)
        return self._rewrite(resp, rq)

    def _place_and_submit(self, rq: RoutedQuery, exclude: set,
                          same_replica: Optional[str] = None) -> dict:
        """Place rq and forward its SUBMIT; walks the fleet on
        transport failures (each one a breaker strike) and on
        replica-level REJECTED_OVERLOADED backpressure (a placement
        miss, not a strike: affinity is only a hint, and a saturated
        affinity target must spill to idle fleet capacity instead of
        bouncing the client forever). Raises ReplicaUnavailableError
        when nobody routable is left or everybody rejected."""
        attempts = len(self.registry.replicas) + 1
        rejected_err: Optional[str] = None
        all_tenant_budget = True  # every rejection so far was tenant-budget
        rec = rq.tracer
        # one router_place span per placement pass (submit or
        # failover move): the ladder walk, every per-replica
        # router_attempt span nested under it, the chosen rung tagged
        # on exit. The span exit auto-tags error_class when the walk
        # raises ReplicaUnavailableError.
        place_cm = (
            obs_trace.span("router_place", rec=rec,
                           mode=self.placement_mode,
                           excluded=len(exclude))
            if rec is not None and obs_trace.ACTIVE
            else obs_trace.NULL
        )
        with place_cm as place_sp:
            for _ in range(attempts):
                decision = None
                if same_replica is not None:
                    r = self.registry.get(same_replica)
                    if r is not None and r.routable():
                        decision = PlacementDecision(r, "same")
                    same_replica = None  # only the first try is pinned
                if decision is None:
                    if self.placement_mode == "random":
                        decision = random_replica(
                            self.registry, next(self._rr_seq),
                            exclude=exclude,
                        )
                    else:
                        decision = choose_replica(
                            self.registry, self.affinity, rq.key,
                            estimated_bytes=rq.meta.get(
                                "estimated_bytes"
                            ),
                            fingerprint=rq.fingerprint,
                            stats_stale_s=self.stats_stale_s,
                            exclude=exclude,
                        )
                if decision is None:
                    break
                replica = decision.replica
                meta = dict(rq.meta)
                meta["detach"] = True  # router owns session semantics
                # the routing key IS the replica's decoded-plan-cache
                # key (placement.affinity_key == zerocopy.plan_digest
                # by construction): forward it so the replica skips
                # re-hashing the blob before its cache probe
                meta["plan_digest"] = rq.key
                hop_cm = (
                    obs_trace.span(
                        "router_attempt", rec=rec,
                        replica=replica.replica_id,
                        rung=decision.reason,
                        affinity_hit=decision.reason == "affinity",
                    )
                    if rec is not None and obs_trace.ACTIVE
                    else obs_trace.NULL
                )
                with hop_cm as hop:
                    try:
                        resp = self._call(
                            replica,
                            lambda c: c.submit_raw(
                                rq.task_bytes, meta=meta,
                                is_ref=rq.is_ref,
                                manifest_bytes=rq.manifest_bytes,
                            ),
                        )
                    except (ConnectionError, OSError,
                            ServiceError) as e:
                        log.warning(
                            "submit to %s failed (%r); trying next",
                            replica.replica_id, e,
                        )
                        hop.tag(transport_error=type(e).__name__,
                                error_class="TRANSIENT")
                        self.breaker.note_fatal(
                            replica.replica_id, kind="transport"
                        )
                        exclude.add(replica.replica_id)
                        continue
                    if "query_id" not in resp:
                        # in-band replica error (protocol): surface
                        hop.tag(inband_error=True)
                        return resp
                    if resp.get("state") == "REJECTED_OVERLOADED":
                        draining = _is_draining_rejection(resp)
                        tenant_budget = _is_tenant_budget_rejection(
                            resp
                        )
                        if draining:
                            # the replica announced a drain the next
                            # STATS poll has not delivered yet: stop
                            # placing here NOW. A placement miss like
                            # any backpressure - spill, zero breaker
                            # strikes (the replica is healthy, just
                            # leaving)
                            replica.draining = True
                            self.registry.note_membership(
                                "drain_reject", replica.replica_id
                            )
                            with self._lock:
                                self.counters["drain_spills"] += 1
                        elif tenant_budget:
                            # the TENANT is over budget on this
                            # replica, not the replica over capacity:
                            # spill (another replica may have budget
                            # headroom for it), no draining mark, zero
                            # breaker strikes
                            with self._lock:
                                self.counters[
                                    "tenant_budget_spills"
                                ] += 1
                            self._tenant_count(
                                str(rq.meta.get("tenant")
                                    or "default"),
                                "budget_spills",
                            )
                        if not tenant_budget:
                            all_tenant_budget = False
                        log.info(
                            "replica %s rejected %s (%s); spilling",
                            replica.replica_id, rq.external_id,
                            "draining" if draining
                            else "tenant budget" if tenant_budget
                            else "overloaded",
                        )
                        hop.tag(overflow_spill=True,
                                draining=draining or None,
                                tenant_budget=tenant_budget or None)
                        place_sp.event(
                            "overflow_spill",
                            replica=replica.replica_id,
                        )
                        with self._lock:
                            self.counters["overflow_spills"] += 1
                        rejected_err = resp.get("error") \
                            or "queue full"
                        exclude.add(replica.replica_id)
                        continue
                    hop.tag(internal_id=resp["query_id"])
                    with rq.lock:
                        rq.replica_id = replica.replica_id
                        rq.internal_id = resp["query_id"]
                        rq.generation += 1
                        if resp.get("fingerprint"):
                            rq.fingerprint = resp["fingerprint"]
                        if isinstance(hop, obs_trace.Span):
                            # the graft point for this generation's
                            # replica subtree (REPORT)
                            rq.hop_span = hop
                replica.note_routed()
                # placement record: recovery re-adopts by POLLing
                # this (replica_id, internal_id); failover moves land
                # as newer P records for the same handle
                self._journal_place(rq)
                place_sp.tag(rung=decision.reason,
                             replica=replica.replica_id)
                reason = f"placed_{decision.reason}" \
                    if decision.reason != "same" else None
                with self._lock:
                    if reason in self.counters:
                        self.counters[reason] += 1
                if self.placement_mode == "affinity" \
                        and rq.fingerprint:
                    # stable-fingerprint plans stick: repeats land on
                    # the replica whose ResultCache holds the result
                    self.affinity.record(
                        rq.key, replica.replica_id, rq.fingerprint
                    )
                    # hot-result replication payload capture: if this
                    # fingerprint ranks hot, the replicator re-places
                    # these bytes on a second replica
                    self.hot.note_submit(
                        rq.key, rq.fingerprint, rq.task_bytes,
                        rq.is_ref, rq.manifest_bytes,
                        replica.replica_id,
                    )
                return resp
            if rejected_err is not None:
                if all_tenant_budget:
                    # every routable replica rejected on THIS tenant's
                    # budget: keep the replica's marker as the message
                    # prefix so the client classifies it
                    # TenantBudgetError (not generic overload) through
                    # the router's error passthrough
                    raise ReplicaUnavailableError(rejected_err)
                raise ReplicaUnavailableError(
                    "every routable replica rejected overloaded "
                    f"(last: {rejected_err})"
                )
            raise ReplicaUnavailableError(
                "no routable replica "
                f"(fleet={len(self.registry.replicas)}, "
                f"excluded={len(exclude)})"
            )

    # -- failover moves --------------------------------------------------
    def _resubmit(self, rq: RoutedQuery, observed_gen: int, *,
                  same_replica: bool, exclude: set,
                  counter: str) -> bool:
        """Re-submit rq (same replica for TRANSIENT, elsewhere for
        failover). Generation-guarded: if another path already
        re-routed it, this is a no-op success."""
        with rq.lock:
            if rq.cancelled or rq.generation != observed_gen:
                # cancelled: the client let this query go - a pending
                # failover must not resurrect it on a healthy replica
                return True  # already moved / deliberately dropped
            # claim the move under the lock: a concurrent observer of
            # the same failure (death sweep vs. poll-path transport
            # error) now sees a newer generation and no-ops instead of
            # double-submitting the query downstream
            rq.generation += 1
            pin = rq.replica_id if same_replica else None
            old = rq.replica_id
            old_internal = rq.internal_id
            # a finished query's slot was already released by _finish
            # (e.g. DONE, then the replica restarted and lost the
            # result, and a re-FETCH is re-running it): releasing it
            # again below would under-count that replica's in_flight
            # and bias load-rung placement toward it for good
            old_released = rq.finished
        try:
            resp = self._place_and_submit(
                rq, exclude=set(exclude), same_replica=pin
            )
        except ReplicaUnavailableError:
            return False
        if "query_id" not in resp:
            # in-band protocol error from the chosen replica: nothing
            # was placed and rq still points at its OLD execution, so
            # falling through would release that slot and cancel the
            # query's only live downstream run as "superseded"
            return False
        if old and not old_released:
            # the original placement's in-flight slot is superseded by
            # the one _place_and_submit just counted - release it even
            # when the re-submission landed on the SAME replica
            r = self.registry.get(old)
            if r is not None:
                r.note_unrouted()
                if not same_replica and old_internal and r.alive:
                    # cross-replica failover away from a LIVE replica
                    # (breaker trip / lost handle): the superseded
                    # downstream execution was submitted detach=True,
                    # so nothing else will ever stop it - without this
                    # cancel it runs to completion holding the sick
                    # replica's admission slot and device reservation,
                    # and the query executes twice fleet-wide
                    self._cancel_superseded(r, old_internal)
        with rq.lock:
            if rq.cancelled:
                # the client cancelled while the move was in flight:
                # the fresh placement is already superseded - kill it
                # instead of resurrecting a handle the client let go
                new_rid, new_internal = rq.replica_id, rq.internal_id
                rq.finished = True
            else:
                new_rid = None
                rq.finished = False  # a moved query is live again
                rq.last_state = None
        if new_rid is not None:
            nr = self.registry.get(new_rid)
            if nr is not None:
                nr.note_unrouted()
                if new_internal:
                    self._cancel_superseded(nr, new_internal)
            return True
        with self._lock:
            self.counters[counter] += 1
        if counter == "failovers":
            rq.failovers += 1
        else:
            rq.resubmits += 1
        if rq.tracer is not None:
            # the move lands as a root-span event (the per-attempt
            # router_attempt spans carry the detail)
            rq.tracer.event("router_move", kind=counter,
                            replica=rq.replica_id)
        return True

    def _cancel_superseded(self, replica: Replica,
                           internal_id: str) -> None:
        """Fire-and-forget downstream cancel of an execution a
        failover just re-routed elsewhere. A dedicated short-timeout
        connection on a daemon thread - never the pooled verb client
        (a quarantined-but-alive replica must not stall healthy
        traffic behind its verb lock) and never the failover path's
        own latency budget."""
        from blaze_tpu.service.wire import ServiceClient

        def _go():
            try:
                with ServiceClient(replica.host, replica.port,
                                   timeout=5.0,
                                   reconnect_attempts=0) as c:
                    c.cancel(internal_id)
            except Exception:  # noqa: BLE001 - the replica may be
                pass           # mid-death; best-effort by design

        threading.Thread(
            target=_go, daemon=True,
            name=f"blaze-router-cancel-{replica.replica_id}",
        ).start()

    # -- membership ------------------------------------------------------
    def membership(self, payload: dict) -> dict:
        """The MEMBER verb: JOIN/LEAVE from replicas (announced by
        router/membership.MembershipAnnouncer). The `router.membership`
        chaos seam fires on every frame, so dropped JOINs, LEAVE races
        and flapping replicas are chaos-testable like every other
        failure path."""
        op = str(payload.get("op", ""))
        try:
            host, port = parse_replica(
                "%s:%s" % (payload.get("host"), payload.get("port"))
            )
        except (ValueError, TypeError):
            return {"error": f"membership: bad address in {payload!r}"}
        rid = f"{host}:{port}"
        if chaos.ACTIVE:
            # DROP = the ack never reaches the replica (announcer
            # retries next tick); STALL = a slow membership authority
            chaos.fire("router.membership", op=op, replica=rid)
        if op == "join":
            return self._member_join(
                host, port, devices=payload.get("devices")
            )
        if op == "leave":
            return self._member_leave(
                rid, str(payload.get("reason") or "leave")
            )
        return {"error": f"membership: unknown op {op!r}"}

    def _fleet_resize(self) -> None:
        """Re-derive the fleet device pool from live membership (the
        ledger's total rides JOIN/LEAVE; outstanding claims keep
        their grants across a shrink)."""
        self._fleet_ledger.resize(sum(
            getattr(r, "devices", 1)
            for r in self.registry.replicas.values()
            if not r.departed
        ))

    def mesh_exchange(self, payload: dict) -> dict:
        """Router-tier MESH_EXCHANGE ops: the fleet device claim plane
        (fleet/claims). Stage shipping (`run_stage`) is serve-tier
        only - hosts exchange stage data peer-to-peer, the router only
        arbitrates devices. Denials reuse the admission wire shapes
        (REJECTED_TENANT_BUDGET / DRAINING under REJECTED_OVERLOADED)
        and never touch the breaker."""
        from blaze_tpu.fleet.claims import FleetClaimDenied

        op = str(payload.get("op", ""))
        if op == "claim":
            try:
                token = self._fleet_ledger.claim(
                    str(payload.get("tenant") or "default"),
                    int(payload.get("devices", 1)),
                    timeout_s=float(payload.get("timeout_s", 0.0)),
                )
            except FleetClaimDenied as e:
                return {"error": str(e),
                        "state": "REJECTED_OVERLOADED"}
            return {"ok": True, "token": token}
        if op == "release":
            return {
                "ok": True,
                "released": self._fleet_ledger.release(
                    str(payload.get("token", ""))
                ),
            }
        if op == "stats":
            return {"ok": True, "fleet": self._fleet_ledger.stats()}
        return {"error": f"mesh_exchange: unknown router op {op!r}"}

    def _member_join(self, host: str, port: int,
                     devices=None) -> dict:
        r, created = self.registry.add((host, port))
        if devices is not None:
            try:
                r.devices = max(1, int(devices))
            except (TypeError, ValueError):
                pass
        self._fleet_resize()
        rid = r.replica_id
        self._client_cv.setdefault(
            rid,
            threading.Condition(obs_contention.TimedLock("conn_pool")),
        )
        if created and not r.alive:
            # one synchronous probe so the ack implies routability -
            # a joining replica takes traffic NOW, not a poll tick
            # from now
            try:
                self.registry.probe(rid)
            except Exception:  # noqa: BLE001 - the poller retries
                pass
        if self._recover_pending:
            # recovery reconciles as announcers re-JOIN: this may be
            # the replica a journaled placement is waiting for
            self._recover_kick.set()
        return {
            "ok": True,
            "replica": rid,
            "created": created,
            "state": r.membership_state(),
            "fleet": len(self.registry.replicas),
        }

    def _member_leave(self, rid: str, reason: str) -> dict:
        r = self.registry.remove(rid, reason="leave")
        if r is None:
            # LEAVE of an unknown (or already-left) replica: ack -
            # the desired end state already holds
            return {"ok": True, "replica": rid, "known": False}
        self._fleet_resize()
        self._evict_and_promote(rid)
        # drop the pooled verb clients: the address may be reused by
        # a restarted replica that must start on fresh connections
        cv = self._client_cv.get(rid)
        if cv is not None:
            with cv:
                idle = self._clients.pop(rid, [])
                self._client_counts.pop(rid, None)
                # epoch bump: clients currently CHECKED OUT (invisible
                # to this purge) close at check-in instead of being
                # pooled for whoever re-joins at this address
                self._client_epoch[rid] = (
                    self._client_epoch.get(rid, 0) + 1
                )
                cv.notify_all()
            for c in idle:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 - teardown
                    pass
        # a LEAVE racing in-flight queries (crash-leave, drain
        # timeout): re-route them like a death would
        with self._lock:
            stranded = any(
                rq.replica_id == rid and not rq.finished
                for rq in self._queries.values()
            )
        if stranded:
            threading.Thread(
                target=self._on_replica_dead, args=(r,), daemon=True,
                name=f"blaze-router-failover-{rid}",
            ).start()
        return {
            "ok": True, "replica": rid, "known": True,
            "reason": reason,
        }

    def _evict_and_promote(self, replica_id: str) -> None:
        """Departure bookkeeping (LEAVE or heartbeat death): evict the
        replica's AffinityMap entries eagerly - instead of letting
        each decay into a failed placement + failover - then promote
        confirmed hot-result secondaries to the affinity home so
        repeats stay warm on the survivor."""
        evicted = self.affinity.evict_replica(replica_id)
        if evicted:
            REGISTRY.inc("blaze_router_affinity_evictions_total",
                         evicted)
        promoted = self.hot.on_replica_gone(replica_id)
        log.info(
            "replica %s departed: %d affinity entries evicted, %d "
            "hot fingerprints promoted to survivors",
            replica_id, evicted, len(promoted),
        )

    def _on_replica_departed_async(self, replica: Replica) -> None:
        """Registry death callback: the re-route sweep performs
        downstream submits (seconds per query against a slow fleet)
        and the registry poller must not stall behind failover work
        (a second concurrent death must still be detected while the
        first one's queries move) - detach the sweep. Affinity
        eviction + hot promotion run inline first: they are lock-bound
        and the next submit must already see the re-pointed fleet.
        The breaker-trip path calls _on_replica_dead directly: a
        quarantine is a cool-off, not a departure - affinity state
        survives it."""
        self._evict_and_promote(replica.replica_id)
        threading.Thread(
            target=self._on_replica_dead, args=(replica,),
            daemon=True,
            name=f"blaze-router-failover-{replica.replica_id}",
        ).start()

    def _on_replica_dead(self, replica: Replica) -> None:
        """Re-route the dead replica's in-flight routed queries to
        healthy replicas. DONE queries are left alone - a later FETCH
        fails over on demand (their results died with the replica's
        cache)."""
        with self._lock:
            moved = [
                rq for rq in self._queries.values()
                if rq.replica_id == replica.replica_id
                and not rq.finished
            ]
        for rq in moved:
            ok = self._resubmit(
                rq, rq.generation, same_replica=False,
                exclude={replica.replica_id}, counter="failovers",
            )
            log.warning(
                "replica %s dead: query %s %s",
                replica.replica_id, rq.external_id,
                "re-routed to %s" % rq.replica_id if ok
                else "stranded (no routable replica)",
            )

    def _observe_failed(self, rq: RoutedQuery, status: dict) -> dict:
        """Class-aware reaction to a FAILED status seen through the
        proxy: TRANSIENT re-submits to the same replica (bounded, with
        backoff); fatal classes strike the circuit breaker (tripping
        quarantines the replica and re-routes its other queries);
        PLAN_INVALID/CANCELLED surface untouched."""
        action = failover_action(status.get("error_class"))
        rid = rq.replica_id
        if action == "resubmit" and rq.resubmits < self.max_resubmits:
            if not self._retry_spend(
                str(rq.meta.get("tenant") or "default")
            ):
                # windowed retry budget exhausted: surface the
                # ORIGINAL classified error instead of letting one
                # tenant's persistently-failing plan amplify into
                # fleet-wide retry storms. Other tenants' budgets are
                # untouched
                return status
            delay = self.resubmit_backoff_s * (2 ** rq.resubmits)
            time.sleep(random.uniform(delay * 0.5, delay))
            if self._resubmit(rq, rq.generation, same_replica=True,
                              exclude=set(),
                              counter="resubmits_transient"):
                st = self._downstream_status(rq)
                if st.get("state") == "FAILED" and not rq.finished:
                    # the re-run failed within one status round trip:
                    # react to ITS class too, or a remaining resubmit
                    # budget would be silently abandoned (bounded:
                    # each round consumed one resubmit above)
                    return self._observe_failed(rq, st)
                return st
        elif action == "breaker" and rid is not None:
            # this query surfaces as-is: finalize it BEFORE the trip so
            # the quarantine's in-flight sweep re-routes only the
            # replica's OTHER queries, not the one whose fatal failure
            # is being reported. Only the finalization WINNER strikes:
            # concurrent observers of the same failure (two pollers, a
            # poll racing the FETCH error path) must count ONE event
            if self._finish(rq, status.get("state")):
                tripped = self.breaker.note_fatal(rid, kind="query")
                if tripped:
                    dead = self.registry.get(rid)
                    if dead is not None:
                        self._on_replica_dead(dead)
        return status

    # -- proxy verbs -----------------------------------------------------
    def _downstream_status(self, rq: RoutedQuery,
                           depth: int = 0) -> dict:
        if depth > len(self.registry.replicas) + 2:
            raise ReplicaUnavailableError(
                f"status of {rq.external_id} unobtainable: the fleet "
                "keeps failing under it"
            )
        if rq.recovered and not rq.reconciled and not rq.finished:
            # a recovered handle awaiting reconcile: its detached
            # downstream run is (presumably) still executing - report
            # RUNNING instead of finalizing on replayed state, and
            # never error on a replica the announcers have not
            # re-delivered yet
            return {
                "query_id": rq.external_id,
                "state": "RUNNING",
                "note": "recovering: awaiting replica re-JOIN for "
                        "reconciliation",
                "replica": rq.replica_id,
            }
        if rq.internal_id is None:
            # never placed (REJECTED_OVERLOADED at submit): the
            # routing table still owns the handle - report its
            # terminal state instead of pretending it is unknown
            return {
                "query_id": rq.external_id,
                "state": rq.last_state or "REJECTED_OVERLOADED",
                "error": "never placed: no routable replica",
                "error_class": "TRANSIENT",
            }
        gen = rq.generation
        replica = self.registry.get(rq.replica_id or "")
        if replica is None:
            if rq.finished and rq.last_state:
                # e.g. a stranded recovery: the replica never came
                # back, but the router still owns the handle
                return self._last_known_status(rq)
            raise KeyError(f"unknown replica for {rq.external_id}")
        try:
            st = self._call(
                replica, lambda c: c.poll(rq.internal_id)
            )
        except (ConnectionError, OSError, ServiceError):
            self.breaker.note_fatal(
                replica.replica_id, kind="transport"
            )
            if rq.finished and rq.last_state:
                # the query already reached a terminal state through
                # this router: report it from the routing table - a
                # status check must never resurrect a dead handle
                return self._last_known_status(rq)
            if not self._retry_spend(
                str(rq.meta.get("tenant") or "default")
            ):
                raise ReplicaUnavailableError(
                    f"replica {replica.replica_id} unreachable and "
                    "tenant retry budget exhausted"
                )
            if not self._resubmit(rq, gen, same_replica=False,
                                  exclude={replica.replica_id},
                                  counter="failovers"):
                raise ReplicaUnavailableError(
                    f"replica {replica.replica_id} unreachable and "
                    "no routable replica to re-route to"
                )
            return self._downstream_status(rq, depth + 1)
        if "error" in st and "query_id" not in st:
            # replica lost the handle (restarted)
            if rq.finished and rq.last_state:
                return self._last_known_status(rq)  # never re-run
            # live query: re-route = fresh run (budget-gated: a lost
            # handle re-run is a failover re-submit like any other)
            if self._retry_spend(
                str(rq.meta.get("tenant") or "default")
            ) and self._resubmit(rq, gen, same_replica=False,
                                 exclude=set(), counter="failovers"):
                return self._downstream_status(rq, depth + 1)
        return st

    def _last_known_status(self, rq: RoutedQuery) -> dict:
        return {
            "query_id": rq.external_id,
            "state": rq.last_state,
            "note": "replica no longer holds the handle; state is "
                    "the router's last observation",
        }

    def poll(self, external_id: str) -> dict:
        rq = self.get(external_id)
        st = self._downstream_status(rq)
        if st.get("state") == "FAILED" and not rq.finished:
            st = self._observe_failed(rq, st)
        return self._rewrite(st, rq)

    def cancel(self, external_id: str) -> dict:
        rq = self.get(external_id)
        # finalize FIRST (stops the failover machinery and releases
        # the replica's in-flight slot) - the downstream cancel below
        # is best-effort cleanup of a handle we already let go of.
        # The flag + generation bump under the lock make any in-flight
        # _resubmit no-op (or kill its fresh placement): a cancelled
        # query must never be resurrected by failover
        with rq.lock:
            rq.cancelled = True
            rq.generation += 1
            replica_id, internal_id = rq.replica_id, rq.internal_id
        self._finish(rq, rq.last_state)
        replica = self.registry.get(replica_id or "")
        try:
            if replica is None:
                raise ConnectionError("no replica")
            st = self._call(
                replica, lambda c: c.cancel(internal_id)
            )
        except (ConnectionError, OSError, ServiceError):
            # replica gone: nothing to cancel - the handle just ends
            st = {"state": "CANCELLED",
                  "error": "replica unreachable; handle abandoned"}
        return self._rewrite(st, rq)

    def report(self, external_id: str, flags: int = 0) -> dict:
        rq = self.get(external_id)
        if rq.internal_id is None:
            # never placed (REJECTED_OVERLOADED at submit): answer
            # from the routing table like poll() does - the router
            # issued this handle, so it must not report it unknown
            out = {
                "query_id": rq.external_id,
                "replica": None,
                "state": rq.last_state or "REJECTED_OVERLOADED",
                "report": "never placed: no routable replica",
            }
            if flags & 1 and rq.tracer is not None:
                out["trace"] = obs_trace.chrome_trace(rq.tracer)
            if flags & 2 and rq.tracer is not None:
                out["trace_spans"] = rq.tracer.to_dicts()
            return out
        rec = rq.tracer
        # the router honors BOTH report flag bits, exactly like a
        # single serve instance (the shared verb loop's protocol
        # symmetry): bit 0 = rendered Chrome doc, bit 1 = raw span
        # dicts - so a router can itself sit behind another router's
        # cross-hop graft
        want_doc = bool(flags & 1)
        want_spans = bool(flags & 2)
        # snapshot the generation under the lock BEFORE the RPC: a
        # failover racing this REPORT swaps replica_id/internal_id/
        # hop_span, and grafting the OLD generation's spans under the
        # NEW hop span (or marking the new id grafted with the old
        # subtree) would permanently wedge the trace
        with rq.lock:
            internal_id = rq.internal_id
            anchor = rq.hop_span
            replica_id = rq.replica_id
        replica = self.registry.get(replica_id or "")
        if replica is None:
            raise KeyError(f"unknown replica for {external_id}")
        try:
            if want_doc or want_spans:
                # cross-hop trace: when the router recorded its own
                # span tree, ask the replica for RAW span dicts
                # (flags bit 1) and graft them under the current hop
                # span - ONE Perfetto document spanning client ->
                # router -> replica -> worker. Routers without a
                # recorder (route --no-trace) pass the replica's
                # rendered document / raw spans through untouched.
                resp = self._call(
                    replica,
                    lambda c: c.report_full(
                        internal_id,
                        include_trace=want_doc and rec is None,
                        include_spans=want_spans or rec is not None,
                    ),
                )
                if "error" in resp and "report" not in resp:
                    resp = None  # replica lost the handle (restarted)
            else:
                resp = {"report": self._call(
                    replica, lambda c: c.report(internal_id)
                )}
        except (ConnectionError, OSError, ServiceError, KeyError):
            # unreachable replica, or one that restarted and lost the
            # handle (ServiceClient.report KeyErrors on its error
            # reply): fall back to the routing table below
            resp = None
        if resp is None:
            # the router issued this handle, so it must not surface a
            # replica-side lookup miss as an opaque "unknown query"
            # error - report what the routing table knows, the same
            # way poll() answers for finalized queries
            out = {
                "query_id": rq.external_id,
                "replica": rq.replica_id,
                "state": rq.last_state,
                "report": "replica no longer holds the handle; "
                          "state is the router's last observation",
            }
            if want_doc and rec is not None:
                # the router-side spans survive the replica's death
                out["trace"] = obs_trace.chrome_trace(rec)
            if want_spans and rec is not None:
                out["trace_spans"] = rec.to_dicts()
            return out
        if (want_doc or want_spans) and rec is not None:
            spans = resp.pop("trace_spans", None)
            if spans:
                with rq.lock:
                    # keyed + anchored on the PRE-RPC snapshot: the
                    # fetched spans belong to THAT generation, and a
                    # failover that moved the query mid-RPC must not
                    # see its fresh internal_id marked grafted
                    fresh = internal_id not in rq.grafted
                    if fresh:
                        rq.grafted.add(internal_id)
                if fresh:
                    # id-remapped graft (obs/trace.attach_subtree):
                    # the replica's root re-parents under the hop
                    # span that submitted this generation
                    rec.attach_subtree(spans, parent=anchor)
            if want_doc:
                resp["trace"] = obs_trace.chrome_trace(rec)
            if want_spans:
                # the GRAFTED tree: an upstream router re-grafts the
                # whole client->router->replica subtree in one piece
                resp["trace_spans"] = rec.to_dicts()
        resp["query_id"] = rq.external_id
        resp["replica"] = rq.replica_id
        return resp

    def stats(self) -> dict:
        """The fleet view: router decision/health counters, per-replica
        health snapshots, and replica STATS aggregates."""
        fleet = {
            "replicas": len(self.registry.replicas),
            "alive": 0,
            "draining": 0,
            "departed": len(self.registry.departed),
            "queued": 0,
            "running": 0,
            "headroom_bytes": 0,
            "cache": {"hits": 0, "misses": 0, "coalesced": 0},
            # zero-copy serve path aggregates (zerocopy/): how often
            # the fleet skipped protobuf decode / served from arena
            "plan_cache": {"hits": 0, "misses": 0, "evictions": 0},
            "arena": {"segments": 0, "bytes": 0, "sg_serves": 0,
                      "handle_hits": 0},
            "queries_by_state": {},
            # per-tenant admission state summed across replica STATS
            # (queued/running/reserved_bytes + replica-side budget
            # rejections); the router-tier guards (rate_limited,
            # retry_budget_*) live under "router.tenants"
            "tenants": {},
        }
        for r in self.registry.replicas.values():
            if r.alive:
                fleet["alive"] += 1
            if r.draining:
                fleet["draining"] += 1
            if r.stats is None:
                continue
            a = r.stats.get("admission", {})
            fleet["queued"] += int(a.get("queued", 0))
            fleet["running"] += int(a.get("running", 0))
            fleet["headroom_bytes"] += max(
                0, r.effective_headroom() or 0
            )
            c = r.stats.get("cache", {})
            for k in fleet["cache"]:
                fleet["cache"][k] += int(c.get(k, 0))
            pc = r.stats.get("plan_cache", {})
            for k in fleet["plan_cache"]:
                fleet["plan_cache"][k] += int(pc.get(k, 0))
            ar = r.stats.get("arena", {})
            for k in fleet["arena"]:
                fleet["arena"][k] += int(ar.get(k, 0))
            for s, n in (
                r.stats.get("queries", {}).get("by_state", {}).items()
            ):
                fleet["queries_by_state"][s] = (
                    fleet["queries_by_state"].get(s, 0) + int(n)
                )
            for t, ts in (r.stats.get("tenants") or {}).items():
                agg = fleet["tenants"].setdefault(t, {
                    "queued": 0, "running": 0, "reserved_bytes": 0,
                    "submitted": 0, "admitted": 0,
                    "rejected_budget": 0,
                })
                for k in agg:
                    agg[k] += int(ts.get(k, 0))
        with self._lock:
            counters = dict(self.counters)
            retained = len(self._queries)
        with self._tenant_mu:
            tenant_counters = {
                t: dict(c) for t, c in self._tenant_counters.items()
            }
        return {
            "router": {
                "placement": self.placement_mode,
                **counters,
                "queries_retained": retained,
                "affinity_entries": len(self.affinity),
                # crash-safety state: journaling on/off + how many
                # recovered handles still await reconciliation
                "journal": self.journal is not None,
                "recover_pending": len(self._recover_pending),
                # streaming relay flow control (counters above carry
                # stream_stalls / stream_window_waits)
                "streaming": {
                    "window": self.stream_window,
                    "stall_s": self.stream_stall_s,
                },
                # router-tier tenant guards: per-tenant counters plus
                # the effective default knobs (per-tenant overrides
                # come from tenant_config)
                "tenants": tenant_counters,
                "tenant_limits": {
                    "rate": self.tenant_rate,
                    "retry_budget": self.tenant_retry_budget,
                    "retry_window_s": self.tenant_retry_window_s,
                },
            },
            "replicas": self.registry.snapshot(),
            "fleet": fleet,
            # hot-result replication state (replication.py): which
            # fingerprints hold a confirmed second copy - the churn
            # tests and dashboards wait on this
            "hot": self.hot.snapshot(),
            # this process's per-phase rollup (the `router` phase for
            # proxied queries; regress can diff a live router too)
            "phases": obs_phases.ROLLUP.snapshot(max_classes=6),
            # lock-wait accounting (obs/contention.py): empty dict
            # when the gate is off or nothing contended yet
            "contention": obs_contention.snapshot(),
            # mesh stage anatomy (obs/meshprof.py): empty on a pure
            # router unless an embedded replica ran a mesh stage in
            # this process - served here so both tiers expose the
            # same observability sections
            "meshprof": obs_meshprof.snapshot(),
        }

    def metrics(self) -> str:
        """Fleet Prometheus exposition: the router process's own
        registry (router counters, per-replica gauges) plus every
        reachable replica's scrape stamped with a `replica` label.
        Replicas are scraped CONCURRENTLY on dedicated short-timeout
        connections - never the pooled verb clients (a wedged replica
        must not stall SUBMIT/POLL behind a 120s _call lock), and
        never serially (a fleet scrape must cost max(replica), not
        sum(replica), or slow replicas push it past the collector's
        own timeout)."""
        from blaze_tpu.service.wire import ServiceClient

        per_replica: Dict[str, str] = {}

        def scrape(rid, r):
            try:
                with ServiceClient(r.host, r.port, timeout=5.0,
                                   reconnect_attempts=0) as c:
                    per_replica[rid] = c.metrics()
            except Exception:  # noqa: BLE001 - counted, not raised
                # a quarantined (or just-wedged) replica silently
                # vanishing from the merged exposition looks exactly
                # like it was never configured - count the failure
                # with the replica label so dashboards see the GAP,
                # not just the absence
                REGISTRY.inc("blaze_router_scrape_failed",
                             replica=rid)

        # heartbeat-DEAD replicas are counted failed WITHOUT a
        # connect attempt: the pollers already know nothing answers,
        # and a black-holed host would otherwise add its full connect
        # timeout to every fleet scrape. Quarantined-but-alive
        # replicas (breaker-open) still answer METRICS and are
        # scraped normally.
        threads = []
        for rid, r in self.registry.replicas.items():
            if not r.ever_alive:
                continue
            if not r.alive:
                REGISTRY.inc("blaze_router_scrape_failed",
                             replica=rid)
                continue
            threads.append(
                threading.Thread(target=scrape, args=(rid, r),
                                 daemon=True,
                                 name=f"blaze-router-scrape-{rid}")
            )
        t_scrape = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        out = merge_expositions(
            REGISTRY.render_prometheus(), per_replica
        )
        # scrape cost is itself observable; a fleet scrape prices
        # max(replica), and this lands in the NEXT exposition
        REGISTRY.observe("blaze_scrape_seconds",
                         time.perf_counter() - t_scrape,
                         tier="router")
        return out

    def _collect_metrics(self):
        # a generator: the registry consumes it at scrape time, so no
        # per-scrape sample list is materialized here
        with self._lock:
            counters = dict(self.counters)
        for k, v in counters.items():
            yield ("blaze_router_events_total", {"event": k}, v,
                   "counter")
        # fleet-wide relay-window memory across concurrent streams:
        # the observability precursor to a fleet-wide relay-memory cap
        yield ("blaze_router_stream_buffered_bytes", {},
               self._stream_buffered, "gauge")
        # router-tier tenant guards (replica-side budget state comes
        # from each replica's own blaze_tenant_* gauges)
        with self._tenant_mu:
            tenant_counters = {
                t: dict(c) for t, c in self._tenant_counters.items()
            }
        for t, c in tenant_counters.items():
            for k in ("rate_limited", "budget_spills",
                      "retry_budget_spent"):
                yield (f"blaze_tenant_{k}", {"tenant": t},
                       c.get(k, 0), "counter")

    # -- FETCH passthrough -----------------------------------------------
    def _splice_note(self, rq, i: int, payload: bytes) -> bool:
        """Verify part `i` against (or extend) the canonical part
        record: parts the client already received - from this stream
        or a previous aborted one - must be byte-identical in a
        re-executed result, or the client's count-based resume would
        splice two different results into one corrupt table. Returns
        True when the stream is splice-broken. Shared by the threaded
        and event-loop relay paths."""
        h = hashlib.blake2b(payload, digest_size=16).digest()
        with rq.lock:
            if i < len(rq.delivered_hashes):
                if rq.delivered_hashes[i] != h:
                    rq.splice_broken = True
            else:
                rq.delivered_hashes.append(h)
        return rq.splice_broken

    def _relay_admit(self, nbytes: int, pending: list) -> bool:
        """Try to account `nbytes` of relay-parked payload against the
        router-wide gauge AND the fleet-wide stream_total_bytes
        budget. `pending` is this stream's share cell ([bytes]). A
        stream with nothing parked always admits one part (progress
        beats the bound); returns False when the caller must wait."""
        with self._stream_buffered_mu:
            if (
                self.stream_total_bytes > 0
                and pending[0] > 0
                and self._stream_buffered + nbytes
                > self.stream_total_bytes
            ):
                return False
            pending[0] += nbytes
            self._stream_buffered += nbytes
            return True

    def _relay_release(self, nbytes: int, pending: list) -> None:
        with self._stream_buffered_mu:
            pending[0] -= nbytes
            self._stream_buffered -= nbytes

    def stream_parts(self, external_id: str,
                     timeout_ms: int = 0) -> Iterator[bytes]:
        """Yield the raw segmented-IPC part payloads for one query,
        surviving replica death mid-stream: the query is re-routed
        (fresh execution on a healthy replica - results are
        deterministic per part, the ServiceClient re-FETCH contract)
        and parts the client already received are skipped."""
        rq = self.get(external_id)
        if rq.splice_broken:
            raise ServiceError(_SPLICE_ERR)
        # a FETCH racing the reconcile pass waits for it (bounded):
        # fetching a stale placement would bounce off a replica one
        # announcer tick away from serving the journaled result
        self._await_reconcile(rq)
        sent = 0
        cycles = 0
        max_cycles = 3 + self.max_resubmits \
            + len(self.registry.replicas)
        stream_t0 = time.monotonic()
        completed = False
        try:
            while True:
                gen = rq.generation
                replica = self.registry.get(rq.replica_id or "")
                if replica is None:
                    raise ServiceError(
                        f"UNKNOWN: no replica for {external_id}"
                    )
                try:
                    for i, payload in enumerate(self._raw_fetch(
                        replica, rq.internal_id, timeout_ms
                    )):
                        if self._splice_note(rq, i, payload):
                            raise ServiceError(_SPLICE_ERR)
                        if i < sent:
                            continue  # already delivered on this stream
                        sent += 1
                        yield payload
                    completed = True
                    self._finish(rq, "DONE")
                    return
                except ServiceError as e:
                    if rq.splice_broken:
                        self._finish(rq, "FAILED")
                        raise
                    cycles += 1
                    if cycles > max_cycles:
                        raise
                    if e.state == "FAILED":
                        st = self._downstream_status(rq)
                        if st.get("state") == "FAILED" and not rq.finished:
                            # same guard as poll(): a re-FETCH of an
                            # already-finalized failure must not land a
                            # second breaker strike for the same event
                            st = self._observe_failed(rq, st)
                        if st.get("state") == "FAILED" or rq.finished:
                            self._finish(rq, st.get("state"))
                            raise
                        continue  # re-routed or retrying: fetch again
                    if e.state == "UNKNOWN":
                        if self._resubmit(rq, gen, same_replica=False,
                                          exclude=set(),
                                          counter="failovers"):
                            continue
                    raise
                except (ConnectionError, OSError) as e:
                    cycles += 1
                    if cycles > max_cycles:
                        raise
                    if rq.generation != gen:
                        continue  # death callback already moved it
                    self.breaker.note_fatal(
                        replica.replica_id, kind="transport"
                    )
                    if replica.routable():
                        continue  # transient drop: re-FETCH same replica
                    if not self._resubmit(rq, gen, same_replica=False,
                                          exclude={replica.replica_id},
                                          counter="failovers"):
                        raise ReplicaUnavailableError(
                            f"replica {replica.replica_id} lost "
                            f"mid-FETCH of {external_id}: {e!r}"
                        ) from e
        finally:
            if rq.tracer is not None:
                # retroactive proxy-streaming span (a live span would
                # straddle generator suspensions): parts actually
                # forwarded + resume cycles; aborted streams (client
                # gone, fleet lost) are tagged - the re-FETCH records
                # its own span
                tags = {"parts": sent}
                if cycles:
                    tags["resumes"] = cycles
                if not completed:
                    tags["aborted"] = True
                try:
                    rq.tracer.record_span(
                        "router_stream", stream_t0,
                        time.monotonic(), **tags,
                    )
                except Exception:  # noqa: BLE001 - obs must not raise
                    pass

    def _raw_fetch(self, replica: Replica, internal_id: str,
                   timeout_ms: int) -> Iterator[bytes]:
        """One downstream FETCH as raw part payloads (never decoded),
        every part yielded in order (the caller skips/verifies).
        stream_window > 1 overlaps the downstream RECV with the client
        SEND through a bounded credit window; window <= 1 keeps the
        strictly-serial path (recv one part, relay it, recv the
        next)."""
        if self.stream_window <= 1:
            yield from self._raw_fetch_direct(
                replica, internal_id, timeout_ms
            )
        else:
            yield from self._raw_fetch_windowed(
                replica, internal_id, timeout_ms
            )

    def _fetch_connect(self, replica: Replica):
        # connect on its own budget: fetch_block_s slices RECV waits
        # (a socket.timeout there is a poll tick, not a failure), but
        # bounding the CONNECT at 0.5s would turn accept-backlog
        # latency on a busy-but-healthy replica into transport-class
        # breaker strikes - and a few of those quarantine the replica
        # and duplicate every one of its in-flight queries
        sock = socket.create_connection(
            (replica.host, replica.port),
            timeout=min(self.downstream_timeout_s, 10.0),
        )
        sock.settimeout(self.fetch_block_s)
        return sock

    def _raw_fetch_direct(self, replica: Replica, internal_id: str,
                          timeout_ms: int) -> Iterator[bytes]:
        """Serial relay: blocks in short slices so replica death
        during a long wait is noticed between frames instead of
        hanging the client."""
        from blaze_tpu.runtime.gateway import _FLAG_SERVICE
        from blaze_tpu.service.wire import ServiceClient

        sock = self._fetch_connect(replica)
        try:
            sock.sendall(_U64.pack(_FLAG_SERVICE))
            sock.sendall(ServiceClient._id_verb(
                VERB_FETCH, internal_id, timeout_ms
            ))
            while True:
                header = self._recv_checked(sock, _U64.size, replica)
                (length,) = _U64.unpack(header)
                if length == 0:
                    return
                if length == _ERR:
                    (mlen,) = _U32.unpack(
                        self._recv_checked(sock, _U32.size, replica)
                    )
                    msg = self._recv_checked(
                        sock, mlen, replica
                    ).decode("utf-8")
                    raise ServiceError(msg)
                payload = self._recv_checked(sock, length, replica)
                yield payload
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _raw_fetch_windowed(self, replica: Replica, internal_id: str,
                            timeout_ms: int) -> Iterator[bytes]:
        """Credit-window relay: a reader thread pulls raw parts off
        the downstream socket into a bounded queue while the caller
        (the client-facing writer) drains it - at most stream_window
        parts in flight at the router, each the SAME bytes object that
        came off the wire (no per-part materialization or re-framing;
        the zero-copy bar of the passthrough survives the overlap). A
        full window parks the READER (the downstream replica's own
        stream buffer absorbs the backpressure and accounts it against
        the query's reservation); `stream_window_waits` counts parts
        that had to park, `stream_total_waits` parts held back by the
        FLEET-WIDE stream_total_bytes budget across concurrent
        streams. Queue items: ("part", payload) in order, then exactly
        one ("end", None) or ("err", exc)."""
        from blaze_tpu.runtime.gateway import _FLAG_SERVICE
        from blaze_tpu.service.wire import ServiceClient

        sock = self._fetch_connect(replica)
        window: queue.Queue = queue.Queue(maxsize=self.stream_window)
        stop = threading.Event()
        # this stream's share of the router-wide buffered-bytes
        # gauge; the finally below subtracts the residual so an
        # abandoned stream cannot leak gauge weight
        pending = [0]

        def _put(item) -> bool:
            waited = False
            if item[0] == "part":
                # account BEFORE parking so the gauge covers the
                # window-full wait, not just settled parts - gated on
                # the shared relay-memory budget first
                total_waited = False
                while not self._relay_admit(len(item[1]), pending):
                    if not total_waited:
                        total_waited = True
                        with self._lock:
                            self.counters["stream_total_waits"] += 1
                    if stop.wait(0.05):
                        return False
            while not stop.is_set():
                try:
                    window.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    if not waited and item[0] == "part":
                        waited = True
                        with self._lock:
                            self.counters["stream_window_waits"] += 1
            if item[0] == "part":
                self._relay_release(len(item[1]), pending)
            return False  # consumer gone: drop, reader exits

        def _reader() -> None:
            try:
                sock.sendall(_U64.pack(_FLAG_SERVICE))
                sock.sendall(ServiceClient._id_verb(
                    VERB_FETCH, internal_id, timeout_ms
                ))
                while True:
                    header = self._recv_checked(
                        sock, _U64.size, replica
                    )
                    (length,) = _U64.unpack(header)
                    if length == 0:
                        _put(("end", None))
                        return
                    if length == _ERR:
                        (mlen,) = _U32.unpack(self._recv_checked(
                            sock, _U32.size, replica
                        ))
                        msg = self._recv_checked(
                            sock, mlen, replica
                        ).decode("utf-8")
                        _put(("err", ServiceError(msg)))
                        return
                    payload = self._recv_checked(
                        sock, length, replica
                    )
                    if not _put(("part", payload)):
                        return
            except BaseException as e:  # noqa: BLE001 - relayed
                # the consumer re-raises it in stream_parts, where
                # the failover ladder classifies it; swallowing here
                # would hang the relay on a dead downstream
                _put(("err", e))

        reader = threading.Thread(
            target=_reader, daemon=True,
            name="blaze-router-stream-reader",
        )
        reader.start()
        try:
            while True:
                kind, payload = window.get()
                if kind == "part":
                    self._relay_release(len(payload), pending)
                    yield payload
                elif kind == "end":
                    return
                else:
                    raise payload
        finally:
            # generator close (client gone, failover cycle, or clean
            # end): release the reader - stop flag first so a parked
            # _put exits, then the socket so a blocked recv does
            stop.set()
            try:
                sock.close()
            except OSError:
                pass
            reader.join(timeout=2.0)
            # reader joined, consumer done: whatever this stream
            # still attributes to the gauge is residual - drop it
            with self._stream_buffered_mu:
                self._stream_buffered -= pending[0]
                pending[0] = 0

    def _recv_checked(self, sock, n: int,
                      replica: Replica) -> bytes:
        """recv_exact in fetch_block_s slices, aborting promptly when
        the replica goes unroutable mid-wait (a FETCH blocked on a
        dead replica must fail over, not hang)."""
        buf = bytearray()
        stalled = 0
        # a mid-frame stall means bytes stopped flowing mid-payload;
        # bound it separately from the legitimate between-frame wait
        max_midframe = max(4, int(60.0 / self.fetch_block_s))
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except socket.timeout:
                if not replica.routable():
                    raise ConnectionError(
                        f"replica {replica.replica_id} unroutable "
                        "mid-FETCH"
                    ) from None
                if buf:
                    stalled += 1
                    if stalled > max_midframe:
                        raise ConnectionError(
                            "mid-frame stall from "
                            f"{replica.replica_id}"
                        ) from None
                continue
            if not chunk:
                raise ConnectionError("EOF from replica mid-FETCH")
            stalled = 0
            buf += chunk
        return bytes(buf)

    # -- event-loop relay (service/wire_async.py data plane) -----------
    async def stream_parts_async(self, external_id: str,
                                 timeout_ms: int = 0):
        """Coroutine twin of stream_parts: the same failover ladder,
        splice verification, and tracer span, with the downstream
        FETCH riding the wire loop (no reader thread per open
        stream). Blocking failure-path helpers (reconcile, downstream
        status, resubmit) run on the default executor - they are rare
        and must not starve the bounded verb-dispatch pool."""
        loop = asyncio.get_running_loop()
        rq = self.get(external_id)
        if rq.splice_broken:
            raise ServiceError(_SPLICE_ERR)
        await loop.run_in_executor(None, self._await_reconcile, rq)
        sent = 0
        cycles = 0
        max_cycles = 3 + self.max_resubmits \
            + len(self.registry.replicas)
        stream_t0 = time.monotonic()
        completed = False
        try:
            while True:
                gen = rq.generation
                replica = self.registry.get(rq.replica_id or "")
                if replica is None:
                    raise ServiceError(
                        f"UNKNOWN: no replica for {external_id}"
                    )
                try:
                    agen = self._raw_fetch_async(
                        replica, rq.internal_id, timeout_ms
                    )
                    try:
                        i = -1
                        async for payload in agen:
                            i += 1
                            if self._splice_note(rq, i, payload):
                                raise ServiceError(_SPLICE_ERR)
                            if i < sent:
                                continue  # delivered on this stream
                            sent += 1
                            yield payload
                    finally:
                        try:
                            await agen.aclose()
                        except Exception:  # noqa: BLE001 - teardown
                            pass
                    completed = True
                    await loop.run_in_executor(
                        None, self._finish, rq, "DONE"
                    )
                    return
                except ServiceError as e:
                    if rq.splice_broken:
                        await loop.run_in_executor(
                            None, self._finish, rq, "FAILED"
                        )
                        raise
                    cycles += 1
                    if cycles > max_cycles:
                        raise
                    if e.state == "FAILED":
                        st = await loop.run_in_executor(
                            None, self._downstream_status, rq
                        )
                        if st.get("state") == "FAILED" \
                                and not rq.finished:
                            st = await loop.run_in_executor(
                                None, self._observe_failed, rq, st
                            )
                        if st.get("state") == "FAILED" or rq.finished:
                            await loop.run_in_executor(
                                None, self._finish, rq,
                                st.get("state"),
                            )
                            raise
                        continue  # re-routed or retrying: fetch again
                    if e.state == "UNKNOWN":
                        moved = await loop.run_in_executor(
                            None,
                            partial(self._resubmit, rq, gen,
                                    same_replica=False, exclude=set(),
                                    counter="failovers"),
                        )
                        if moved:
                            continue
                    raise
                except (ConnectionError, OSError) as e:
                    cycles += 1
                    if cycles > max_cycles:
                        raise
                    if rq.generation != gen:
                        continue  # death callback already moved it
                    self.breaker.note_fatal(
                        replica.replica_id, kind="transport"
                    )
                    if replica.routable():
                        continue  # transient drop: re-FETCH same
                    moved = await loop.run_in_executor(
                        None,
                        partial(self._resubmit, rq, gen,
                                same_replica=False,
                                exclude={replica.replica_id},
                                counter="failovers"),
                    )
                    if not moved:
                        raise ReplicaUnavailableError(
                            f"replica {replica.replica_id} lost "
                            f"mid-FETCH of {external_id}: {e!r}"
                        ) from e
        finally:
            if rq.tracer is not None:
                tags = {"parts": sent}
                if cycles:
                    tags["resumes"] = cycles
                if not completed:
                    tags["aborted"] = True
                try:
                    rq.tracer.record_span(
                        "router_stream", stream_t0,
                        time.monotonic(), **tags,
                    )
                except Exception:  # noqa: BLE001 - obs must not raise
                    pass

    async def _raw_fetch_async(self, replica: Replica,
                               internal_id: str, timeout_ms: int):
        """One downstream FETCH on the wire loop. The credit window is
        an asyncio.Queue filled by a reader coroutine (the threaded
        tier's reader THREAD, without the thread); window<=1 keeps the
        strictly-serial path. Same budget gates, same counters."""
        from blaze_tpu.runtime.gateway import _FLAG_SERVICE
        from blaze_tpu.service.wire import ServiceClient

        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(replica.host, replica.port),
                timeout=min(self.downstream_timeout_s, 10.0),
            )
        except asyncio.TimeoutError as e:
            raise ConnectionError(
                f"connect to {replica.replica_id} timed out"
            ) from e
        pending = [0]
        fill_task = None
        try:
            writer.write(
                _U64.pack(_FLAG_SERVICE)
                + ServiceClient._id_verb(
                    VERB_FETCH, internal_id, timeout_ms
                )
            )
            await writer.drain()
            if self.stream_window <= 1:
                while True:
                    (length,) = _U64.unpack(
                        await self._recv_checked_async(
                            reader, _U64.size, replica
                        )
                    )
                    if length == 0:
                        return
                    if length == _ERR:
                        (mlen,) = _U32.unpack(
                            await self._recv_checked_async(
                                reader, _U32.size, replica
                            )
                        )
                        raise ServiceError(
                            (await self._recv_checked_async(
                                reader, mlen, replica
                            )).decode("utf-8")
                        )
                    yield await self._recv_checked_async(
                        reader, length, replica
                    )
            window: asyncio.Queue = asyncio.Queue(
                maxsize=self.stream_window
            )

            async def _fill():
                try:
                    while True:
                        (length,) = _U64.unpack(
                            await self._recv_checked_async(
                                reader, _U64.size, replica
                            )
                        )
                        if length == 0:
                            await window.put(("end", None))
                            return
                        if length == _ERR:
                            (mlen,) = _U32.unpack(
                                await self._recv_checked_async(
                                    reader, _U32.size, replica
                                )
                            )
                            msg = (await self._recv_checked_async(
                                reader, mlen, replica
                            )).decode("utf-8")
                            await window.put(
                                ("err", ServiceError(msg))
                            )
                            return
                        payload = await self._recv_checked_async(
                            reader, length, replica
                        )
                        total_waited = False
                        while not self._relay_admit(
                            len(payload), pending
                        ):
                            if not total_waited:
                                total_waited = True
                                with self._lock:
                                    self.counters[
                                        "stream_total_waits"
                                    ] += 1
                            await asyncio.sleep(0.02)
                        if window.full():
                            with self._lock:
                                self.counters[
                                    "stream_window_waits"
                                ] += 1
                        await window.put(("part", payload))
                except asyncio.CancelledError:
                    raise
                except BaseException as e:  # noqa: BLE001 - relayed
                    await window.put(("err", e))

            fill_task = asyncio.get_running_loop().create_task(
                _fill()
            )
            while True:
                kind, payload = await window.get()
                if kind == "part":
                    self._relay_release(len(payload), pending)
                    yield payload
                elif kind == "end":
                    return
                else:
                    raise payload
        finally:
            if fill_task is not None:
                fill_task.cancel()
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            # consumer done: whatever this stream still attributes to
            # the gauge is residual - drop it
            with self._stream_buffered_mu:
                self._stream_buffered -= pending[0]
                pending[0] = 0

    async def _recv_checked_async(self, reader, n: int,
                                  replica: Replica) -> bytes:
        """Async twin of _recv_checked: fetch_block_s read slices,
        aborting promptly when the replica goes unroutable mid-wait,
        with the same mid-frame stall bound."""
        buf = bytearray()
        stalled = 0
        max_midframe = max(4, int(60.0 / self.fetch_block_s))
        while len(buf) < n:
            try:
                chunk = await asyncio.wait_for(
                    reader.read(n - len(buf)), self.fetch_block_s
                )
            except asyncio.TimeoutError:
                if not replica.routable():
                    raise ConnectionError(
                        f"replica {replica.replica_id} unroutable "
                        "mid-FETCH"
                    ) from None
                if buf:
                    stalled += 1
                    if stalled > max_midframe:
                        raise ConnectionError(
                            "mid-frame stall from "
                            f"{replica.replica_id}"
                        ) from None
                continue
            if not chunk:
                raise ConnectionError("EOF from replica mid-FETCH")
            stalled = 0
            buf += chunk
        return bytes(buf)


# ---------------------------------------------------------------------------
# wire tier: the router as a service-protocol server
# ---------------------------------------------------------------------------


class RouterVerbBackend:
    """The Router behind the shared verb loop
    (service/wire.serve_verb_connection): the same protocol skeleton
    as a single serve instance with the routing table behind every
    verb - plus MEMBER, where the router is the fleet's membership
    authority (a bare serve instance answers it with an in-band
    error). Non-detached queries submitted on a connection are
    cancelled (on their replicas) when the client vanishes."""

    tier = "router"  # wire-latency / scrape-cost metric label

    def __init__(self, router: Router):
        self.router = router

    def submit(self, meta: dict, task_bytes: bytes, is_ref: bool,
               manifest_bytes: Optional[bytes]) -> dict:
        return self.router.submit(
            meta, task_bytes, is_ref=is_ref,
            manifest_bytes=manifest_bytes,
        )

    def poll(self, qid: str) -> dict:
        return self.router.poll(qid)

    def cancel(self, qid: str) -> dict:
        return self.router.cancel(qid)

    def report_frame(self, qid: str, flags: int) -> dict:
        return self.router.report(qid, flags)

    def stats(self) -> dict:
        return self.router.stats()

    def metrics_frame(self) -> dict:
        return {"metrics": self.router.metrics()}

    def member_frame(self, payload: dict) -> dict:
        return self.router.membership(payload)

    def mesh_exchange_frame(self, payload: dict, parts: list):
        # claim plane only: the router never carries stage data (the
        # input parts were drained by the wire layer and are ignored)
        return self.router.mesh_exchange(payload), []

    def profile_frame(self, payload: dict) -> dict:
        from blaze_tpu.service.wire import handle_profile_frame

        return handle_profile_frame(self.tier, payload)

    def abandon(self, qid: str) -> None:
        try:
            rq = self.router.get(qid)
        except KeyError:
            return
        if not rq.finished:
            self.router.cancel(qid)

    def fetch(self, sock, qid: str, timeout_ms: int) -> None:
        router = self.router
        sent = 0
        # slow-consumer protection at the relay: a client that stops
        # draining for stream_stall_s holds a downstream stream (and
        # its replica-side buffer bytes) hostage - abort THIS relay
        # only. The downstream ring keeps the parts; a re-FETCH
        # resumes. Never a breaker strike: the replica did nothing
        # wrong, so the abort stays off the failover ladder entirely
        # (ConnectionError from OUR send is not caught below).
        stall_s = router.stream_stall_s
        prev_timeout = sock.gettimeout()
        if stall_s > 0:
            sock.settimeout(stall_s)
        try:
            for payload in router.stream_parts(qid, timeout_ms):
                try:
                    sock.sendall(_U64.pack(len(payload)) + payload)
                except (socket.timeout, TimeoutError) as e:
                    with router._lock:
                        router.counters["stream_stalls"] += 1
                    raise ConnectionError(
                        f"relay send stalled past {stall_s}s "
                        f"for {qid}"
                    ) from e
                sent += 1
            sock.sendall(_U64.pack(0))
        except KeyError:
            if sent:
                raise ConnectionError(
                    "fetch aborted after parts sent"
                )
            _send_err(sock, f"UNKNOWN: no query {qid}")
        except (ServiceError, ReplicaUnavailableError) as e:
            if sent:
                # parts are on the wire: a JSON/ERR frame would
                # desync the client - abort the connection (its
                # reconnect re-FETCHes)
                raise ConnectionError(
                    f"fetch stream aborted: {e!r}"
                ) from e
            msg = str(e)
            if isinstance(e, ReplicaUnavailableError):
                # ERR frames carry "STATE: detail"
                # (ServiceError.state splits on the first colon) -
                # raw text here would parse to a garbage state like
                # "replica 127.0.0.1". Stamp the router's
                # fleet-unavailable convention (same as the submit
                # path: retry with backoff once capacity returns)
                msg = f"REJECTED_OVERLOADED: {msg}"
            _send_err(sock, msg)
        finally:
            if stall_s > 0:
                try:
                    sock.settimeout(prev_timeout)
                except OSError:
                    pass  # connection already torn down

    async def fetch_async(self, writer, qid: str,
                          timeout_ms: int) -> None:
        """Event-loop relay FETCH: same ladder as fetch(), with the
        slow-client stall enforced by a drain timeout (the coroutine
        parks, not an OS thread)."""
        router = self.router
        stall_s = router.stream_stall_s
        sent = 0
        agen = router.stream_parts_async(qid, timeout_ms)
        try:
            try:
                async for payload in agen:
                    writer.write(_U64.pack(len(payload)) + payload)
                    try:
                        if stall_s > 0:
                            await asyncio.wait_for(
                                writer.drain(), stall_s
                            )
                        else:
                            await writer.drain()
                    except asyncio.TimeoutError as e:
                        with router._lock:
                            router.counters["stream_stalls"] += 1
                        raise ConnectionError(
                            f"relay send stalled past {stall_s}s "
                            f"for {qid}"
                        ) from e
                    sent += 1
                writer.write(_U64.pack(0))
                await writer.drain()
            except KeyError:
                if sent:
                    raise ConnectionError(
                        "fetch aborted after parts sent"
                    ) from None
                from blaze_tpu.service.wire_async import _send_err \
                    as _send_err_async

                await _send_err_async(
                    writer, f"UNKNOWN: no query {qid}"
                )
            except (ServiceError, ReplicaUnavailableError) as e:
                if sent:
                    raise ConnectionError(
                        f"fetch stream aborted: {e!r}"
                    ) from e
                msg = str(e)
                if isinstance(e, ReplicaUnavailableError):
                    msg = f"REJECTED_OVERLOADED: {msg}"
                from blaze_tpu.service.wire_async import _send_err \
                    as _send_err_async

                await _send_err_async(writer, msg)
        finally:
            try:
                await agen.aclose()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


def handle_router_connection(sock, router: Router) -> None:
    """Drive one client connection against the router through the
    SHARED table-driven verb loop (service/wire.py) - one skeleton for
    both protocol speakers, so framing and error handling cannot drift
    between tiers."""
    from blaze_tpu.service.wire import serve_verb_connection

    serve_verb_connection(sock, RouterVerbBackend(router))


class _RouterHandler(socketserver.BaseRequestHandler):
    def handle(self):
        from blaze_tpu.runtime.gateway import _FLAG_SERVICE
        from blaze_tpu.runtime.transport import _recv_exact

        sock = self.request
        try:
            (header,) = _U64.unpack(_recv_exact(sock, _U64.size))
        except Exception:  # noqa: BLE001 - never spoke
            return
        if not header & _FLAG_SERVICE:
            msg = b"router speaks the service protocol only"
            try:
                sock.sendall(
                    _U64.pack(_ERR) + _U32.pack(len(msg)) + msg
                )
            except OSError:
                pass
            return
        handle_router_connection(sock, self.server.router)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RouterServer:
    """TCP front for a Router: ServiceClient-compatible listener.
    `wire` picks the data plane exactly like TaskGatewayServer:
    "async" (event-loop relay, the default) or "threaded" (the legacy
    thread-per-connection front, the differential oracle). BLAZE_WIRE
    overrides the default."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0, wire: Optional[str] = None):
        if wire is None:
            wire = os.environ.get("BLAZE_WIRE", "async")
        if wire not in ("async", "threaded"):
            raise ValueError(f"unknown wire mode {wire!r}")
        self.wire = wire
        self.router = router
        self._srv = None
        self._async = None
        self._thread = None
        if wire == "threaded":
            self._srv = _Server((host, port), _RouterHandler)
            self._srv.router = router
            self._thread = threading.Thread(
                target=self._srv.serve_forever, daemon=True,
                name="blaze-router-accept",
            )
        else:
            from blaze_tpu.service import wire_async

            self._async = wire_async.AsyncWireServer(
                host, port, self._handle_async
            )

    async def _handle_async(self, conn):
        from blaze_tpu.service import wire_async

        router = self.router
        await wire_async.handle_wire_connection(
            conn,
            backend_factory=lambda: RouterVerbBackend(router),
            legacy=None,
        )

    @property
    def address(self):
        if self._async is not None:
            return self._async.address
        return self._srv.server_address

    def start(self) -> "RouterServer":
        if self._async is not None:
            self._async.start()
        else:
            self._thread.start()
        return self

    def serve_blocking(self) -> None:
        if self._async is not None:
            self._async.serve_blocking()
        else:
            self._srv.serve_forever()

    def stop(self) -> None:
        if self._async is not None:
            self._async.stop()
        else:
            self._srv.shutdown()
            self._srv.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def route_forever(host: str, port: int, replicas, wire=None,
                  **router_kw) -> None:  # pragma: no cover - CLI
    router = Router(replicas, **router_kw)
    try:
        router.registry.poll_now()  # startup probe: log who answers
        alive = [
            r.replica_id
            for r in router.registry.replicas.values() if r.alive
        ]
        srv = RouterServer(router, host, port, wire=wire)
        print(
            f"blaze_tpu router listening on {srv.address} -> "
            f"{len(alive)}/{len(router.registry.replicas)} replicas "
            f"alive {alive}",
            flush=True,
        )
        srv.serve_blocking()
    finally:
        router.close()
