"""Replica router: N `serve` instances behind one service endpoint.

The Spark-driver-analog layer above per-replica engines (SURVEY 2.2;
Flare's scheduler-fronting-heterogeneous-executors shape, PAPERS.md):
a `ServiceClient` talks to the router exactly as it talks to a single
`python -m blaze_tpu serve` instance, and the router owns

  membership  - registry.py: DYNAMIC fleet membership (JOIN/LEAVE over
                the MEMBER wire verb; membership.py announces from the
                replica side, the --replica list is only a bootstrap
                hint) with STATS-poll heartbeats under the
                cluster-runner Liveness window; per-replica health,
                drain state, quarantine, Prometheus gauges
  replication - replication.py: the top-K hot fingerprints get a
                confirmed second ResultCache copy, promoted to the
                affinity home when the first replica departs
  placement   - placement.py: plan-fingerprint affinity (repeats hit
                the replica whose ResultCache holds the result - zero
                dispatches), then headroom-fits-estimated-cost, then a
                bounded-staleness least-loaded fallback
  failover    - failover.py: the PR 3 error taxonomy consumed one tier
                up (TRANSIENT re-submits same-replica with backoff,
                fatal classes strike a per-replica circuit breaker,
                heartbeat death re-routes in-flight queries)
  proxy       - proxy.py: verb forwarding with query-id rewriting and
                raw segmented-IPC FETCH passthrough (zero decode at
                the router), fleet-aggregating STATS/METRICS

Code map details in docs/ROUTER.md; `python -m blaze_tpu route` is the
CLI entry.
"""

from blaze_tpu.router.failover import CircuitBreaker, failover_action
from blaze_tpu.router.membership import MembershipAnnouncer
from blaze_tpu.router.placement import (
    AffinityMap,
    affinity_key,
    choose_replica,
)
from blaze_tpu.router.proxy import (
    RoutedQuery,
    Router,
    RouterServer,
    handle_router_connection,
)
from blaze_tpu.router.registry import Replica, ReplicaRegistry
from blaze_tpu.router.replication import HotReplicator

__all__ = [
    "AffinityMap",
    "CircuitBreaker",
    "HotReplicator",
    "MembershipAnnouncer",
    "Replica",
    "ReplicaRegistry",
    "RoutedQuery",
    "Router",
    "RouterServer",
    "affinity_key",
    "choose_replica",
    "failover_action",
    "handle_router_connection",
]
