"""Durable routing journal: the router's crash-safety substrate.

PR 8 made every replica disposable, which left the router as the
fleet's last single point of failure: a restarted router re-learns
MEMBERSHIP within one announcer tick, but every in-flight routed
query was forgotten - even though its downstream run is detach=True
and keeps executing on the replica. The source paper's Spark lineage
makes the driver recoverable by re-spooling work from retained state;
this module is the retained state.

The journal is an append-only record of each routed query's
lifecycle, written from the router's verb paths and replayed by a
restarting router (router/proxy.py `Router._recover_*`):

  S  SUBMIT    admission: client query_id + meta + the raw task bytes
               (enough to re-place the query from scratch)
  P  PLACE     a placement landing: replica_id + replica-local
               internal_id (+ learned fingerprint) - written every
               time `_place_and_submit` succeeds, so failover moves
               journal as newer P records for the same id
  F  FINISH    terminal state: a truncation marker - replay drops the
               entry, and compaction reclaims its bytes

Durability model: appends go straight to the OS (unbuffered
`os.write` on a raw fd), fsync is BATCHED from a flusher thread every
`fsync_interval_s`. A router SIGKILL therefore loses nothing (the
page cache survives process death on one host); only a host power
loss can drop the tail since the last fsync - and replay treats any
torn or unparseable tail as the crash point, truncating to the last
whole record instead of refusing to start. Each record is framed
`u32 len | u32 crc32 | payload` so a half-written final record is
detected by length or checksum, never misparsed.

Compaction: replay-time (a restart rewrites only the live entries)
and opportunistic from the flusher once the file accumulates more
dead records than live ones - the journal's steady-state size is
O(in-flight queries), not O(queries ever routed).

Chaos seam `router.journal` (testing/chaos.py): op="append" (a DROP
fault tears the record mid-write - the crash-at-the-worst-moment
test), op="fsync" (STALL = a slow disk under the flusher), and
op="reconcile_poll" fired by the recovery pass in proxy.py (DROP = a
reconcile POLL that never reaches the replica; the pass retries on
its next tick).

Depth/replay health is exported as `blaze_router_journal_*` metrics
through the process registry.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import struct
import threading
import zlib
from base64 import b64decode, b64encode
from typing import Dict, Optional, Tuple

from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.testing import chaos

log = logging.getLogger("blaze_tpu.router")

_HDR = struct.Struct("<II")  # payload length, crc32(payload)
# a length field above this is framing corruption, not a real record
_MAX_RECORD = 256 << 20


@dataclasses.dataclass
class JournalEntry:
    """One live routed query reconstructed by replay."""

    external_id: str
    key: str
    meta: dict
    task_bytes: bytes
    is_ref: bool
    manifest_bytes: Optional[bytes]
    replica_id: Optional[str] = None
    internal_id: Optional[str] = None
    fingerprint: Optional[str] = None
    generation: int = 0

    @property
    def placed(self) -> bool:
        return self.internal_id is not None


class RouterJournal:
    """Append-only, fsync-batched lifecycle journal with torn-tail
    tolerant replay. Thread-safe: verb handlers append concurrently;
    the flusher thread owns fsync and opportunistic compaction."""

    def __init__(self, path: str, fsync_interval_s: float = 0.05,
                 compact_min_records: int = 1024):
        self.path = path
        self.fsync_interval_s = float(fsync_interval_s)
        self.compact_min_records = int(compact_min_records)
        self._lock = threading.Lock()
        self._dirty = False
        self._closed = False
        # replay BEFORE opening for append: a torn tail is truncated
        # so the next append extends a well-framed file
        self.replayed, truncated = self.replay_file(path)
        if truncated is not None:
            REGISTRY.inc("blaze_router_journal_truncations_total")
            log.warning(
                "journal %s: torn tail truncated at byte %d "
                "(%d live entries survive)",
                path, truncated, len(self.replayed),
            )
            with open(path, "r+b") as f:
                f.truncate(truncated)
        REGISTRY.inc("blaze_router_journal_replayed_total",
                     len(self.replayed))
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT
                           | os.O_APPEND, 0o644)
        # live-entry tracking for the depth gauge + compaction
        # trigger; replayed entries count as live until finished
        self._live = set(self.replayed)
        self._records = 0   # appended since open/compaction
        self._dead = 0      # F-marked among them
        self._collector_key = f"router-journal:{id(self):x}"
        REGISTRY.register_collector(
            self._collector_key, self._collect_metrics
        )
        # startup compaction: a restart inherits every dead record of
        # the previous life - rewrite only what replay kept alive
        if self.replayed or os.path.getsize(path) > 0:
            self._compact_locked()
        self._stop_wait = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, daemon=True,
            name="blaze-router-journal-flush",
        )
        self._flusher.start()

    # -- replay ----------------------------------------------------------
    @staticmethod
    def replay_file(path: str
                    ) -> Tuple[Dict[str, JournalEntry],
                               Optional[int]]:
        """Replay `path` into {external_id: JournalEntry} of LIVE
        queries (F records drop their entry). Returns (entries,
        torn_offset) where torn_offset is the byte offset of the
        first unreadable record (None = clean tail). Idempotent by
        construction: replaying the same bytes always yields the
        same entries."""
        entries: Dict[str, JournalEntry] = {}
        if not os.path.exists(path):
            return entries, None
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        torn: Optional[int] = None
        n = len(data)
        while off < n:
            if off + _HDR.size > n:
                torn = off
                break
            length, crc = _HDR.unpack_from(data, off)
            if length > _MAX_RECORD or off + _HDR.size + length > n:
                torn = off
                break
            payload = data[off + _HDR.size: off + _HDR.size + length]
            if zlib.crc32(payload) != crc:
                # checksum mismatch = the crash point; framing after
                # it cannot be trusted either
                torn = off
                break
            off += _HDR.size + length
            try:
                rec = json.loads(payload)
            except ValueError:
                torn = off - _HDR.size - length
                break
            kind = rec.get("k")
            qid = rec.get("id")
            if not qid:
                continue
            if kind == "S":
                entries[qid] = JournalEntry(
                    external_id=qid,
                    key=str(rec.get("key", "")),
                    meta=dict(rec.get("meta") or {}),
                    task_bytes=b64decode(rec.get("blob", "")),
                    is_ref=bool(rec.get("ref")),
                    manifest_bytes=(
                        b64decode(rec["man"])
                        if rec.get("man") is not None else None
                    ),
                )
            elif kind == "P":
                e = entries.get(qid)
                if e is not None:
                    e.replica_id = rec.get("r")
                    e.internal_id = rec.get("iid")
                    e.fingerprint = rec.get("fp") or e.fingerprint
                    e.generation = int(rec.get("gen", 0))
            elif kind == "F":
                entries.pop(qid, None)
        return entries, torn

    # -- record encoding (THE dict shapes; replay_file is the decoder,
    # and compaction re-emits through these same builders so the field
    # sets cannot drift between the append and rewrite paths) --------
    @staticmethod
    def _submit_record(external_id: str, key: str, meta: dict,
                       task_bytes: bytes, is_ref: bool,
                       manifest_bytes: Optional[bytes]) -> dict:
        return {
            "id": external_id,
            "key": key,
            "meta": meta,
            "blob": b64encode(task_bytes).decode("ascii"),
            "ref": bool(is_ref),
            "man": (b64encode(manifest_bytes).decode("ascii")
                    if manifest_bytes is not None else None),
        }

    @staticmethod
    def _place_record(external_id: str, replica_id: str,
                      internal_id: str, fingerprint: Optional[str],
                      generation: int) -> dict:
        return {
            "id": external_id,
            "r": replica_id,
            "iid": internal_id,
            "fp": fingerprint,
            "gen": int(generation),
        }

    @staticmethod
    def _encode_frame(kind: str, rec: dict) -> bytes:
        rec["k"] = kind
        payload = json.dumps(rec, separators=(",", ":")).encode()
        return _HDR.pack(len(payload), zlib.crc32(payload)) + payload

    # -- append paths ----------------------------------------------------
    def record_submit(self, external_id: str, key: str, meta: dict,
                      task_bytes: bytes, is_ref: bool,
                      manifest_bytes: Optional[bytes]) -> None:
        self._append("S", self._submit_record(
            external_id, key, meta, task_bytes, is_ref,
            manifest_bytes,
        ), live=external_id)

    def record_place(self, external_id: str, replica_id: str,
                     internal_id: str,
                     fingerprint: Optional[str],
                     generation: int) -> None:
        self._append("P", self._place_record(
            external_id, replica_id, internal_id, fingerprint,
            generation,
        ))

    def record_finish(self, external_id: str, state: str) -> None:
        self._append("F", {"id": external_id, "st": state},
                     dead=external_id)

    def _append(self, kind: str, rec: dict,
                live: Optional[str] = None,
                dead: Optional[str] = None) -> None:
        frame = self._encode_frame(kind, rec)
        with self._lock:
            if self._closed:
                return
            torn = False
            if chaos.ACTIVE:
                # DROP = the process dies mid-write: only part of the
                # frame reaches the file (the torn-tail replay path);
                # STALL = slow disk under the appender
                try:
                    chaos.fire("router.journal", op="append",
                               kind=kind, id=rec.get("id"))
                except ConnectionError:
                    torn = True
            if torn:
                os.write(self._fd, frame[: max(1, len(frame) // 2)])
            else:
                os.write(self._fd, frame)
            self._dirty = True
            self._records += 1
            if live is not None:
                self._live.add(live)
            if dead is not None:
                self._live.discard(dead)
                self._dead += 1
        REGISTRY.inc("blaze_router_journal_records_total", kind=kind)

    # -- fsync batching / compaction -------------------------------------
    def sync(self) -> None:
        """Force one fsync (tests and close; the steady-state path is
        the batched flusher)."""
        with self._lock:
            self._fsync_locked()

    def _fsync_locked(self) -> None:
        if self._closed or not self._dirty:
            return
        if chaos.ACTIVE:
            chaos.fire("router.journal", op="fsync")
        os.fsync(self._fd)
        self._dirty = False
        REGISTRY.inc("blaze_router_journal_fsyncs_total")

    def _flush_loop(self) -> None:
        while not self._closed:
            try:
                with self._lock:
                    self._fsync_locked()
                    # opportunistic compaction: more dead weight than
                    # live entries and enough volume to matter
                    if (self._records >= self.compact_min_records
                            and self._dead > max(1, len(self._live))):
                        self._compact_locked()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("journal flush failed")
            if self._stop_wait.wait(self.fsync_interval_s):
                return

    def _compact_locked(self) -> None:
        """Rewrite only the LIVE entries (their S + last P) into a tmp
        file, fsync, and atomically swap it in. Caller holds _lock."""
        live, _ = self.replay_file(self.path)
        # include records buffered since the last fsync: replay reads
        # the file, and O_APPEND writes land immediately, so this is
        # simply "the current file state" - flush first regardless
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for e in live.values():
                f.write(self._encode_frame("S", self._submit_record(
                    e.external_id, e.key, e.meta, e.task_bytes,
                    e.is_ref, e.manifest_bytes,
                )))
                if e.placed:
                    f.write(self._encode_frame(
                        "P", self._place_record(
                            e.external_id, e.replica_id,
                            e.internal_id, e.fingerprint,
                            e.generation,
                        ),
                    ))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        old_fd = self._fd
        self._fd = os.open(self.path, os.O_WRONLY | os.O_CREAT
                           | os.O_APPEND, 0o644)
        try:
            os.close(old_fd)
        except OSError:
            pass
        self._live = set(live)
        self._records = len(live)
        self._dead = 0
        self._dirty = False
        REGISTRY.inc("blaze_router_journal_compactions_total")

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            try:
                self._fsync_locked()
            except OSError:
                pass
            self._closed = True
        self._stop_wait.set()
        if self._flusher.is_alive():
            self._flusher.join(timeout=5)
        REGISTRY.unregister_collector(self._collector_key)
        try:
            os.close(self._fd)
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- exposition ------------------------------------------------------
    def _collect_metrics(self):
        with self._lock:
            live = len(self._live)
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        return [
            ("blaze_router_journal_live_entries", {}, live, "gauge"),
            ("blaze_router_journal_bytes", {}, size, "gauge"),
        ]
