"""Replica membership: who is alive, and what shape they are in.

The router's view of the fleet is built from exactly the surfaces the
replicas already expose - no new RPC:

  * liveness: the cluster-runner contract (runtime/cluster.py
    `Liveness`) applied to STATS polls. A successful poll is the
    heartbeat; a replica is DEAD only when no poll has succeeded
    within the window - progress-aware, so a slow replica (cold
    compile, long scan) is never declared dead while it still answers.
  * shape: the structured STATS payload (ISSUE 4) - admission headroom
    and queue depth, per-fingerprint runtime-history p50s, cache
    counters. Placement (router/placement.py) reads the last snapshot
    with a bounded-staleness rule instead of polling inline on the
    submit path.
  * quarantine: the failover tier (router/failover.py circuit breaker,
    or heartbeat death) marks a replica unroutable for a cool-off
    window; after it the replica is half-open - the next successful
    STATS poll readmits it.

Per-replica state is exported through the process metrics registry as
`blaze_router_replica_*{replica=...}` gauges, so the fleet view rides
the existing Prometheus exposition.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from blaze_tpu.obs.contention import TimedLock
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.runtime.cluster import Liveness

log = logging.getLogger("blaze_tpu.router")


class Replica:
    """One serve instance: address + last-known shape + health.

    Membership lifecycle (docs/ROUTER.md):

        joining -> alive <-> quarantined
                     |  \\-> draining -> gone (LEAVE)
                     '-> gone (LEAVE / removal)

    `membership_state()` derives the label STATS and the
    `blaze_router_replica_membership` gauge expose."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = int(port)
        self.replica_id = f"{host}:{port}"
        self.liveness = Liveness(clock=time.monotonic)
        self.alive = False          # becomes True on the first OK poll
        self.ever_alive = False
        self.stats: Optional[dict] = None
        self.stats_at: float = 0.0  # monotonic time of last snapshot
        self.quarantined_until: float = 0.0
        self.quarantine_reason: Optional[str] = None
        self.poll_failures = 0      # consecutive
        self.in_flight = 0          # router-tracked live routed queries
        # advertised accelerator count (JOIN payload "devices"): the
        # fleet mesh ledger sizes the claimable device pool from these
        self.devices = 1
        # DRAINING (rolling restart): announced through the replica's
        # STATS `service.draining` flag, or observed directly from a
        # DRAINING submit rejection - either way NEW placements stop
        # while in-flight POLL/FETCH keep working
        self.draining = False
        # set when the replica LEFT (or was removed): the record may
        # linger in the registry's departed ring for STATS visibility
        self.departed = False
        self._client = None         # poll-loop ServiceClient
        self._lock = threading.Lock()
        # serializes whole poll round trips (the background loop vs. a
        # manual poll_now startup probe): ServiceClient is NOT
        # thread-safe - two threads recv-ing one socket steal each
        # other's frames. Never taken by the verb hot paths.
        self._poll_lock = threading.Lock()
        # per-replica poller shutdown: dynamic membership stops ONE
        # replica's poller on LEAVE without a registry-wide barrier
        self._stop = threading.Event()

    def note_routed(self) -> None:
        """Count one routed query (locked: submit handlers race)."""
        with self._lock:
            self.in_flight += 1

    def note_unrouted(self) -> None:
        with self._lock:
            self.in_flight = max(0, self.in_flight - 1)

    # -- derived views ---------------------------------------------------
    def quarantined(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.monotonic()) \
            < self.quarantined_until

    def routable(self, now: Optional[float] = None) -> bool:
        # draining replicas keep answering POLL/FETCH for in-flight
        # queries but take no NEW placements
        return (
            self.alive and not self.quarantined(now)
            and not self.draining and not self.departed
        )

    def membership_state(self, now: Optional[float] = None) -> str:
        """joining | alive | draining | quarantined | gone - the
        membership label on STATS snapshots and the
        blaze_router_replica_membership gauge."""
        if self.departed:
            return "gone"
        if self.draining and self.alive:
            return "draining"
        if self.quarantined(now) or (self.ever_alive
                                     and not self.alive):
            # breaker-open, OR heartbeat-dead (still dead past the
            # quarantine window = still effectively quarantined; the
            # next successful poll revives it)
            return "quarantined"
        if self.alive:
            return "alive"
        return "joining"

    def stats_age_s(self, now: Optional[float] = None) -> float:
        if self.stats is None:
            return float("inf")
        return (now if now is not None else time.monotonic()) \
            - self.stats_at

    def effective_headroom(self) -> Optional[int]:
        """Device bytes this replica could admit right now: reported
        tracker headroom minus what admitted queries already reserved.
        None when no STATS snapshot exists yet."""
        if self.stats is None:
            return None
        a = self.stats.get("admission", {})
        return int(a.get("headroom", 0)) - int(
            a.get("reserved_bytes", 0)
        )

    def load(self) -> int:
        """Queue pressure: replica-reported queued+running plus the
        router's own in-flight count (covers submits the next STATS
        poll has not seen yet)."""
        q = r = 0
        if self.stats is not None:
            a = self.stats.get("admission", {})
            q, r = int(a.get("queued", 0)), int(a.get("running", 0))
        return q + r + max(0, self.in_flight - q - r)

    def fingerprint_p50(self, fingerprint: str) -> Optional[float]:
        """This replica's reported runtime-history p50 for a full
        fingerprint (joined on the `fp` field STATS carries)."""
        if self.stats is None or not fingerprint:
            return None
        for e in self.stats.get("runtime_history", {}).get("top", ()):
            if e.get("fp") == fingerprint and "p50" in e:
                return float(e["p50"])
        return None

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = now if now is not None else time.monotonic()
        out = {
            "alive": self.alive,
            "state": self.membership_state(now),
            "quarantined": self.quarantined(now),
            "in_flight": self.in_flight,
            "poll_failures": self.poll_failures,
            "stats_age_s": (
                round(self.stats_age_s(now), 3)
                if self.stats is not None else None
            ),
        }
        if self.quarantine_reason and self.quarantined(now):
            out["quarantine_reason"] = self.quarantine_reason
        if self.stats is not None:
            a = self.stats.get("admission", {})
            out["queued"] = a.get("queued", 0)
            out["running"] = a.get("running", 0)
            out["headroom"] = self.effective_headroom()
        return out


def parse_replica(spec) -> Tuple[str, int]:
    """'host:port' | (host, port) -> (host, port)."""
    if isinstance(spec, (tuple, list)):
        return str(spec[0]), int(spec[1])
    host, _, port = str(spec).rpartition(":")
    if not host:
        raise ValueError(f"replica spec {spec!r} is not host:port")
    return host, int(port)


class ReplicaRegistry:
    """Membership + health, fed by PERSISTENT per-replica pollers.

    `start()` spawns one long-lived poller thread per replica, each
    polling STATS every `poll_interval_s` (per-poll latency lands in
    the `blaze_router_poll_round_seconds{replica=...}` histogram);
    `poll_now()` runs one synchronous round for tests and the CLI's
    startup probe. Death and revival fire the registered callbacks
    exactly once per transition - the router uses on_dead to re-route
    a dead replica's in-flight queries.

    Membership is DYNAMIC (ROADMAP item 4): `add()` registers a
    JOINing replica (spinning up its poller when the registry is
    started) and `remove()` retires a LEAVing one (its poller stops at
    the next tick, its record moves to the bounded `departed` ring for
    STATS visibility). The constructor's replica list is only the
    BOOTSTRAP hint - the fleet the router actually routes to is
    whatever joined minus whatever left. Every membership transition
    lands on the `blaze_router_membership_events{kind=...}` counter."""

    def __init__(
        self,
        replicas: Sequence,
        poll_interval_s: float = 1.0,
        heartbeat_timeout_s: float = 5.0,
        quarantine_s: float = 15.0,
        connect_timeout_s: float = 5.0,
        on_dead: Optional[Callable[[Replica], None]] = None,
        on_revive: Optional[Callable[[Replica], None]] = None,
    ):
        self.replicas: Dict[str, Replica] = {}
        for spec in replicas:
            host, port = parse_replica(spec)
            r = Replica(host, port)
            self.replicas[r.replica_id] = r
        self.poll_interval_s = float(poll_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.quarantine_s = float(quarantine_s)
        # a poll slower than the liveness window is useless - and with
        # the default connect timeout ABOVE the default heartbeat
        # window, the advertised death-detection latency would be
        # unachievable against a black-holing host
        self.connect_timeout_s = min(
            float(connect_timeout_s),
            max(0.5, float(heartbeat_timeout_s)),
        )
        self.on_dead = on_dead
        self.on_revive = on_revive
        self._lock = TimedLock("registry_swap")
        self._stop = threading.Event()
        self._started = False
        self._threads: Dict[str, threading.Thread] = {}
        # pollers of removed replicas, kept until close() joins them
        # (they exit at their next tick; the flapping tests assert
        # none leak)
        self._retired: List[threading.Thread] = []
        # LEFT replicas, bounded ring: rid -> (Replica, departed_at) -
        # STATS keeps showing them as state=gone so churn is visible,
        # not inferable only from scrape gaps
        self.departed: "collections.OrderedDict[str, Tuple[Replica, float]]" = (
            collections.OrderedDict()
        )
        self._collector_key = f"router-registry:{id(self):x}"
        REGISTRY.register_collector(
            self._collector_key, self._collect_metrics
        )

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaRegistry":
        """Spawn one PERSISTENT poller thread per replica. The old
        shape - a coordinator spawning a fresh thread per replica per
        0.5s round - cost a thread create/join cycle per replica per
        round forever, and is the wrong substrate for dynamic
        membership (ROADMAP item 4): with per-replica pollers, a
        joining replica is one new thread and a leaving one is one
        stopped thread, no round choreography."""
        with self._lock:
            if self._started:
                return self
            self._started = True
        for r in list(self.replicas.values()):
            self._spawn_poller(r)
        return self

    def _spawn_poller(self, r: Replica) -> None:
        with self._lock:
            if self._stop.is_set() or r.replica_id in self._threads:
                return
            t = threading.Thread(
                target=self._poller_loop, args=(r,), daemon=True,
                name=f"blaze-router-poll-{r.replica_id}",
            )
            self._threads[r.replica_id] = t
        t.start()

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            threads = list(self._threads.values()) + self._retired
            self._threads = {}
            self._retired = []
        for r in list(self.replicas.values()):
            r._stop.set()
        for t in threads:
            t.join(timeout=5)
        REGISTRY.unregister_collector(self._collector_key)
        for r in list(self.replicas.values()):
            c, r._client = r._client, None
            if c is not None:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 - teardown
                    pass

    # -- dynamic membership ----------------------------------------------
    def note_membership(self, kind: str, replica_id: str) -> None:
        """One membership transition onto the fleet-view counter. kind:
        join | rejoin | leave | drain | drain_reject | dead | revive."""
        REGISTRY.inc("blaze_router_membership_events", kind=kind)
        log.info("membership %s: %s", kind, replica_id)

    def add(self, spec) -> Tuple[Replica, bool]:
        """JOIN: register a replica (idempotent - the announcer
        re-JOINs periodically so a restarted router re-learns the
        fleet). Returns (replica, created); a poller spins up when the
        registry is started and membership counters fire only on real
        transitions, never on idempotent re-announcements."""
        host, port = parse_replica(spec)
        rid = f"{host}:{port}"
        created = False
        with self._lock:
            r = self.replicas.get(rid)
            if r is None:
                r = Replica(host, port)
                created = True
                # atomic dict swap: readers iterate a stable snapshot
                # (routable()/snapshot()/metrics run lock-free)
                m = dict(self.replicas)
                m[rid] = r
                self.replicas = m
                rejoined = self.departed.pop(rid, None) is not None
            started = self._started
        if created:
            self.note_membership("rejoin" if rejoined else "join",
                                 rid)
            if started:
                self._spawn_poller(r)
        return r, created

    def remove(self, replica_id: str,
               reason: str = "leave") -> Optional[Replica]:
        """LEAVE (or forced removal): retire the replica - stop its
        poller at the next tick, close its poll client, and move the
        record to the bounded departed ring (state=gone on STATS)."""
        with self._lock:
            r = self.replicas.get(replica_id)
            if r is None:
                return None
            m = dict(self.replicas)
            m.pop(replica_id, None)
            self.replicas = m
            r.departed = True
            r.alive = False
            r._stop.set()
            t = self._threads.pop(replica_id, None)
            if t is not None:
                self._retired.append(t)
            self.departed[replica_id] = (r, time.monotonic())
            while len(self.departed) > 64:
                self.departed.popitem(last=False)
            c, r._client = r._client, None
        if c is not None:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - teardown
                pass
        self.note_membership(reason, replica_id)
        return r

    def probe(self, replica_id: str) -> bool:
        """One synchronous poll of a single replica (the JOIN ack
        path: a joining replica becomes routable without waiting a
        poller tick). True when the poll succeeded."""
        r = self.replicas.get(replica_id)
        if r is None:
            return False
        self._poll_one(r)
        return r.alive

    # -- polling ---------------------------------------------------------
    def _poller_loop(self, r: Replica) -> None:
        """One replica's long-lived poller: independent cadences mean
        a black-holing host delays only ITS OWN snapshot - healthy
        replicas keep their freshness and death-detection latency no
        matter how many peers are wedged. Polls FIRST, then sleeps: a
        JOINing replica is routable within one round trip, not one
        interval."""
        while not (self._stop.is_set() or r._stop.is_set()):
            t0 = time.monotonic()
            try:
                self._poll_one(r)
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("poll of %s failed", r.replica_id)
            REGISTRY.observe(
                "blaze_router_poll_round_seconds",
                time.monotonic() - t0, replica=r.replica_id,
            )
            if r._stop.wait(self.poll_interval_s):
                break

    def poll_now(self) -> None:
        """One synchronous STATS round across the fleet - the manual
        probe (tests, the CLI's startup check). The recurring path is
        the per-replica poller threads (`start()`); rounds against one
        replica serialize on its `_poll_lock`, so a manual round
        during background polling never crosses frames. Replicas are
        polled concurrently: a black-holing host costs the round one
        connect timeout, not one per wedged replica."""
        reps = list(self.replicas.values())
        if len(reps) <= 1:
            for r in reps:
                self._poll_one(r)
            return
        threads = [
            threading.Thread(
                target=self._poll_one, args=(r,), daemon=True,
                name=f"blaze-router-probe-{r.replica_id}",
            )
            for r in reps
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _poll_one(self, r: Replica) -> None:
        with r._poll_lock:
            self._poll_one_locked(r)

    def _poll_one_locked(self, r: Replica) -> None:
        from blaze_tpu.service.wire import ServiceClient

        if r.departed:
            # a straggler round racing remove() must not resurrect a
            # replica that LEFT (its record lives on in the departed
            # ring only for STATS visibility)
            return
        try:
            # the connect + STATS round trip runs OUTSIDE r._lock:
            # note_routed/note_unrouted take that lock on the submit
            # and query-finish hot paths, and a wedged replica must
            # cost this poll its timeout - not stall client-visible
            # verbs behind a blocked lock for connect_timeout_s
            # (rounds themselves are serialized by r._poll_lock)
            with r._lock:
                c = r._client
            if c is None:
                c = ServiceClient(
                    r.host, r.port,
                    timeout=self.connect_timeout_s,
                    reconnect_attempts=0,  # the loop IS the retry
                )
                with r._lock:
                    r._client = c
            stats = c.stats()
        except Exception as e:  # noqa: BLE001 - poll failure = signal
            with r._lock:
                c, r._client = r._client, None
            if c is not None:
                try:
                    c.close()
                except Exception:  # noqa: BLE001
                    pass
            r.poll_failures += 1
            REGISTRY.inc("blaze_router_polls_total", outcome="error")
            if r.alive and r.liveness.expired(
                self.heartbeat_timeout_s
            ):
                self._mark_dead(r, repr(e))
            return
        r.poll_failures = 0
        r.stats = stats
        r.stats_at = time.monotonic()
        r.liveness.note_progress()
        REGISTRY.inc("blaze_router_polls_total", outcome="ok")
        # membership: the replica's own DRAINING announcement (rolling
        # restart). Flipping it stops NEW placements one poll after
        # SIGTERM landed; clearing happens if the drain was aborted.
        was_draining = r.draining
        r.draining = bool(
            (stats.get("service") or {}).get("draining")
        )
        if r.draining and not was_draining:
            self.note_membership("drain", r.replica_id)
        if not r.alive:
            first_contact = not r.ever_alive
            r.alive = True
            r.ever_alive = True
            if r.quarantine_reason == "heartbeat-dead":
                # revival closes a death quarantine; breaker-opened
                # quarantines keep their cool-off (the replica answers
                # STATS but still fails queries)
                r.quarantined_until = 0.0
                r.quarantine_reason = None
            log.info("replica %s alive", r.replica_id)
            self.note_membership(
                "alive" if first_contact else "revive", r.replica_id
            )
            if self.on_revive is not None:
                try:
                    self.on_revive(r)
                except Exception:  # noqa: BLE001 - callback safety
                    log.exception("on_revive callback failed")

    def _mark_dead(self, r: Replica, cause: str) -> None:
        r.alive = False
        self.quarantine(r.replica_id, reason="heartbeat-dead")
        log.warning("replica %s heartbeat-dead (%s): quarantined, "
                    "re-routing its in-flight queries",
                    r.replica_id, cause)
        REGISTRY.inc("blaze_router_replica_deaths_total",
                     replica=r.replica_id)
        self.note_membership("dead", r.replica_id)
        if self.on_dead is not None:
            try:
                self.on_dead(r)
            except Exception:  # noqa: BLE001 - callback safety
                log.exception("on_dead callback failed")

    # -- health verdicts -------------------------------------------------
    def quarantine(self, replica_id: str,
                   reason: str = "circuit-open") -> None:
        r = self.replicas.get(replica_id)
        if r is None:
            return
        r.quarantined_until = time.monotonic() + self.quarantine_s
        r.quarantine_reason = reason
        REGISTRY.inc("blaze_router_quarantines_total",
                     replica=replica_id, reason=reason)

    def routable(self) -> List[Replica]:
        now = time.monotonic()
        return [
            r for r in self.replicas.values() if r.routable(now)
        ]

    def get(self, replica_id: str) -> Optional[Replica]:
        return self.replicas.get(replica_id)

    # -- exposition ------------------------------------------------------
    def _collect_metrics(self):
        # a generator: the registry consumes it at scrape time, so no
        # per-scrape sample list is materialized here
        now = time.monotonic()
        for rid, r in self.replicas.items():
            lab = {"replica": rid}
            yield ("blaze_router_replica_alive", lab,
                   1 if r.alive else 0, "gauge")
            yield ("blaze_router_replica_quarantined", lab,
                   1 if r.quarantined(now) else 0, "gauge")
            yield ("blaze_router_replica_in_flight", lab,
                   r.in_flight, "gauge")
            # the membership `state` label: churn renders on the
            # scrape surface, not just as scrape gaps
            yield ("blaze_router_replica_membership",
                   {**lab, "state": r.membership_state(now)}, 1,
                   "gauge")
            if r.stats is not None:
                a = r.stats.get("admission", {})
                yield ("blaze_router_replica_queue_depth", lab,
                       a.get("queued", 0), "gauge")
                yield ("blaze_router_replica_headroom_bytes", lab,
                       r.effective_headroom() or 0, "gauge")
        with self._lock:
            gone = list(self.departed)
        for rid in gone:
            yield ("blaze_router_replica_membership",
                   {"replica": rid, "state": "gone"}, 1, "gauge")

    def snapshot(self) -> Dict[str, dict]:
        now = time.monotonic()
        out = {
            rid: r.snapshot(now)
            for rid, r in self.replicas.items()
        }
        with self._lock:
            gone = [(rid, at) for rid, (_r, at)
                    in self.departed.items()]
        for rid, at in gone:
            out.setdefault(rid, {
                "state": "gone",
                "alive": False,
                "departed_age_s": round(now - at, 3),
            })
        return out
