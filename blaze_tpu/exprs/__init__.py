"""Expression engine: IR + columnar evaluators.

Covers the reference's physical expression surface (spark-extension
NativeConverters.scala:380-501 and plan-serde from_proto.rs expression arms):
literals, column refs, casts, binary arithmetic/comparison/logic, null
predicates, In/InSet, If/CaseWhen, ~40 scalar functions, and the Spark
aggregate set (MIN/MAX/SUM/AVG/COUNT/VAR/STDDEV).

Two evaluators share the IR:
- `eval.DeviceEvaluator`: jnp ops inside jit over padded device columns
  (values + validity). The TPU compute path.
- string-typed subtrees are evaluated host-side (pyarrow compute) by the
  pipeline compiler and enter the device pipeline as precomputed inputs;
  TPUs have no string compute so we split at the type boundary.
"""

from blaze_tpu.exprs.ir import (
    Expr,
    Literal,
    Col,
    BoundCol,
    Cast,
    BinaryOp,
    Not,
    Negate,
    IsNull,
    IsNotNull,
    InList,
    If,
    CaseWhen,
    ScalarFn,
    Coalesce,
    AggExpr,
    AggFn,
)

__all__ = [
    "Expr",
    "Literal",
    "Col",
    "BoundCol",
    "Cast",
    "BinaryOp",
    "Not",
    "Negate",
    "IsNull",
    "IsNotNull",
    "InList",
    "If",
    "CaseWhen",
    "ScalarFn",
    "Coalesce",
    "AggExpr",
    "AggFn",
]
