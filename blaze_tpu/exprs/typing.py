"""Output-type inference for the expression IR.

Promotion rules follow Spark's numeric widening (TinyInt<SmallInt<Int<BigInt<
Float<Double); decimals stay in the engine's i64-unscaled representation
(reference plan.proto:598-601). Plans arriving from a Spark-side converter
already carry explicit Casts (NativeConverters.scala convertExpr), so these
rules only need to cover well-typed trees.
"""

from __future__ import annotations

from blaze_tpu.types import DataType, Schema, TypeId
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import Op

_NUMERIC_ORDER = [
    TypeId.INT8,
    TypeId.INT16,
    TypeId.INT32,
    TypeId.INT64,
    TypeId.FLOAT32,
    TypeId.FLOAT64,
]


def promote(a: DataType, b: DataType) -> DataType:
    if a == b:
        return a
    if a.id is TypeId.NULL:
        return b
    if b.id is TypeId.NULL:
        return a
    if a.id is TypeId.DECIMAL or b.id is TypeId.DECIMAL:
        if a.id is TypeId.DECIMAL and b.id is TypeId.DECIMAL:
            return DataType.decimal(
                max(a.precision, b.precision), max(a.scale, b.scale)
            )
        other = b if a.id is TypeId.DECIMAL else a
        if other.is_integer:
            return a if a.id is TypeId.DECIMAL else b
        return DataType.float64()
    if a.id in _NUMERIC_ORDER and b.id in _NUMERIC_ORDER:
        return DataType(
            _NUMERIC_ORDER[
                max(_NUMERIC_ORDER.index(a.id), _NUMERIC_ORDER.index(b.id))
            ]
        )
    if a.id is TypeId.BOOL and b.id in _NUMERIC_ORDER:
        return b
    if b.id is TypeId.BOOL and a.id in _NUMERIC_ORDER:
        return a
    # date/timestamp comparisons against each other handled by equality of
    # ids above; anything else is a planner bug.
    raise TypeError(f"cannot promote {a} vs {b}")


_DEVICE_FN_TYPES = {
    # name -> fixed result type (None = same as first arg promoted to float)
    "sqrt": TypeId.FLOAT64,
    "exp": TypeId.FLOAT64,
    "ln": TypeId.FLOAT64,
    "log": TypeId.FLOAT64,
    "log2": TypeId.FLOAT64,
    "log10": TypeId.FLOAT64,
    "sin": TypeId.FLOAT64,
    "cos": TypeId.FLOAT64,
    "tan": TypeId.FLOAT64,
    "asin": TypeId.FLOAT64,
    "acos": TypeId.FLOAT64,
    "atan": TypeId.FLOAT64,
    "atan2": TypeId.FLOAT64,
    "sinh": TypeId.FLOAT64,
    "cosh": TypeId.FLOAT64,
    "tanh": TypeId.FLOAT64,
    "pow": TypeId.FLOAT64,
    "isnan": TypeId.BOOL,
}

_STRING_FNS_BOOL = {"starts_with", "ends_with", "contains", "like"}
_STRING_FNS_STR = {
    "lower",
    "upper",
    "trim",
    "ltrim",
    "rtrim",
    "substring",
    "concat",
    "replace",
    "reverse",
}


def expr_computes_wide_decimal(e: ir.Expr, schema: Schema) -> bool:
    """True when any non-passthrough node consumes a decimal(>18) input.
    Wide decimals are limb-pair columns (types.is_wide_decimal) that
    pass through scans/projections/aggregate states exactly, but VALUE
    compute on them needs 128-bit host math - operators raise at
    CONSTRUCTION so the planner's tryConvert falls back to the host
    tier (the window the reference uses, BlazeConverters tryConvert)."""
    if isinstance(e, (ir.BoundCol, ir.Col, ir.Literal)):
        return False
    if (
        isinstance(e, ir.BinaryOp)
        and e.op in ir.COMPARISON_OPS
        and all(
            isinstance(c, (ir.BoundCol, ir.Col, ir.Literal))
            for c in ir.children(e)
        )
    ):
        # comparisons stay on device: the evaluator's two-limb
        # lexicographic compare handles wide pairs - provided all
        # operands are integers-at-one-scale (unscaled values are then
        # directly comparable; rescaling would need 128-bit multiplies,
        # and floats cannot ride the limb compare at all)
        scales = set()
        ok = True
        for c in ir.children(e):
            try:
                dt = infer_dtype(c, schema)
            except Exception:
                ok = False
                break
            if dt.id is TypeId.DECIMAL:
                scales.add(dt.scale)
            elif dt.is_floating or dt.is_string_like:
                ok = False
                break
            else:
                scales.add(0)  # integer comparand = scale 0
        if ok and len(scales) <= 1:
            return False
    if (
        isinstance(e, ir.BinaryOp)
        and e.op in (Op.ADD, Op.SUB, Op.MUL, Op.DIV)
        and all(
            isinstance(c, (ir.BoundCol, ir.Col, ir.Literal))
            for c in ir.children(e)
        )
    ):
        # +,-,* (and / -> float64) over wide decimals run on device
        # since round 4: 128-bit limb arithmetic with Spark overflow-
        # NULL and HALF_UP rounding (exprs/int128.py, evaluator
        # _decimal_arith_wide). Only direct column/literal operands
        # qualify - nested wide arithmetic still composes through the
        # host tier (each node's output would need limb-pair
        # propagation through the expression cache).
        ok = True
        for c in ir.children(e):
            try:
                dt = infer_dtype(c, schema)
            except Exception:
                ok = False
                break
            if not (dt.id is TypeId.DECIMAL or dt.is_integer):
                ok = False
                break
        if ok:
            return False
    for c in ir.children(e):
        if expr_computes_wide_decimal(c, schema):
            return True
        try:
            if infer_dtype(c, schema).is_wide_decimal:
                return True
        except Exception:
            continue
    return False


def infer_dtype(e: ir.Expr, schema: Schema) -> DataType:
    if isinstance(e, ir.Literal):
        return e.dtype
    if isinstance(e, ir.Col):
        return schema.field(e.name).dtype
    if isinstance(e, ir.BoundCol):
        return e.dtype
    if isinstance(e, ir.Cast):
        return e.to
    if isinstance(e, ir.BinaryOp):
        lt = infer_dtype(e.left, schema)
        rt = infer_dtype(e.right, schema)
        if e.op in ir.COMPARISON_OPS or e.op in ir.LOGIC_OPS:
            return DataType.bool_()
        if e.op is Op.DIV and not (lt.is_floating or rt.is_floating) and (
            lt.id is TypeId.DECIMAL or rt.id is TypeId.DECIMAL
        ):
            return DataType.float64()
        return promote(lt, rt)
    if isinstance(e, (ir.Not,)):
        return DataType.bool_()
    if isinstance(e, ir.Negate):
        return infer_dtype(e.child, schema)
    if isinstance(e, (ir.IsNull, ir.IsNotNull)):
        return DataType.bool_()
    if isinstance(e, ir.InList):
        return DataType.bool_()
    if isinstance(e, ir.If):
        return promote(
            infer_dtype(e.then, schema), infer_dtype(e.otherwise, schema)
        )
    if isinstance(e, ir.CaseWhen):
        t = None
        for _, r in e.branches:
            rt = infer_dtype(r, schema)
            t = rt if t is None else promote(t, rt)
        if e.otherwise is not None:
            t = promote(t, infer_dtype(e.otherwise, schema))
        return t
    if isinstance(e, ir.Coalesce):
        t = None
        for a in e.args:
            at = infer_dtype(a, schema)
            t = at if t is None else promote(t, at)
        return t
    if isinstance(e, ir.ScalarFn):
        n = e.name
        if n in _DEVICE_FN_TYPES:
            return DataType(_DEVICE_FN_TYPES[n])
        if n in ("abs", "negative", "positive", "signum", "round", "trunc",
                 "ceil", "floor", "nanvl", "greatest", "least", "pmod"):
            if n in ("ceil", "floor"):
                # Spark: ceil/floor(double) -> bigint
                ct = infer_dtype(e.args[0], schema)
                return (
                    ct if ct.is_integer or ct.id is TypeId.DECIMAL
                    else DataType.int64()
                )
            t = None
            for a in e.args:
                at = infer_dtype(a, schema)
                t = at if t is None else promote(t, at)
            return t
        if n in ("length", "char_length"):
            return DataType.int32()
        if n in _STRING_FNS_BOOL:
            return DataType.bool_()
        if n in _STRING_FNS_STR:
            return DataType.utf8()
        if n == "spark_unscaled_value":
            return DataType.int64()
        if n == "spark_make_decimal":
            return DataType.decimal(38, 0)
        if n in ("murmur3_hash", "hash"):
            return DataType.int32()
        if n in ("year", "month", "day", "dayofmonth", "dayofweek",
                 "dayofyear", "quarter", "hour", "minute", "second",
                 "weekofyear", "date_part", "octet_length"):
            return DataType.int32()
        if n in ("to_date", "trunc_date"):
            return DataType.date32()
        if n == "null_if":
            return infer_dtype(e.args[0], schema)
        if n in ("md5", "sha224", "sha256", "sha384", "sha512"):
            return DataType.utf8()
        raise NotImplementedError(f"unknown scalar fn {n}")
    if isinstance(e, ir.AggExpr):
        from blaze_tpu.exprs.ir import AggFn

        if e.fn in (AggFn.COUNT, AggFn.COUNT_STAR):
            return DataType.int64()
        ct = infer_dtype(e.child, schema)
        if e.fn is AggFn.SUM:
            if ct.is_integer:
                return DataType.int64()
            if ct.id is TypeId.DECIMAL:
                return DataType.decimal(38, ct.scale)
            return DataType.float64()
        if e.fn is AggFn.AVG:
            if ct.id is TypeId.DECIMAL:
                return DataType.decimal(38, min(ct.scale + 4, 38))
            return DataType.float64()
        if e.fn in (AggFn.MIN, AggFn.MAX, AggFn.FIRST, AggFn.LAST):
            return ct
        return DataType.float64()  # var/stddev family
    raise TypeError(f"cannot infer type of {type(e)}")
