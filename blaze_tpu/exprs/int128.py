"""Vectorized 128-bit decimal arithmetic on device.

Reference counterpart: the reference gets full Decimal128 +/-/* from
arrow-rs compute kernels (from_proto.rs Decimal arms; the 16-byte slot
of shuffle_writer_exec.rs:196-220). The engine's wide decimals are
(capacity, 2) [lo, hi] int64 limb pairs (types.is_wide_decimal); until
round 4, VALUE arithmetic on them routed to the host tier. This module
does it in jnp so wide +/-/* stays on device.

Internal model: sign-magnitude. Magnitudes ride as TWO uint64 lanes
(lo, hi); signs as bool. Two's-complement limb pairs convert at the
boundaries. Everything is elementwise over row vectors - no lax control
flow except static Python loops - so it fuses into the surrounding
expression kernel.

Overflow semantics: Spark non-ANSI - a result beyond decimal(38)
becomes NULL (the `ok` lane returned by each op). Rounding is HALF_UP
(away from zero), matching the host tier's _reassemble_decimal.

Static-per-trace quantities: rescale exponents. Spark's analyzer fixes
result scales at plan time, so every 10^k here is a Python int constant
folded into the program.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

_DEC38_MAX = 10**38 - 1

U64 = jnp.uint64
_MASK32 = np.uint64(0xFFFFFFFF)


def _u(x):
    return jax.lax.bitcast_convert_type(x, jnp.uint64)


def _i(x):
    return jax.lax.bitcast_convert_type(x, jnp.int64)


# ---------------------------------------------------------------------------
# boundary conversions: two's-complement limb pair <-> sign+magnitude
# ---------------------------------------------------------------------------

def from_limbs(lo_i64, hi_i64):
    """(lo, hi) int64 two's-complement -> (mlo, mhi u64, neg bool)."""
    neg = hi_i64 < 0
    ulo = _u(lo_i64)
    uhi = _u(hi_i64)
    # 128-bit negate: ~x + 1; the +1 carries into the high limb
    # exactly when the low limb is zero
    nlo = ~ulo + U64(1)
    nhi = ~uhi + jnp.where(ulo == 0, U64(1), U64(0))
    mlo = jnp.where(neg, nlo, ulo)
    mhi = jnp.where(neg, nhi, uhi)
    return mlo, mhi, neg


def to_limbs(mlo, mhi, neg):
    """sign+magnitude -> (lo, hi) int64 two's complement."""
    nlo = ~mlo + U64(1)
    carry = mlo == 0
    nhi = ~mhi + jnp.where(carry, U64(1), U64(0))
    lo = jnp.where(neg, nlo, mlo)
    hi = jnp.where(neg, nhi, mhi)
    return _i(lo), _i(hi)


def from_narrow(v_i64):
    """int64 unscaled value -> sign+magnitude pair."""
    neg = v_i64 < 0
    # abs is safe: |INT64_MIN| = 2^63 fits uint64
    mag = jnp.where(neg, _u(-v_i64), _u(v_i64))
    # -INT64_MIN wraps to itself; its bit pattern IS 2^63 unsigned
    return mag, jnp.zeros_like(mag), neg


# ---------------------------------------------------------------------------
# magnitude primitives
# ---------------------------------------------------------------------------

def _mag_add(alo, ahi, blo, bhi):
    """u128 + u128 -> (lo, hi, overflow_bit)."""
    lo = alo + blo
    c = lo < alo  # low-limb carry
    hi_sum = ahi + bhi
    ovf1 = hi_sum < ahi
    hi = hi_sum + jnp.where(c, U64(1), U64(0))
    ovf2 = c & (hi < hi_sum)  # carry wrapped the high limb
    return lo, hi, ovf1 | ovf2


def _mag_sub(alo, ahi, blo, bhi):
    """u128 - u128 (requires a >= b) -> (lo, hi)."""
    lo = alo - blo
    borrow = alo < blo
    hi = ahi - bhi - jnp.where(borrow, U64(1), U64(0))
    return lo, hi


def _mag_cmp_lt(alo, ahi, blo, bhi):
    return (ahi < bhi) | ((ahi == bhi) & (alo < blo))


def _mag_cmp_gt(alo, ahi, blo, bhi):
    return _mag_cmp_lt(blo, bhi, alo, ahi)


def _split32(x_u64):
    return x_u64 & _MASK32, x_u64 >> np.uint64(32)


def _mag_mul_by_u64(mlo, mhi, m: int):
    """u128 x u64-constant -> (lo, hi, overflow). `m` is a Python int
    (0 < m < 2^64), so limb products fold to constants where possible.
    Overflow = any bits at 2^128 and above."""
    assert 0 < m < (1 << 64)
    m0 = np.uint64(m & 0xFFFFFFFF)
    m1 = np.uint64(m >> 32)
    a0, a1 = _split32(mlo)
    a2, a3 = _split32(mhi)
    # partial products: limb i of a times limb j of m lands at 32*(i+j)
    res = [jnp.zeros_like(mlo) for _ in range(6)]
    for i, ai in enumerate((a0, a1, a2, a3)):
        for j, mj in enumerate((m0, m1)):
            if int(mj) == 0:
                continue
            p = ai * mj  # < 2^64: 32-bit x 32-bit
            res[i + j] = res[i + j] + (p & _MASK32)
            res[i + j + 1] = res[i + j + 1] + (p >> np.uint64(32))
    # carry-normalize (each res lane < a few * 2^32, sums stay < 2^64)
    for k in range(5):
        res[k + 1] = res[k + 1] + (res[k] >> np.uint64(32))
        res[k] = res[k] & _MASK32
    lo = res[0] | (res[1] << np.uint64(32))
    hi = res[2] | (res[3] << np.uint64(32))
    ovf = (res[4] | res[5]) != 0
    return lo, hi, ovf


def _mag_mul(alo, ahi, blo, bhi):
    """u128 x u128 -> (lo, hi, overflow). Full 4x4 32-bit limb product
    with everything at or above 2^128 folded into the overflow bit."""
    a = _split32(alo) + _split32(ahi)
    b = _split32(blo) + _split32(bhi)
    res = [jnp.zeros_like(alo) for _ in range(8)]
    ovf = jnp.zeros(alo.shape, dtype=jnp.bool_)
    for i in range(4):
        for j in range(4):
            p = a[i] * b[j]
            k = i + j
            if k >= 4:
                ovf = ovf | (p != 0)
                continue
            res[k] = res[k] + (p & _MASK32)
            if k + 1 >= 4:
                ovf = ovf | ((p >> np.uint64(32)) != 0)
            else:
                res[k + 1] = res[k + 1] + (p >> np.uint64(32))
    for k in range(3):
        res[k + 1] = res[k + 1] + (res[k] >> np.uint64(32))
        res[k] = res[k] & _MASK32
    ovf = ovf | ((res[3] >> np.uint64(32)) != 0)
    res[3] = res[3] & _MASK32
    lo = res[0] | (res[1] << np.uint64(32))
    hi = res[2] | (res[3] << np.uint64(32))
    return lo, hi, ovf


def _pow10_limbs(k: int) -> Tuple[np.uint64, np.uint64]:
    v = 10**k
    return np.uint64(v & ((1 << 64) - 1)), np.uint64(v >> 64)


def _mag_divmod_u32(mlo, mhi, d: int):
    """u128 // u32-constant with remainder (vectorized long division
    high->low over four 32-bit limbs; every intermediate fits u64)."""
    assert 0 < d < (1 << 32)
    du = np.uint64(d)
    limbs = list(_split32(mlo)) + list(_split32(mhi))  # [l0..l3]
    q = [None] * 4
    rem = jnp.zeros_like(mlo)
    for idx in (3, 2, 1, 0):
        cur = (rem << np.uint64(32)) | limbs[idx]
        q[idx] = cur // du
        rem = cur % du
    qlo = q[0] | (q[1] << np.uint64(32))
    qhi = q[2] | (q[3] << np.uint64(32))
    return qlo, qhi, rem  # rem < d


def div_pow10_half_up(mlo, mhi, k: int):
    """u128 magnitude // 10^k with HALF_UP (round-half-away-from-zero
    on the magnitude) -> (lo, hi). k is a static Python int >= 0."""
    if k == 0:
        return mlo, mhi
    # chain 10^9-sized chunks; accumulate the FULL remainder (vs the
    # whole 10^k divisor) in 128 bits so the final half-comparison is
    # exact - rounding digit-at-a-time would be wrong (0.45 -> 0.5 ->
    # 1 instead of 0)
    qlo, qhi = mlo, mhi
    rlo = jnp.zeros_like(mlo)
    rhi = jnp.zeros_like(mhi)
    divided = 1  # product of divisors applied so far (Python int)
    left = k
    while left > 0:
        step = min(9, left)
        d = 10**step
        qlo, qhi, rem = _mag_divmod_u32(qlo, qhi, d)
        if divided == 1:
            rlo, rhi = rem, jnp.zeros_like(rem)
        else:
            # full remainder so far = rem * (divisors so far) + prior.
            # rem < 10^9 and divided <= 10^29, so the product fits 128
            # bits (10^38 < 2^127); split `divided` into <= 2^64
            # chunks for the by-constant multiply
            plo, phi = rem, jnp.zeros_like(rem)
            dleft = divided
            while dleft > 1:
                chunk = min(dleft, 10**19)
                # divided is a power of 10, so chunks divide exactly
                while dleft % chunk:
                    chunk //= 10
                plo, phi, _ = _mag_mul_by_u64(plo, phi, chunk)
                dleft //= chunk
            rlo, rhi, _ = _mag_add(rlo, rhi, plo, phi)
        divided *= d
        left -= step
    # HALF_UP: q += (2*rem >= 10^k)
    tlo, thi, _ = _mag_add(rlo, rhi, rlo, rhi)  # 2*rem < 2*10^38 < 2^128
    dlo, dhi = _pow10_limbs(k)
    ge = ~_mag_cmp_lt(
        tlo, thi, jnp.full_like(tlo, dlo), jnp.full_like(thi, dhi)
    )
    qlo2 = qlo + jnp.where(ge, U64(1), U64(0))
    qhi2 = qhi + jnp.where(ge & (qlo2 == 0), U64(1), U64(0))
    return qlo2, qhi2


def rescale_up(mlo, mhi, k: int):
    """u128 magnitude x 10^k -> (lo, hi, overflow); k static >= 0."""
    if k == 0:
        return mlo, mhi, jnp.zeros(mlo.shape, dtype=jnp.bool_)
    ovf = jnp.zeros(mlo.shape, dtype=jnp.bool_)
    left = k
    while left > 0:
        step = min(19, left)  # 10^19 < 2^64
        mlo, mhi, o = _mag_mul_by_u64(mlo, mhi, 10**step)
        ovf = ovf | o
        left -= step
    return mlo, mhi, ovf


_D38_LO, _D38_HI = _pow10_limbs(38)  # 10^38 limbs


def exceeds_dec38(mlo, mhi):
    """|x| > 10^38 - 1 (the Spark non-ANSI NULL-on-overflow bound)."""
    return ~_mag_cmp_lt(
        mlo, mhi,
        jnp.full_like(mlo, _D38_LO), jnp.full_like(mhi, _D38_HI),
    )


# ---------------------------------------------------------------------------
# signed ops over (mlo, mhi, neg) triples
# ---------------------------------------------------------------------------

def signed_add(a, b):
    """(mag, sign) + (mag, sign) -> (mlo, mhi, neg, ok)."""
    alo, ahi, aneg = a
    blo, bhi, bneg = b
    same = aneg == bneg
    slo, shi, ovf = _mag_add(alo, ahi, blo, bhi)
    # opposite signs: larger magnitude wins
    a_lt_b = _mag_cmp_lt(alo, ahi, blo, bhi)
    dlo1, dhi1 = _mag_sub(blo, bhi, alo, ahi)
    dlo2, dhi2 = _mag_sub(alo, ahi, blo, bhi)
    dlo = jnp.where(a_lt_b, dlo1, dlo2)
    dhi = jnp.where(a_lt_b, dhi1, dhi2)
    mlo = jnp.where(same, slo, dlo)
    mhi = jnp.where(same, shi, dhi)
    neg = jnp.where(same, aneg, jnp.where(a_lt_b, bneg, aneg))
    zero = (mlo == 0) & (mhi == 0)
    neg = neg & ~zero
    ok = ~(same & ovf) & ~exceeds_dec38(mlo, mhi)
    return mlo, mhi, neg, ok


def signed_mul(a, b, down: int):
    """(mag, sign) x (mag, sign), then HALF_UP divide by 10^down
    (static) -> (mlo, mhi, neg, ok)."""
    alo, ahi, aneg = a
    blo, bhi, bneg = b
    mlo, mhi, ovf = _mag_mul(alo, ahi, blo, bhi)
    if down > 0:
        # the truncated product must itself fit 128 bits for the
        # divide to see true limbs; a product that overflowed is
        # unrecoverable here even when the rescaled value would fit -
        # Spark's BigDecimal keeps arbitrary precision. Documented
        # deviation: those rows NULL (they need >38-digit
        # intermediates, beyond the decimal128 slot either engine
        # ships over the wire).
        mlo, mhi = div_pow10_half_up(mlo, mhi, down)
    neg = (aneg ^ bneg)
    zero = (mlo == 0) & (mhi == 0)
    neg = neg & ~zero
    ok = ~ovf & ~exceeds_dec38(mlo, mhi)
    return mlo, mhi, neg, ok


def to_float64(lo_i64, hi_i64):
    """two's-complement limb pair -> f64 approximation (for the
    decimal DIV -> float64 path)."""
    mlo, mhi, neg = from_limbs(lo_i64, hi_i64)
    f = mlo.astype(jnp.float64) + mhi.astype(jnp.float64) * (2.0**64)
    return jnp.where(neg, -f, f)
