"""Spark-compatible Murmur3 (x86_32, seed 42) hashing.

This is a *bit-compatibility contract* (SURVEY 4): shuffle partitioning must
place rows exactly where a Spark executor would, or exchange interop breaks.
The reference implements the same contract in Rust (datafusion-ext
spark_hash.rs:27-87) against Spark's `Murmur3_x86_32.hashInt/hashLong/
hashUnsafeBytes` (seed 42, null columns skipped, hash chains across columns).

Three implementations, cross-checked by tests:
- device (jnp uint32 ops, runs inside jit - TPU VPU friendly)
- host numpy (vectorized over byte arrays, for string columns)
- the C++ host runtime (cpp/blaze_host) for bulk string hashing off-device

Spark quirks captured here:
- tail bytes of a byte-string are mixed one at a time as *sign-extended*
  ints through the full mixK1/mixH1 pipeline (unlike standard murmur3 tails)
- float -0.0 normalizes to 0.0 before hashing; float hashes as
  hashInt(floatToIntBits), double as hashLong(doubleToLongBits)
- NULL values leave the running hash unchanged
- multi-column hash: h = hash(col_i, h) folded left over columns from seed 42
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from blaze_tpu.types import DataType, TypeId

SPARK_SEED = np.uint32(42)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(0xE6546B64)


# ---------------------------------------------------------------------------
# device (jnp) implementation - fixed-width types
# ---------------------------------------------------------------------------

def _rotl32(x, r: int):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    k1 = k1 * _C2
    return k1


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    h1 = h1 * np.uint32(5) + _M5
    return h1


def _fmix(h1, length):
    h1 = h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * np.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> 16)
    return h1


def hash_int32(v, seed):
    """hashInt: v is uint32-reinterpreted int32; seed uint32."""
    return _fmix(_mix_h1(seed, _mix_k1(v)), 4)


def hash_int64(v, seed):
    """hashLong: low word then high word.

    Splits via arithmetic rather than a 64-bit bitcast: the TPU backend's
    no-X64 rewrite pass does not implement u64 bitcast-convert, but it does
    emulate i64 shifts/masks as 32-bit pairs.
    """
    v = v.astype(jnp.int64)
    low = jnp.bitwise_and(v, 0xFFFFFFFF).astype(jnp.uint32)
    high = jnp.bitwise_and(
        jnp.right_shift(v, 32), 0xFFFFFFFF
    ).astype(jnp.uint32)
    h1 = _mix_h1(seed, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, 8)


def _scale_pow2(x: jax.Array, k: jax.Array) -> jax.Array:
    """x * 2^k computed exactly for integer k in [-1023, 1023].

    Decomposes |k| into bits and multiplies by exact compile-time constants
    2^(+-2^b); every factor and (with bits applied in descending order, which
    moves the value monotonically toward its target) every intermediate stays
    a normal f64, so each multiply is exact.
    """
    neg = k < 0
    mag = jnp.abs(k)
    out = x
    # bit 10 (|k| >= 1024, reachable when log2 rounds DBL_MAX up to 1024):
    # 2^1024 overflows f64, so apply it as two half-factors
    has10 = (mag & 1024) != 0
    half10 = jnp.where(has10, jnp.where(neg, 2.0 ** -512, 2.0 ** 512), 1.0)
    out = out * half10 * half10
    for b in range(9, -1, -1):
        p = 1 << b
        has = (mag & p) != 0
        factor = jnp.where(has, jnp.where(neg, 2.0 ** -p, 2.0 ** p), 1.0)
        out = out * factor
    return out


def double_to_long_bits(v: jax.Array) -> jax.Array:
    """Java Double.doubleToLongBits reconstructed arithmetically.

    The TPU backend's no-X64 rewrite implements neither u64 nor f64
    bitcast-convert (and jnp.frexp/signbit lower to one), so the IEEE754
    fields are rebuilt with pure arithmetic: exponent from log2 with
    correction rounds, mantissa by exact power-of-two scaling (every scaling
    below stays a power of two, so it is exact); NaN canonicalizes to
    0x7ff8000000000000 like Java.
    """
    v = v.astype(jnp.float64)
    # signbit without bitcast: 1/-0.0 == -inf
    negative = (v < 0.0) | ((v == 0.0) & (1.0 / v < 0.0))
    sign = negative.astype(jnp.int64) << 63
    a = jnp.abs(v)
    finite_pos = (a > 0.0) & jnp.isfinite(a)
    safe_a = jnp.where(finite_pos, a, 1.0)
    # lift subnormals into normal range so log2/exp2 stay exact (note: XLA
    # flushes f64 subnormals to zero, so true subnormal inputs hash as +-0
    # on device; exchange code routes f64 keys through the exact host path)
    is_sub_range = safe_a < 2.0 ** -1022
    a2 = jnp.where(is_sub_range, safe_a * (2.0 ** 64), safe_a)

    e = jnp.floor(jnp.log2(a2))
    # m = a2 * 2^-e, correcting log2 rounding at power-of-two boundaries.
    # XLA's exp2 is approximate even at integer args and its division is not
    # correctly rounded, so the scaling uses _scale_pow2 (exact constant
    # power-of-two factors) exclusively.
    for _ in range(2):
        m = _scale_pow2(a2, -e.astype(jnp.int32))
        e = jnp.where(m >= 2.0, e + 1.0, jnp.where(m < 1.0, e - 1.0, e))
    m = _scale_pow2(a2, -e.astype(jnp.int32))
    true_e = e - jnp.where(is_sub_range, 64.0, 0.0)
    is_sub = true_e < -1022.0
    biased = jnp.where(
        is_sub, jnp.int64(0), true_e.astype(jnp.int64) + 1023
    )
    # normal: frac = (m - 1) * 2^52 (m in [1,2), exact)
    frac_norm = jnp.floor((m - 1.0) * (2.0 ** 52)).astype(jnp.int64)
    # subnormal: frac = |v| * 2^1074 = m * 2^(true_e + 1074), exponent <= 52
    sub_pow = jnp.clip(true_e + 1074.0, 0.0, 52.0).astype(jnp.int32)
    frac_sub = jnp.floor(_scale_pow2(m, sub_pow)).astype(jnp.int64)
    frac = jnp.where(is_sub, frac_sub, frac_norm)
    bits = sign | (biased << 52) | frac
    bits = jnp.where(finite_pos, bits, sign)  # +-0.0 handled here
    bits = jnp.where(
        jnp.isinf(v), sign | (jnp.int64(2047) << 52), bits
    )
    bits = jnp.where(jnp.isnan(v), jnp.int64(0x7FF8000000000000), bits)
    return bits


def device_hash_supported(dtype: DataType, backend: Optional[str] = None
                          ) -> bool:
    """Whether `hash_column_device` is bit-exact for this dtype on the given
    backend. Strings always hash host-side (no TPU string compute). FLOAT64
    is device-exact only on the CPU backend: TPU emulates f64 as f32 pairs
    (~49-bit mantissa), so exchange code routes f64 keys through
    `hash_rows_host` on TPU hardware.
    """
    import jax as _jax

    backend = backend or _jax.default_backend()
    if dtype.id in (TypeId.UTF8, TypeId.BINARY):
        return False
    if dtype.id is TypeId.FLOAT64:
        return backend == "cpu"
    if dtype.id is TypeId.DECIMAL and dtype.precision > 18:
        return False
    return True


def hash_column_device(values: jax.Array, validity: Optional[jax.Array],
                       dtype: DataType, seed: jax.Array) -> jax.Array:
    """Chain one column into the running per-row hash (uint32)."""
    tid = dtype.id
    if tid in (TypeId.BOOL,):
        h = hash_int32(values.astype(jnp.uint32), seed)
    elif tid in (TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.DATE32):
        # sign-extend to int32 then reinterpret
        h = hash_int32(values.astype(jnp.int32).view(jnp.uint32), seed)
    elif tid in (TypeId.INT64, TypeId.TIMESTAMP_US):
        h = hash_int64(values.astype(jnp.int64), seed)
    elif tid is TypeId.DECIMAL and dtype.precision <= 18:
        h = hash_int64(values.astype(jnp.int64), seed)
    elif tid is TypeId.FLOAT32:
        v = jnp.where(values == 0.0, 0.0, values)  # -0.0 -> 0.0
        h = hash_int32(
            lax.bitcast_convert_type(v.astype(jnp.float32), jnp.uint32), seed
        )
    elif tid is TypeId.FLOAT64:
        v = jnp.where(values == 0.0, 0.0, values).astype(jnp.float64)
        h = hash_int64(double_to_long_bits(v), seed)
    else:
        raise NotImplementedError(
            f"device hash of {dtype}; string columns hash host-side"
        )
    if validity is not None:
        h = jnp.where(validity, h, seed)  # NULL leaves hash unchanged
    return h


def hash_columns_device(
    cols: Sequence[Tuple[jax.Array, Optional[jax.Array], DataType]],
    capacity: int,
    precomputed: Sequence[Optional[jax.Array]] = (),
) -> jax.Array:
    """Multi-column Spark hash as int32. `precomputed` lets the host pass
    already-hashed uint32 lanes for string columns: entry i non-None means
    'chain this per-row hash value instead of hashing values[i] on device'.

    A precomputed lane carries the *final* per-row uint32 for that column
    having been chained from the running seed host-side is not possible
    (seed differs per row), so string lanes are mixed in as one
    hashInt-style link of their own 32-bit value. Matching Spark exactly
    for strings therefore requires host hashing of the string bytes into
    the chain; `hash_rows_host` does the exact chain - the device variant
    with precomputed lanes is used only for engine-internal partitioning
    consistency, never for Spark interop, and bench/shuffle code selects
    `hash_rows_host` whenever a string key is present.
    """
    h = jnp.full(capacity, SPARK_SEED, dtype=jnp.uint32)
    pre = list(precomputed) + [None] * (len(cols) - len(precomputed))
    for (values, validity, dtype), p in zip(cols, pre):
        if p is not None:
            link = _fmix(_mix_h1(h, _mix_k1(p.astype(jnp.uint32))), 4)
            if validity is not None:
                link = jnp.where(validity, link, h)
            h = link
        else:
            h = hash_column_device(values, validity, dtype, h)
    return h.view(jnp.int32)


def pmod(hash_i32: jax.Array, n: int) -> jax.Array:
    """Spark's non-negative modulo for partition assignment
    (reference spark_hash.rs pmod)."""
    r = hash_i32 % np.int32(n)
    return jnp.where(r < 0, r + np.int32(n), r).astype(jnp.int32)


# ---------------------------------------------------------------------------
# host (numpy) implementation - includes byte strings
# ---------------------------------------------------------------------------

def _np_rotl32(x, r):
    return np.uint32((np.uint32(x) << np.uint32(r)) |
                     (np.uint32(x) >> np.uint32(32 - r)))


def _np_hash_int(v: np.ndarray, seed: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        k1 = (v.astype(np.uint32) * _C1)
        k1 = (k1 << np.uint32(15)) | (k1 >> np.uint32(17))
        k1 = k1 * _C2
        h1 = seed ^ k1
        h1 = (h1 << np.uint32(13)) | (h1 >> np.uint32(19))
        h1 = h1 * np.uint32(5) + _M5
        h1 = h1 ^ np.uint32(4)
        h1 ^= h1 >> np.uint32(16)
        h1 = h1 * np.uint32(0x85EBCA6B)
        h1 ^= h1 >> np.uint32(13)
        h1 = h1 * np.uint32(0xC2B2AE35)
        h1 ^= h1 >> np.uint32(16)
    return h1


def _np_mix_h1(h1, k1):
    with np.errstate(over="ignore"):
        h1 = h1 ^ k1
        h1 = (h1 << np.uint32(13)) | (h1 >> np.uint32(19))
        return h1 * np.uint32(5) + _M5


def _np_mix_k1(k1):
    with np.errstate(over="ignore"):
        k1 = k1 * _C1
        k1 = (k1 << np.uint32(15)) | (k1 >> np.uint32(17))
        return k1 * _C2


def _np_fmix(h1, length):
    with np.errstate(over="ignore"):
        h1 = h1 ^ np.uint32(length) if np.isscalar(length) else \
            h1 ^ length.astype(np.uint32)
        h1 ^= h1 >> np.uint32(16)
        h1 = h1 * np.uint32(0x85EBCA6B)
        h1 ^= h1 >> np.uint32(13)
        h1 = h1 * np.uint32(0xC2B2AE35)
        h1 ^= h1 >> np.uint32(16)
    return h1


def hash_bytes_host(data: bytes, seed: int = 42) -> int:
    """Spark Murmur3_x86_32.hashUnsafeBytes of one byte string (scalar)."""
    h1 = np.uint32(seed)
    n = len(data)
    aligned = n - n % 4
    for i in range(0, aligned, 4):
        word = np.uint32(int.from_bytes(data[i:i + 4], "little"))
        h1 = _np_mix_h1(h1, _np_mix_k1(word))
    for i in range(aligned, n):
        b = data[i]
        sb = b - 256 if b >= 128 else b  # sign-extended java byte
        h1 = _np_mix_h1(h1, _np_mix_k1(np.uint32(np.int32(sb))))
    return int(_np_fmix(h1, n))


def hash_long_host(v: int, seed: int = 42) -> int:
    u = np.uint64(np.int64(v).view(np.uint64) if hasattr(v, "view")
                  else np.int64(v).astype(np.uint64))
    low = np.uint32(u & np.uint64(0xFFFFFFFF))
    high = np.uint32(u >> np.uint64(32))
    h1 = _np_mix_h1(np.uint32(seed), _np_mix_k1(low))
    h1 = _np_mix_h1(h1, _np_mix_k1(high))
    return int(_np_fmix(h1, 8))


def hash_int_host(v: int, seed: int = 42) -> int:
    return int(_np_hash_int(
        np.array(np.int32(v)).view(np.uint32), np.uint32(seed)
    ))


def hash_rows_host(columns, num_rows: int) -> np.ndarray:
    """Exact Spark multi-column hash on host, as int32 per row.

    `columns` is a list of (numpy_values, numpy_validity|None, DataType,
    dictionary|None) - the host mirror of a batch. Strings are hashed from
    their real utf8 bytes (dictionary lookup), everything else through the
    same int paths as the device version. The differential reference for
    hash_columns_device and the interop path for string shuffle keys.
    """
    h = np.full(num_rows, SPARK_SEED, dtype=np.uint32)
    for values, validity, dtype, dictionary in columns:
        tid = dtype.id
        if tid in (TypeId.UTF8, TypeId.BINARY):
            assert dictionary is not None
            from blaze_tpu.runtime import native

            link = native.murmur3_dict_strings_chain(
                dictionary,
                np.ascontiguousarray(values[:num_rows], dtype=np.int32),
                validity[:num_rows] if validity is not None else None,
                h.copy(),
            )
        elif tid in (TypeId.BOOL,):
            link = _np_hash_int(values[:num_rows].astype(np.uint32), h)
        elif tid in (TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.DATE32):
            link = _np_hash_int(
                values[:num_rows].astype(np.int32).view(np.uint32), h
            )
        elif tid in (TypeId.INT64, TypeId.TIMESTAMP_US) or (
            tid is TypeId.DECIMAL and dtype.precision <= 18
        ):
            u = values[:num_rows].astype(np.int64).view(np.uint64)
            low = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            high = (u >> np.uint64(32)).astype(np.uint32)
            h1 = _np_mix_h1(h, _np_mix_k1(low))
            h1 = _np_mix_h1(h1, _np_mix_k1(high))
            link = _np_fmix(h1, 8)
        elif tid is TypeId.FLOAT32:
            v = values[:num_rows].astype(np.float32)
            v = np.where(v == 0.0, np.float32(0.0), v)
            link = _np_hash_int(v.view(np.uint32), h)
        elif tid is TypeId.FLOAT64:
            v = values[:num_rows].astype(np.float64)
            v = np.where(v == 0.0, 0.0, v)
            u = v.view(np.uint64)
            low = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            high = (u >> np.uint64(32)).astype(np.uint32)
            h1 = _np_mix_h1(h, _np_mix_k1(low))
            h1 = _np_mix_h1(h1, _np_mix_k1(high))
            link = _np_fmix(h1, 8)
        else:
            raise NotImplementedError(f"host hash of {dtype}")
        if validity is not None:
            link = np.where(validity[:num_rows], link, h)
        h = link
    return h.view(np.int32)
