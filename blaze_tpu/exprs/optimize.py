"""Expression-level optimizations applied at operator bind time.

`narrow_literals`: Python-inferred literals arrive as int64/float64 (like
Spark's parser defaults to the widest comfortable type), but comparing or
combining an int32/float32 column with a wide literal promotes the whole
column - and on TPU, 64-bit integer and especially float64 arithmetic is
*emulated* (f32-pair software arithmetic after the no-X64 rewrite), an
order-of-magnitude penalty on the VPU. When the literal's value is exactly
representable in the other operand's narrower type, rewriting the literal
is semantics-preserving and keeps the whole expression in native-width
arithmetic. Lossless-only: 50.0 narrows to f32, 50.3 does not (its f32
rounding would change comparison results), 2^40 never narrows to int32.
"""

from __future__ import annotations

import numpy as np

from blaze_tpu.types import DataType, Schema, TypeId
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.typing import infer_dtype

_NARROWABLE_NUM = {
    TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
    TypeId.FLOAT32, TypeId.FLOAT64,
}


def _try_narrow(lit: ir.Literal, target: DataType) -> ir.Literal:
    if lit.value is None or lit.dtype == target:
        return lit
    if target.id not in _NARROWABLE_NUM or \
            lit.dtype.id not in _NARROWABLE_NUM:
        return lit
    v = lit.value
    phys = target.physical_dtype()
    if target.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        cast = phys.type(v)
        if float(cast) == float(v) or (np.isnan(cast) and v != v):
            return ir.Literal(float(v), target)
        return lit
    # integer target: must be an integral value in range
    if isinstance(v, float) and not float(v).is_integer():
        return lit
    iv = int(v)
    info = np.iinfo(phys)
    if info.min <= iv <= info.max:
        return ir.Literal(iv, target)
    return lit


def narrow_literals(e: ir.Expr, schema: Schema) -> ir.Expr:
    """Bottom-up literal narrowing across binary ops and IN lists."""

    def rule(x: ir.Expr) -> ir.Expr:
        if isinstance(x, ir.BinaryOp):
            lt = _safe_dtype(x.left, schema)
            rt = _safe_dtype(x.right, schema)
            if isinstance(x.right, ir.Literal) and lt is not None:
                return ir.BinaryOp(
                    x.op, x.left, _try_narrow(x.right, lt)
                )
            if isinstance(x.left, ir.Literal) and rt is not None:
                return ir.BinaryOp(
                    x.op, _try_narrow(x.left, rt), x.right
                )
        if isinstance(x, ir.InList):
            ct = _safe_dtype(x.child, schema)
            if ct is not None:
                return ir.InList(
                    x.child,
                    tuple(
                        _try_narrow(v, ct)
                        if isinstance(v, ir.Literal) else v
                        for v in x.values
                    ),
                    x.negated,
                )
        return x

    return ir.transform(e, rule)


def _safe_dtype(e: ir.Expr, schema: Schema):
    if isinstance(e, ir.Literal):
        return None
    try:
        return infer_dtype(e, schema)
    except Exception:
        return None


def bind_opt(e: ir.Expr, schema: Schema) -> ir.Expr:
    """bind + standard optimization passes (operator entry point)."""
    return narrow_literals(ir.bind(e, schema), schema)
