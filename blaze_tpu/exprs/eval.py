"""Device (TPU) columnar expression evaluator.

Evaluates the IR over padded device columns inside `jax.jit`. Everything here
is pure jnp/lax - no data-dependent Python control flow - so whole pipelines
(scan -> filter -> project -> partial aggregate) fuse into one XLA program
(SURVEY 7 design stance).

Null semantics follow Spark SQL (non-ANSI), the contract the reference is
validated against by differential TPC-DS testing (SURVEY 4):
- arithmetic/comparison: NULL if any input is NULL
- x / 0 and x % 0 are NULL (all numeric types)
- AND/OR are three-valued (FALSE AND NULL = FALSE, TRUE OR NULL = TRUE)
- NaN equals NaN and sorts greater than any other double
- IS NULL / IS NOT NULL never return NULL

A column value is the pair (values, validity) where validity is None for
all-valid; helpers keep validity lazy so fully-valid pipelines never
materialize masks.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from blaze_tpu.types import DataType, Schema, TypeId
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import Op
from blaze_tpu.exprs.typing import infer_dtype, promote

CV = Tuple[jax.Array, Optional[jax.Array]]  # (values, validity|None)


def and_validity(a: Optional[jax.Array],
                 b: Optional[jax.Array]) -> Optional[jax.Array]:
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def valid_or_true(v: Optional[jax.Array], shape) -> jax.Array:
    if v is None:
        return jnp.ones(shape, dtype=jnp.bool_)
    return jnp.broadcast_to(v, shape)


def _np_dtype(dt: DataType):
    return dt.physical_dtype()


class DeviceEvaluator:
    """Evaluate bound expressions against a batch's device buffers."""

    def __init__(self, schema: Schema, columns: Sequence[CV], capacity: int):
        self.schema = schema
        self.columns = list(columns)
        self.capacity = capacity

    # ------------------------------------------------------------------
    def evaluate(self, e: ir.Expr) -> CV:
        values, validity = self._eval(e)
        return values, validity

    def evaluate_predicate(self, e: ir.Expr) -> jax.Array:
        """Predicate result with SQL WHERE semantics: NULL -> False."""
        v, m = self._eval(e)
        v = v.astype(jnp.bool_)
        if m is not None:
            v = v & m
        return v

    # ------------------------------------------------------------------
    def _eval(self, e: ir.Expr) -> CV:
        if isinstance(e, ir.BoundCol):
            return self.columns[e.index]
        if isinstance(e, ir.Col):
            return self.columns[self.schema.index_of(e.name)]
        if isinstance(e, ir.Literal):
            return self._literal(e)
        if isinstance(e, ir.Cast):
            return self._cast(e)
        if isinstance(e, ir.BinaryOp):
            return self._binary(e)
        if isinstance(e, ir.Not):
            v, m = self._eval(e.child)
            return ~v.astype(jnp.bool_), m
        if isinstance(e, ir.Negate):
            v, m = self._eval(e.child)
            return -v, m
        if isinstance(e, ir.IsNull):
            _, m = self._eval(e.child)
            if m is None:
                return jnp.zeros(self.capacity, dtype=jnp.bool_), None
            return ~m, None
        if isinstance(e, ir.IsNotNull):
            _, m = self._eval(e.child)
            if m is None:
                return jnp.ones(self.capacity, dtype=jnp.bool_), None
            return m, None
        if isinstance(e, ir.InList):
            return self._in_list(e)
        if isinstance(e, ir.If):
            return self._case(
                ir.CaseWhen(((e.cond, e.then),), e.otherwise)
            )
        if isinstance(e, ir.CaseWhen):
            return self._case(e)
        if isinstance(e, ir.Coalesce):
            return self._coalesce(e)
        if isinstance(e, ir.ScalarFn):
            return self._scalar_fn(e)
        raise NotImplementedError(
            f"device evaluator: unsupported expr {type(e).__name__}"
        )

    # ------------------------------------------------------------------
    def _literal(self, e: ir.Literal) -> CV:
        if e.value is None:
            # zeros must carry the literal's PHYSICAL dtype: a NULL
            # int32 literal column that materialized as int8 would
            # poison positional unions with narrowed arithmetic
            # (1999 scatter-cast through int8 -> -49)
            if e.dtype is None:
                phys, shape = jnp.int8, (self.capacity,)
            elif e.dtype.is_wide_decimal:
                phys, shape = jnp.int64, (self.capacity, 2)
            else:
                phys, shape = e.dtype.physical_dtype(), (self.capacity,)
            return (
                jnp.zeros(shape, dtype=phys),
                jnp.zeros(self.capacity, dtype=jnp.bool_),
            )
        if e.dtype.is_string_like:
            raise NotImplementedError(
                "string literals must be lowered host-side before device eval"
            )
        v = jnp.full(
            self.capacity, e.value, dtype=_np_dtype(e.dtype)
        )
        return v, None

    def _cast(self, e: ir.Cast) -> CV:
        v, m = self._eval(e.child)
        src = infer_dtype(e.child, self.schema)
        dst = e.to
        if src == dst:
            return v, m
        if dst.is_string_like or src.is_string_like:
            raise NotImplementedError(
                "string casts are lowered host-side (no TPU string compute)"
            )
        if src.id is TypeId.DECIMAL and dst.id is TypeId.DECIMAL:
            # rescale unscaled i64 by 10^(dst.scale - src.scale)
            dscale = dst.scale - src.scale
            if dscale >= 0:
                return v * (10 ** dscale), m
            return _java_div(v, jnp.asarray(10 ** (-dscale), v.dtype)), m
        if src.id is TypeId.DECIMAL:
            scaled = v.astype(jnp.float64) / (10.0 ** src.scale)
            return scaled.astype(_np_dtype(dst)), m
        if dst.id is TypeId.DECIMAL:
            out = (v.astype(jnp.float64) * (10.0 ** dst.scale))
            return jnp.round(out).astype(jnp.int64), m
        if src.id is TypeId.DATE32 and dst.id is TypeId.TIMESTAMP_US:
            return v.astype(jnp.int64) * 86_400_000_000, m
        if src.id is TypeId.TIMESTAMP_US and dst.id is TypeId.DATE32:
            return jnp.floor_divide(v, 86_400_000_000).astype(jnp.int32), m
        if dst.id is TypeId.BOOL:
            return v != 0, m
        # numeric <-> numeric: Java-style wrap/truncate (astype wraps ints,
        # truncates float->int toward zero)
        return v.astype(_np_dtype(dst)), m

    # ------------------------------------------------------------------
    def _binary(self, e: ir.BinaryOp) -> CV:
        op = e.op
        if op in ir.LOGIC_OPS:
            return self._logic(e)
        lv, lm = self._eval(e.left)
        rv, rm = self._eval(e.right)
        lt = infer_dtype(e.left, self.schema)
        rt = infer_dtype(e.right, self.schema)
        m = and_validity(lm, rm)
        if op in ir.COMPARISON_OPS:
            return self._compare(op, lv, rv, lt, rt, m)
        out_t = infer_dtype(e, self.schema)
        phys = _np_dtype(out_t)
        # decimal alignment for +/-: rescale to common scale
        if lt.id is TypeId.DECIMAL or rt.id is TypeId.DECIMAL:
            return self._decimal_arith(op, lv, rv, lt, rt, out_t, m)
        lv = lv.astype(phys)
        rv = rv.astype(phys)
        if op is Op.ADD:
            return lv + rv, m
        if op is Op.SUB:
            return lv - rv, m
        if op is Op.MUL:
            return lv * rv, m
        if op is Op.DIV:
            return self._div(lv, rv, out_t, m)
        if op is Op.MOD:
            return self._mod(lv, rv, out_t, m)
        if op is Op.BITAND:
            return lv & rv, m
        if op is Op.BITOR:
            return lv | rv, m
        if op is Op.BITXOR:
            return lv ^ rv, m
        if op is Op.SHL:
            return lv << rv, m
        if op is Op.SHR:
            return lv >> rv, m
        raise NotImplementedError(op)

    def _compare(self, op, lv, rv, lt, rt, m) -> CV:
        if lt.is_wide_decimal or rt.is_wide_decimal:
            return self._compare_wide(op, lv, rv, lt, rt, m)
        ct = promote(lt, rt) if lt != rt else lt
        phys = _np_dtype(ct)
        lv = lv.astype(phys)
        rv = rv.astype(phys)
        if ct.is_floating:
            # Spark NaN semantics: NaN == NaN, NaN greater than everything
            ln = jnp.isnan(lv)
            rn = jnp.isnan(rv)
            if op is Op.EQ:
                return (lv == rv) | (ln & rn), m
            if op is Op.NEQ:
                return ~((lv == rv) | (ln & rn)), m
            if op is Op.LT:
                return jnp.where(ln, False, jnp.where(rn, True, lv < rv)), m
            if op is Op.LTE:
                return jnp.where(
                    ln, rn, jnp.where(rn, True, lv <= rv)
                ), m
            if op is Op.GT:
                return jnp.where(rn, False, jnp.where(ln, True, lv > rv)), m
            if op is Op.GTE:
                return jnp.where(
                    rn, ln, jnp.where(ln, True, lv >= rv)
                ), m
        table = {
            Op.EQ: lambda: lv == rv,
            Op.NEQ: lambda: lv != rv,
            Op.LT: lambda: lv < rv,
            Op.LTE: lambda: lv <= rv,
            Op.GT: lambda: lv > rv,
            Op.GTE: lambda: lv >= rv,
        }
        return table[op](), m

    def _compare_wide(self, op, lv, rv, lt, rt, m) -> CV:
        """decimal(>18) comparisons on device: two-limb lexicographic
        compare - signed high limb, unsigned low limb (the (cap, 2)
        [lo, hi] layout wide columns carry). Same-scale operands only;
        the typing gate (expr_computes_wide_decimal) routes
        scale-mismatched comparisons to the host tier, so this sees
        aligned unscaled integers. A narrow (<=18 digit) decimal side
        sign-extends into limbs for free."""
        if (lt.id is TypeId.DECIMAL and rt.id is TypeId.DECIMAL
                and lt.scale != rt.scale):
            raise NotImplementedError(
                "wide decimal comparison needs equal scales"
            )
        min64 = jnp.int64(np.int64(-(2 ** 63)))

        def limbs(v):
            if v.ndim == 2:
                return v[:, 0], v[:, 1]
            v64 = v.astype(jnp.int64)
            return v64, v64 >> jnp.int64(63)  # sign-extended high limb

        llo, lhi = limbs(lv)
        rlo, rhi = limbs(rv)
        ulo_l = jnp.bitwise_xor(llo, min64)  # unsigned-order low limbs
        ulo_r = jnp.bitwise_xor(rlo, min64)
        eq = (lhi == rhi) & (llo == rlo)
        lt_ = (lhi < rhi) | ((lhi == rhi) & (ulo_l < ulo_r))
        table = {
            Op.EQ: lambda: eq,
            Op.NEQ: lambda: ~eq,
            Op.LT: lambda: lt_,
            Op.LTE: lambda: lt_ | eq,
            Op.GT: lambda: ~(lt_ | eq),
            Op.GTE: lambda: ~lt_,
        }
        return table[op](), m

    def _div(self, lv, rv, out_t: DataType, m) -> CV:
        zero = rv == 0
        if out_t.is_floating:
            safe = jnp.where(zero, jnp.ones_like(rv), rv)
            return lv / safe, and_validity(m, ~zero)
        safe = jnp.where(zero, jnp.ones_like(rv), rv)
        return _java_div(lv, safe), and_validity(m, ~zero)

    def _mod(self, lv, rv, out_t: DataType, m) -> CV:
        zero = rv == 0
        safe = jnp.where(zero, jnp.ones_like(rv), rv)
        return lax.rem(lv, safe), and_validity(m, ~zero)

    def _decimal_arith(self, op, lv, rv, lt, rt, out_t, m) -> CV:
        def unscaled(v, t):
            if t.is_wide_decimal:
                return v, t.scale  # (cap, 2) limb pair
            if t.id is TypeId.DECIMAL:
                return v.astype(jnp.int64), t.scale
            if t.is_integer:
                return v.astype(jnp.int64), 0
            return v, None  # float operand -> float path

        lu, ls = unscaled(lv, lt)
        ru, rs = unscaled(rv, rt)
        if ls is None or rs is None or op is Op.DIV:
            def to_f(v, t):
                from blaze_tpu.exprs import int128 as i128

                if t.is_wide_decimal:
                    f = i128.to_float64(v[:, 0], v[:, 1])
                else:
                    f = v.astype(jnp.float64)
                return f / (
                    10.0 ** t.scale if t.id is TypeId.DECIMAL else 1.0
                )

            lf = to_f(lv, lt)
            rf = to_f(rv, rt)
            return self._div(lf, rf, DataType.float64(), m) if op is Op.DIV \
                else (_apply_float_op(op, lf, rf), m)
        if (
            lt.is_wide_decimal or rt.is_wide_decimal
            or out_t.is_wide_decimal
        ):
            return self._decimal_arith_wide(
                op, lu, ru, lt, rt, ls, rs, out_t, m
            )
        target = out_t.scale
        lu = lu * (10 ** (target - ls)) if op in (Op.ADD, Op.SUB) else lu
        ru = ru * (10 ** (target - rs)) if op in (Op.ADD, Op.SUB) else ru
        if op is Op.ADD:
            return lu + ru, m
        if op is Op.SUB:
            return lu - ru, m
        if op is Op.MUL:
            # scale(l)+scale(r) -> rescale down to out scale
            prod = lu * ru
            down = ls + rs - target
            if down > 0:
                prod = _java_div(prod, jnp.asarray(10 ** down, jnp.int64))
            return prod, m
        if op is Op.MOD:
            return self._mod(lu, ru, out_t, m)
        raise NotImplementedError(f"decimal {op}")

    def _decimal_arith_wide(self, op, lu, ru, lt, rt, ls, rs,
                            out_t, m) -> CV:
        """128-bit decimal +/-/* on device (exprs/int128.py): limb-pair
        or narrow operands enter as sign+magnitude, rescale to the
        result scale, combine, and overflow beyond decimal(38) NULLs
        the row (Spark non-ANSI). Rounding on the multiply's
        rescale-down is HALF_UP, matching the host tier."""
        from blaze_tpu.exprs import int128 as i128

        def mag(v, t):
            if t.is_wide_decimal:
                return i128.from_limbs(v[:, 0], v[:, 1])
            return i128.from_narrow(v)

        a = mag(lu, lt)
        b = mag(ru, rt)
        target = out_t.scale
        if op in (Op.ADD, Op.SUB):
            alo, ahi, o1 = i128.rescale_up(a[0], a[1], target - ls)
            blo, bhi, o2 = i128.rescale_up(b[0], b[1], target - rs)
            bneg = b[2] ^ (op is Op.SUB)
            mlo, mhi, neg, ok = i128.signed_add(
                (alo, ahi, a[2]), (blo, bhi, bneg)
            )
            ok = ok & ~o1 & ~o2
        elif op is Op.MUL:
            down = ls + rs - target
            assert down >= 0, (ls, rs, target)
            mlo, mhi, neg, ok = i128.signed_mul(a, b, down)
        else:
            raise NotImplementedError(f"wide decimal {op}")
        lo, hi = i128.to_limbs(mlo, mhi, neg)
        mask = and_validity(m, ok)
        # a wide operand always promotes to a wide result (promote()
        # keeps max precision > 18, and DIV was routed to float64
        # above), so the output is the stacked limb pair
        assert out_t.is_wide_decimal, out_t
        return jnp.stack([lo, hi], axis=1), mask

    def _logic(self, e: ir.BinaryOp) -> CV:
        lv, lm = self._eval(e.left)
        rv, rm = self._eval(e.right)
        lv = lv.astype(jnp.bool_)
        rv = rv.astype(jnp.bool_)
        lvalid = valid_or_true(lm, lv.shape)
        rvalid = valid_or_true(rm, rv.shape)
        if lm is None and rm is None:
            return (lv & rv if e.op is Op.AND else lv | rv), None
        if e.op is Op.AND:
            # known iff either side is known-FALSE or both sides are known;
            # lv&rv is already correct in every known case (garbage values on
            # invalid rows are ANDed with a known False)
            known = (lvalid & ~lv) | (rvalid & ~rv) | (lvalid & rvalid)
            return lv & rv, known
        else:  # OR: known iff either side is known-TRUE or both known
            known = (lvalid & lv) | (rvalid & rv) | (lvalid & rvalid)
            return lv | rv, known

    def _in_list(self, e: ir.InList) -> CV:
        v, m = self._eval(e.child)
        any_null_item = any(
            isinstance(x, ir.Literal) and x.value is None
            for x in e.values
        )
        non_null = [
            x for x in e.values
            if not (isinstance(x, ir.Literal) and x.value is None)
        ]
        all_literals = all(isinstance(x, ir.Literal) for x in non_null)
        ct = infer_dtype(e.child, self.schema)
        if all_literals and len(non_null) > 8 and ct.is_numeric:
            # InSet fast path (the reference keeps a separate InSet node
            # for exactly this case): one searchsorted over a sorted
            # constant table instead of an OR-chain of comparisons
            phys = _np_dtype(ct)
            table = np.sort(
                np.asarray([x.value for x in non_null], dtype=phys)
            )
            tbl = jnp.asarray(table)
            pos = jnp.clip(
                jnp.searchsorted(tbl, v.astype(phys)), 0, len(table) - 1
            )
            hit = jnp.take(tbl, pos) == v.astype(phys)
        else:
            hit = jnp.zeros(self.capacity, dtype=jnp.bool_)
            for item in non_null:
                iv, im = self._eval(item)
                ict = promote(ct, infer_dtype(item, self.schema))
                phys = _np_dtype(ict)
                hit = hit | (v.astype(phys) == iv.astype(phys))
        # Spark: x IN (...) is NULL if no match and any element (or x) is NULL
        validity = m
        if any_null_item:
            validity = and_validity(validity, hit)
        result = ~hit if e.negated else hit
        return result, validity

    def _case(self, e: ir.CaseWhen) -> CV:
        out_t = infer_dtype(e, self.schema)
        phys = _np_dtype(out_t)
        if e.otherwise is not None:
            acc_v, acc_m = self._eval(e.otherwise)
            acc_v = acc_v.astype(phys)
        else:
            acc_v = jnp.zeros(self.capacity, dtype=phys)
            acc_m = jnp.zeros(self.capacity, dtype=jnp.bool_)
        # fold branches right-to-left so the first matching wins
        for cond, result in reversed(e.branches):
            c = self.evaluate_predicate(cond)
            rv, rm = self._eval(result)
            rv = rv.astype(phys)
            acc_v = jnp.where(c, rv, acc_v)
            if rm is None and acc_m is None:
                acc_m = None
            else:
                rvalid = valid_or_true(rm, rv.shape)
                avalid = valid_or_true(acc_m, acc_v.shape)
                acc_m = jnp.where(c, rvalid, avalid)
        return acc_v, acc_m

    def _coalesce(self, e: ir.Coalesce) -> CV:
        out_t = infer_dtype(e, self.schema)
        phys = _np_dtype(out_t)
        acc_v = jnp.zeros(self.capacity, dtype=phys)
        acc_m = jnp.zeros(self.capacity, dtype=jnp.bool_)
        for a in reversed(e.args):
            v, m = self._eval(a)
            v = v.astype(phys)
            valid = valid_or_true(m, v.shape)
            acc_v = jnp.where(valid, v, acc_v)
            acc_m = valid | acc_m
        return acc_v, acc_m

    # ------------------------------------------------------------------
    def _scalar_fn(self, e: ir.ScalarFn) -> CV:
        n = e.name
        # fns with a literal config argument evaluate only the data args
        if n == "date_part":
            part = e.args[0]
            assert isinstance(part, ir.Literal), "date_part needs literal"
            v, m = self._eval(e.args[1])
            return _date_part(str(part.value).lower(), v), m
        if n == "trunc_date":
            part = e.args[1]
            assert isinstance(part, ir.Literal), "trunc needs literal fmt"
            v, m = self._eval(e.args[0])
            return _trunc_date(str(part.value).lower(), v), m
        args = [self._eval(a) for a in e.args]
        m = None
        for _, am in args:
            m = and_validity(m, am)
        vs = [v for v, _ in args]

        def f64(x):
            return x.astype(jnp.float64)

        unary_f64 = {
            "sqrt": jnp.sqrt,
            "exp": jnp.exp,
            "ln": jnp.log,
            "log": jnp.log,
            "log2": jnp.log2,
            "log10": jnp.log10,
            "sin": jnp.sin,
            "cos": jnp.cos,
            "tan": jnp.tan,
            "asin": jnp.arcsin,
            "acos": jnp.arccos,
            "atan": jnp.arctan,
            "sinh": jnp.sinh,
            "cosh": jnp.cosh,
            "tanh": jnp.tanh,
        }
        if n in unary_f64:
            return unary_f64[n](f64(vs[0])), m
        if n == "abs":
            return jnp.abs(vs[0]), m
        if n in ("negative",):
            return -vs[0], m
        if n in ("positive",):
            return vs[0], m
        if n == "signum":
            return jnp.sign(f64(vs[0])), m
        if n == "pow":
            return jnp.power(f64(vs[0]), f64(vs[1])), m
        if n == "atan2":
            return jnp.arctan2(f64(vs[0]), f64(vs[1])), m
        if n == "isnan":
            v = vs[0]
            return (
                jnp.isnan(v) if jnp.issubdtype(v.dtype, jnp.floating)
                else jnp.zeros_like(v, dtype=jnp.bool_)
            ), m
        if n == "nanvl":
            a, b = f64(vs[0]), f64(vs[1])
            return jnp.where(jnp.isnan(a), b, a), m
        if n in ("ceil", "floor"):
            src_t = infer_dtype(e.args[0], self.schema)
            v = vs[0]
            if src_t.is_integer:
                return v, m
            fn = jnp.ceil if n == "ceil" else jnp.floor
            return fn(f64(v)).astype(jnp.int64), m
        if n == "round":
            src_t = infer_dtype(e.args[0], self.schema)
            if src_t.is_integer:
                # round(int, d>=0) is the identity; d<0 rounds to a
                # power of ten with HALF_UP (Spark round(1250,-2)=1300).
                # Only a literal scale is supported on the int path.
                if len(e.args) > 1 and not isinstance(
                    e.args[1], ir.Literal
                ):
                    raise NotImplementedError(
                        "round(int, scale) needs a literal scale"
                    )
                d = e.args[1].value if len(e.args) > 1 else 0
                if d is None or d >= 0:
                    return vs[0], m
                p = 10 ** (-d)
                v = vs[0].astype(jnp.int64)
                q = v // p
                r = v - q * p
                half = jnp.where(v >= 0, 2 * r >= p, 2 * r > p)
                return ((q + half.astype(jnp.int64)) * p).astype(
                    vs[0].dtype
                ), m
            # Spark HALF_UP rounding (not banker's), at optional scale
            # (round(x, d) -> HALF_UP at 10^-d)
            v = f64(vs[0])
            if len(vs) > 1:
                scale = jnp.power(
                    jnp.float64(10.0), f64(vs[1])
                )
                v = v * scale
                r = jnp.where(
                    v >= 0, jnp.floor(v + 0.5), jnp.ceil(v - 0.5)
                )
                return r / scale, m
            return jnp.where(
                v >= 0, jnp.floor(v + 0.5), jnp.ceil(v - 0.5)
            ), m
        if n == "trunc" or n == "truncate":
            return jnp.trunc(f64(vs[0])), m
        if n in ("greatest", "least"):
            # Spark: NULL operands are skipped; NULL only when all are
            phys = _np_dtype(infer_dtype(e, self.schema))
            acc_v = None
            acc_m = None
            for v, vm in args:
                v = v.astype(phys)
                valid = valid_or_true(vm, v.shape)
                if acc_v is None:
                    acc_v, acc_m = v, valid
                    continue
                both = acc_m & valid
                pick = (
                    jnp.maximum(acc_v, v) if n == "greatest"
                    else jnp.minimum(acc_v, v)
                )
                acc_v = jnp.where(
                    both, pick, jnp.where(valid, v, acc_v)
                )
                acc_m = acc_m | valid
            return acc_v, acc_m
        if n == "pmod":
            # non-negative modulo (Spark pmod expression)
            zero = vs[1] == 0
            safe = jnp.where(zero, jnp.ones_like(vs[1]), vs[1])
            r = lax.rem(vs[0], safe)
            r = jnp.where(r < 0, r + jnp.abs(safe), r)
            return r, and_validity(m, ~zero)
        if n == "spark_unscaled_value":
            # decimal (i64-unscaled repr) -> bigint: identity on device
            # (reference spark_ext_function.rs:8)
            return vs[0].astype(jnp.int64), m
        if n == "spark_make_decimal":
            # bigint -> decimal unscaled: identity (spark_ext_function.rs:29)
            return vs[0].astype(jnp.int64), m
        if n in ("year", "month", "day", "dayofmonth", "quarter",
                 "dayofweek", "dayofyear"):
            return _date_part(n, vs[0]), m
        if n == "null_if":
            # NULL when both args are equal (reference NullIf)
            a, b = vs[0], vs[1]
            eq = a == b.astype(a.dtype)
            if m is not None:
                eq = eq & m
            base = args[0][1]
            out_m = (~eq) if base is None else (base & ~eq)
            return a, out_m
        raise NotImplementedError(f"device scalar fn {n}")


def _apply_float_op(op: Op, lv, rv):
    return {
        Op.ADD: lambda: lv + rv,
        Op.SUB: lambda: lv - rv,
        Op.MUL: lambda: lv * rv,
    }[op]()


def _java_div(a, b):
    """Integer division truncating toward zero (Java/Spark semantics)."""
    return lax.div(a, b)


def _days_from_civil(y, mth, d):
    """Inverse of _date_part: civil date -> days since epoch (Hinnant)."""
    y = y - jnp.where(mth <= 2, 1, 0)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(mth > 2, mth - 3, mth + 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(
        yoe, 100
    ) + doy
    return (era * 146_097 + doe - 719_468).astype(jnp.int32)


def _trunc_date(fmt: str, days32) -> jax.Array:
    """TruncDate: round a date32 down to year/quarter/month/week start."""
    y = _date_part("year", days32).astype(jnp.int64)
    mth = _date_part("month", days32).astype(jnp.int64)
    if fmt in ("year", "yyyy", "yy"):
        return _days_from_civil(y, jnp.ones_like(mth), jnp.ones_like(mth))
    if fmt in ("quarter",):
        qm = ((mth - 1) // 3) * 3 + 1
        return _days_from_civil(y, qm, jnp.ones_like(mth))
    if fmt in ("month", "mon", "mm"):
        return _days_from_civil(y, mth, jnp.ones_like(mth))
    if fmt in ("week",):
        d = days32.astype(jnp.int64)
        # 1970-01-01 was a Thursday; Monday-start weeks
        dow = jax.lax.rem(d + 3, jnp.int64(7))
        dow = jnp.where(dow < 0, dow + 7, dow)
        return (d - dow).astype(jnp.int32)
    raise NotImplementedError(f"trunc_date {fmt}")


def _date_part(part: str, days32) -> jax.Array:
    """Extract year/month/day from date32 (days since epoch) using the
    civil-from-days algorithm (Howard Hinnant's public-domain formulation) -
    pure integer ops, vectorizes on the VPU."""
    z = days32.astype(jnp.int64) + 719_468
    era = jnp.floor_divide(z, 146_097)
    doe = z - era * 146_097  # [0, 146096]
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096),
        365,
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    month = mp + jnp.where(mp < 10, 3, -9)
    year = y + jnp.where(month <= 2, 1, 0)
    if part == "year":
        return year.astype(jnp.int32)
    if part == "month":
        return month.astype(jnp.int32)
    if part in ("day", "dayofmonth"):
        return d.astype(jnp.int32)
    if part == "quarter":
        return (jnp.floor_divide(month - 1, 3) + 1).astype(jnp.int32)
    if part in ("dayofweek", "dow"):
        # Spark dayofweek: 1 = Sunday ... 7 = Saturday
        dd = days32.astype(jnp.int64)
        w = jax.lax.rem(dd + 4, jnp.int64(7))
        w = jnp.where(w < 0, w + 7, w)
        return (w + 1).astype(jnp.int32)
    if part in ("dayofyear", "doy"):
        jan1 = _days_from_civil(
            year, jnp.ones_like(year), jnp.ones_like(year)
        )
        return (days32.astype(jnp.int64) - jan1 + 1).astype(jnp.int32)
    raise NotImplementedError(part)
