"""Expression IR.

Frozen dataclass tree; hashable so compiled pipelines can key jit caches on
(plan fingerprint, shape bucket). Mirrors the reference's physical expression
proto surface (plan.proto PhysicalExprNode; NativeConverters.scala convertExpr
coverage) without copying its layout.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple

from blaze_tpu.types import DataType, Schema


class Op(enum.Enum):
    # arithmetic
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"
    # comparison
    EQ = "="
    NEQ = "!="
    LT = "<"
    LTE = "<="
    GT = ">"
    GTE = ">="
    # logic (three-valued)
    AND = "and"
    OR = "or"
    # bitwise
    BITAND = "&"
    BITOR = "|"
    BITXOR = "^"
    SHL = "<<"
    SHR = ">>"


COMPARISON_OPS = {Op.EQ, Op.NEQ, Op.LT, Op.LTE, Op.GT, Op.GTE}
LOGIC_OPS = {Op.AND, Op.OR}


class Expr:
    """Base class. Subclasses are frozen dataclasses."""

    def _b(self, other, op: Op) -> "BinaryOp":
        return BinaryOp(op, self, _lit(other))

    # operator sugar for tests / plan builders
    def __add__(self, o):
        return self._b(o, Op.ADD)

    def __sub__(self, o):
        return self._b(o, Op.SUB)

    def __mul__(self, o):
        return self._b(o, Op.MUL)

    def __truediv__(self, o):
        return self._b(o, Op.DIV)

    def __mod__(self, o):
        return self._b(o, Op.MOD)

    def __eq__(self, o):  # type: ignore[override]
        if isinstance(o, (Expr, int, float, str, bool)):
            return self._b(o, Op.EQ)
        return NotImplemented

    def __ne__(self, o):  # type: ignore[override]
        if isinstance(o, (Expr, int, float, str, bool)):
            return self._b(o, Op.NEQ)
        return NotImplemented

    def __lt__(self, o):
        return self._b(o, Op.LT)

    def __le__(self, o):
        return self._b(o, Op.LTE)

    def __gt__(self, o):
        return self._b(o, Op.GT)

    def __ge__(self, o):
        return self._b(o, Op.GTE)

    def __and__(self, o):
        return self._b(o, Op.AND)

    def __or__(self, o):
        return self._b(o, Op.OR)

    def __invert__(self):
        return Not(self)

    def __hash__(self):
        # dataclass eq=False subclasses inherit identity hash; frozen
        # dataclasses below override via generated __hash__.
        return super().__hash__()

    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "IsNotNull":
        return IsNotNull(self)

    def isin(self, values) -> "InList":
        return InList(self, tuple(_lit(v) for v in values))

    def cast(self, to: DataType) -> "Cast":
        return Cast(self, to)


def _lit(v) -> Expr:
    if isinstance(v, Expr):
        return v
    return Literal.infer(v)


def _expr_dc(cls):
    """Frozen dataclass with structural hash; __eq__ stays the sugar above."""
    cls = dataclasses.dataclass(frozen=True, eq=False, repr=True)(cls)

    def _hash(self):
        return hash(
            (cls.__name__,)
            + tuple(
                tuple(v) if isinstance(v, list) else v
                for v in (
                    getattr(self, f.name) for f in dataclasses.fields(cls)
                )
            )
        )

    cls.__hash__ = _hash
    return cls


@_expr_dc
class Literal(Expr):
    value: object
    dtype: DataType

    @staticmethod
    def infer(v) -> "Literal":
        if v is None:
            return Literal(None, DataType.null())
        if isinstance(v, bool):
            return Literal(v, DataType.bool_())
        if isinstance(v, int):
            return Literal(v, DataType.int64())
        if isinstance(v, float):
            return Literal(v, DataType.float64())
        if isinstance(v, str):
            return Literal(v, DataType.utf8())
        if isinstance(v, bytes):
            return Literal(v, DataType.binary())
        raise TypeError(f"cannot infer literal type of {v!r}")


@_expr_dc
class Col(Expr):
    """Unresolved column reference by name."""

    name: str

    def bind(self, schema: Schema) -> "BoundCol":
        i = schema.index_of(self.name)
        return BoundCol(i, schema.fields[i].dtype)


@_expr_dc
class BoundCol(Expr):
    """Resolved column reference by position."""

    index: int
    dtype: DataType


@_expr_dc
class Cast(Expr):
    child: Expr
    to: DataType


@_expr_dc
class BinaryOp(Expr):
    op: Op
    left: Expr
    right: Expr


@_expr_dc
class Not(Expr):
    child: Expr


@_expr_dc
class Negate(Expr):
    child: Expr


@_expr_dc
class IsNull(Expr):
    child: Expr


@_expr_dc
class IsNotNull(Expr):
    child: Expr


@_expr_dc
class InList(Expr):
    child: Expr
    values: Tuple[Expr, ...]
    negated: bool = False


@_expr_dc
class If(Expr):
    cond: Expr
    then: Expr
    otherwise: Expr


@_expr_dc
class CaseWhen(Expr):
    """CASE [expr] WHEN v1 THEN r1 ... ELSE e END.

    Normalized at build time to predicate form: branches are
    (condition, result) pairs."""

    branches: Tuple[Tuple[Expr, Expr], ...]
    otherwise: Optional[Expr] = None


@_expr_dc
class ScalarFn(Expr):
    """Named scalar function (reference scalar fn surface,
    NativeConverters.scala:395-489 + spark_ext_function.rs)."""

    name: str
    args: Tuple[Expr, ...]


@_expr_dc
class Coalesce(Expr):
    args: Tuple[Expr, ...]


class AggFn(enum.Enum):
    MIN = "min"
    MAX = "max"
    SUM = "sum"
    AVG = "avg"
    COUNT = "count"  # count(expr): non-null rows
    COUNT_STAR = "count_star"
    VAR_SAMP = "var_samp"
    VAR_POP = "var_pop"
    STDDEV_SAMP = "stddev_samp"
    STDDEV_POP = "stddev_pop"
    FIRST = "first"
    LAST = "last"


@_expr_dc
class AggExpr(Expr):
    """Aggregate call; only valid inside Aggregate plan nodes."""

    fn: AggFn
    child: Optional[Expr]  # None for COUNT(*)


# ---------------------------------------------------------------------------
# tree utilities
# ---------------------------------------------------------------------------

def children(e: Expr) -> Tuple[Expr, ...]:
    if isinstance(e, (Literal, Col, BoundCol)):
        return ()
    if isinstance(e, Cast):
        return (e.child,)
    if isinstance(e, BinaryOp):
        return (e.left, e.right)
    if isinstance(e, (Not, Negate, IsNull, IsNotNull)):
        return (e.child,)
    if isinstance(e, InList):
        return (e.child,) + e.values
    if isinstance(e, If):
        return (e.cond, e.then, e.otherwise)
    if isinstance(e, CaseWhen):
        out = []
        for c, r in e.branches:
            out += [c, r]
        if e.otherwise is not None:
            out.append(e.otherwise)
        return tuple(out)
    if isinstance(e, (ScalarFn, Coalesce)):
        return tuple(e.args)
    if isinstance(e, AggExpr):
        return (e.child,) if e.child is not None else ()
    raise TypeError(f"unknown expr {type(e)}")


def with_children(e: Expr, kids) -> Expr:
    """Shallow rebuild of a node with replacement children (same arity and
    order as `children(e)`)."""
    kids = list(kids)
    if isinstance(e, (Literal, Col, BoundCol)):
        return e
    if isinstance(e, Cast):
        return Cast(kids[0], e.to)
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, kids[0], kids[1])
    if isinstance(e, Not):
        return Not(kids[0])
    if isinstance(e, Negate):
        return Negate(kids[0])
    if isinstance(e, IsNull):
        return IsNull(kids[0])
    if isinstance(e, IsNotNull):
        return IsNotNull(kids[0])
    if isinstance(e, InList):
        return InList(kids[0], tuple(kids[1:]), e.negated)
    if isinstance(e, If):
        return If(kids[0], kids[1], kids[2])
    if isinstance(e, CaseWhen):
        nb = len(e.branches)
        branches = tuple(
            (kids[2 * i], kids[2 * i + 1]) for i in range(nb)
        )
        otherwise = kids[2 * nb] if e.otherwise is not None else None
        return CaseWhen(branches, otherwise)
    if isinstance(e, ScalarFn):
        return ScalarFn(e.name, tuple(kids))
    if isinstance(e, Coalesce):
        return Coalesce(tuple(kids))
    if isinstance(e, AggExpr):
        return AggExpr(e.fn, kids[0] if kids else None)
    raise TypeError(f"unknown expr {type(e)}")


def transform(e: Expr, fn) -> Expr:
    """Bottom-up rewrite."""
    if isinstance(e, Cast):
        e = Cast(transform(e.child, fn), e.to)
    elif isinstance(e, BinaryOp):
        e = BinaryOp(e.op, transform(e.left, fn), transform(e.right, fn))
    elif isinstance(e, Not):
        e = Not(transform(e.child, fn))
    elif isinstance(e, Negate):
        e = Negate(transform(e.child, fn))
    elif isinstance(e, IsNull):
        e = IsNull(transform(e.child, fn))
    elif isinstance(e, IsNotNull):
        e = IsNotNull(transform(e.child, fn))
    elif isinstance(e, InList):
        e = InList(
            transform(e.child, fn),
            tuple(transform(v, fn) for v in e.values),
            e.negated,
        )
    elif isinstance(e, If):
        e = If(
            transform(e.cond, fn),
            transform(e.then, fn),
            transform(e.otherwise, fn),
        )
    elif isinstance(e, CaseWhen):
        e = CaseWhen(
            tuple(
                (transform(c, fn), transform(r, fn)) for c, r in e.branches
            ),
            transform(e.otherwise, fn) if e.otherwise is not None else None,
        )
    elif isinstance(e, ScalarFn):
        e = ScalarFn(e.name, tuple(transform(a, fn) for a in e.args))
    elif isinstance(e, Coalesce):
        e = Coalesce(tuple(transform(a, fn) for a in e.args))
    elif isinstance(e, AggExpr):
        e = AggExpr(
            e.fn, transform(e.child, fn) if e.child is not None else None
        )
    return fn(e)


def bind(e: Expr, schema: Schema) -> Expr:
    """Resolve Col -> BoundCol against a schema."""

    def rule(x: Expr) -> Expr:
        if isinstance(x, Col):
            return x.bind(schema)
        return x

    return transform(e, rule)
