"""Host (pyarrow) expression evaluator for string-typed subtrees.

TPUs have no string compute, so the pipeline compiler splits each expression
tree at the type boundary (SURVEY 7 design stance): any node with a direct
string-typed input is evaluated here, over the batch's real utf8 data, and
re-enters the device pipeline as a precomputed column. Null propagation
comes from pyarrow compute kernels natively (matching Spark for the ops
used). Also serves as the engine-independent differential reference for
device results in tests.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from blaze_tpu.types import DataType, Schema, TypeId, to_arrow_type
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import Op


class HostEvaluator:
    """Evaluates bound expressions against positional pyarrow arrays
    (full batch rows, no selection applied - alignment matters)."""

    def __init__(self, schema: Schema, arrays: List[pa.Array]):
        self.schema = schema
        self.arrays = arrays
        self.length = len(arrays[0]) if arrays else 0

    def evaluate(self, e: ir.Expr) -> pa.Array:
        if isinstance(e, ir.BoundCol):
            return self.arrays[e.index]
        if isinstance(e, ir.Literal):
            if e.value is None:
                return pa.nulls(self.length)
            return pa.array(
                [e.value] * self.length, type=to_arrow_type(e.dtype)
            )
        if isinstance(e, ir.Cast):
            child = self.evaluate(e.child)
            return pc.cast(child, to_arrow_type(e.to), safe=False)
        if isinstance(e, ir.BinaryOp):
            return self._binary(e)
        if isinstance(e, ir.Not):
            return pc.invert(self.evaluate(e.child))
        if isinstance(e, ir.IsNull):
            return pc.is_null(self.evaluate(e.child))
        if isinstance(e, ir.IsNotNull):
            return pc.is_valid(self.evaluate(e.child))
        if isinstance(e, ir.InList):
            v = self.evaluate(e.child)
            items = [
                x.value for x in e.values
                if isinstance(x, ir.Literal) and x.value is not None
            ]
            out = pc.is_in(v, value_set=pa.array(items))
            if e.negated:
                out = pc.invert(out)
            # propagate child nulls (pc.is_in treats null as not-found)
            return pc.if_else(pc.is_valid(v), out, pa.nulls(self.length))
        if isinstance(e, ir.If):
            return pc.if_else(
                self.evaluate(e.cond),
                self.evaluate(e.then),
                self.evaluate(e.otherwise),
            )
        if isinstance(e, ir.CaseWhen):
            acc = (
                self.evaluate(e.otherwise)
                if e.otherwise is not None
                else pa.nulls(self.length)
            )
            for cond, res in reversed(e.branches):
                c = self.evaluate(cond)
                c = pc.fill_null(c, False)
                acc = pc.if_else(c, self.evaluate(res), acc)
            return acc
        if isinstance(e, ir.Coalesce):
            return pc.coalesce(*[self.evaluate(a) for a in e.args])
        if isinstance(e, ir.ScalarFn):
            return self._scalar_fn(e)
        raise NotImplementedError(f"host eval: {type(e).__name__}")

    def _binary(self, e: ir.BinaryOp) -> pa.Array:
        l = self.evaluate(e.left)
        r = self.evaluate(e.right)
        cmp = {
            Op.EQ: pc.equal,
            Op.NEQ: pc.not_equal,
            Op.LT: pc.less,
            Op.LTE: pc.less_equal,
            Op.GT: pc.greater,
            Op.GTE: pc.greater_equal,
        }
        if e.op in cmp:
            return cmp[e.op](l, r)
        if e.op is Op.AND:
            return pc.and_kleene(l, r)
        if e.op is Op.OR:
            return pc.or_kleene(l, r)
        arith = {
            Op.ADD: pc.add,
            Op.SUB: pc.subtract,
            Op.MUL: pc.multiply,
        }
        if e.op in arith:
            return arith[e.op](l, r)
        if e.op is Op.DIV:
            # Spark: divide-by-zero -> NULL
            zero = pc.equal(r, pa.scalar(0, type=r.type))
            safe = pc.if_else(zero, pa.scalar(1, type=r.type), r)
            out = pc.divide(l, safe)
            return pc.if_else(zero, pa.nulls(self.length, out.type), out)
        if e.op is Op.MOD:
            # Spark %: truncated remainder, sign of the dividend
            # (device parity: lax.rem in exprs/eval.py _mod);
            # mod-by-zero -> NULL. pyarrow has no modulo kernel, so
            # build it from trunc-division: l - trunc(l/r)*r.
            zero = pc.equal(r, pa.scalar(0, type=r.type))
            safe = pc.if_else(zero, pa.scalar(1, type=r.type), r)
            quot = pc.divide(l, safe)  # integer divide truncates
            if pa.types.is_floating(quot.type):
                quot = pc.trunc(quot)
            rem = pc.subtract(l, pc.multiply(quot, safe))
            return pc.if_else(zero, pa.nulls(self.length, rem.type), rem)
        raise NotImplementedError(f"host binary {e.op}")

    def _scalar_fn(self, e: ir.ScalarFn) -> pa.Array:
        n = e.name
        args = [self.evaluate(a) for a in e.args]
        if n == "lower":
            return pc.utf8_lower(args[0])
        if n == "upper":
            return pc.utf8_upper(args[0])
        if n == "trim":
            return pc.utf8_trim_whitespace(args[0])
        if n == "ltrim":
            return pc.utf8_ltrim_whitespace(args[0])
        if n == "rtrim":
            return pc.utf8_rtrim_whitespace(args[0])
        if n in ("length", "char_length"):
            return pc.cast(pc.utf8_length(args[0]), pa.int32())
        if n == "reverse":
            return pc.utf8_reverse(args[0])
        if n == "starts_with":
            return pc.starts_with(args[0], pattern=_pat(e.args[1]))
        if n == "ends_with":
            return pc.ends_with(args[0], pattern=_pat(e.args[1]))
        if n == "contains":
            return pc.match_substring(args[0], pattern=_pat(e.args[1]))
        if n == "like":
            return pc.match_like(args[0], pattern=_pat(e.args[1]))
        if n == "substring":
            # Spark 1-based start; 0 behaves like 1; negative counts from
            # the end
            start = _int_lit(e.args[1])
            length = _int_lit(e.args[2]) if len(e.args) > 2 else None
            if start > 0:
                start0 = start - 1
            elif start == 0:
                start0 = 0
            else:
                start0 = start  # arrow slice supports negative starts
            if length is None:
                stop = None
            else:
                stop = start0 + length
                if start0 < 0 and stop >= 0:
                    stop = None  # reaches the end of the string
            return pc.utf8_slice_codeunits(args[0], start0, stop)
        if n == "concat":
            return pc.binary_join_element_wise(
                *args, "", null_handling="emit_null"
            )
        if n == "replace":
            return pc.replace_substring(
                args[0], pattern=_pat(e.args[1]),
                replacement=_pat(e.args[2]),
            )
        if n == "null_if":
            eq = pc.fill_null(pc.equal(args[0], args[1]), False)
            return pc.if_else(eq, pa.nulls(self.length, args[0].type),
                              args[0])
        if n == "octet_length":
            return pc.cast(pc.binary_length(args[0]), pa.int32())
        if n in ("md5", "sha224", "sha256", "sha384", "sha512"):
            # digest fns (reference Md5/Sha2 cases): host-only, hashlib
            import hashlib

            fn = getattr(hashlib, n)
            vals = args[0].to_pylist()
            out = [
                None if v is None else fn(
                    v.encode("utf-8") if isinstance(v, str) else v
                ).hexdigest()
                for v in vals
            ]
            return pa.array(out, type=pa.utf8())
        raise NotImplementedError(f"host scalar fn {n}")


def _pat(e: ir.Expr) -> str:
    assert isinstance(e, ir.Literal), "pattern must be a literal"
    return e.value


def _int_lit(e: ir.Expr) -> int:
    assert isinstance(e, ir.Literal), "argument must be a literal"
    return int(e.value)
