"""In-memory scan: the test/bench fixture leaf (DataFusion MemoryExec
analog; the reference's join unit tests are built on the same pattern,
sort_merge_join_exec.rs build_table fixtures)."""

from __future__ import annotations

from typing import Iterator, List, Sequence

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import ExecContext, PhysicalOp


class MemoryScanExec(PhysicalOp):
    def __init__(self, partitions: Sequence[List[ColumnBatch]],
                 schema: Schema):
        self.partitions = list(partitions)
        self._schema = schema
        self.children = []

    @staticmethod
    def from_batches(batches: List[ColumnBatch]) -> "MemoryScanExec":
        assert batches, "use from_schema for empty scans"
        return MemoryScanExec([batches], batches[0].schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return len(self.partitions)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        for b in self.partitions[partition]:
            yield b
