"""Streaming sort-merge join for pre-sorted inputs.

The reference's flagship custom operator streams both sorted sides with
single-row cursors (sort_merge_join_exec.rs:293-601). Row cursors are
hostile to vectorization (SURVEY 7 hard parts), so this operator streams
at BATCH granularity instead: a sliding window of right-side batches is
kept only as wide as the current left batch's key range requires
(sorted-input invariant: once the left stream has passed a key, right rows
below it can never match again), and each left batch joins against the
window with the shared vectorized core. Memory is O(window), not O(side).

Work is O(n) amortized like the reference's cursor merge: every window
batch carries its OWN lazily-built join core (hash + sort index), built
exactly once for the batch's lifetime in the window, and each left batch
probes only the window entries whose key range overlaps its own - no
re-concatenation, no re-sorting per left batch (VERDICT r2 Weak #5).

Contract: both inputs sorted ascending by their join keys (the planner
guarantees this the same way Spark does for SMJ - sort nodes under the
join). All six join types supported; RIGHT/FULL emit evicted-unmatched
window rows incrementally.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch, row_mask
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.joins import (
    JoinType,
    _JoinCore,
    _joined_schema,
    _null_side,
)
from blaze_tpu.ops.util import ensure_compacted


class _WindowEntry:
    """One right-side batch resident in the sliding window, with its
    join core built lazily ON FIRST PROBE and persisted for the entry's
    whole window lifetime (the incremental analog of the reference's
    right cursor position)."""

    __slots__ = ("batch", "min_key", "max_key", "core")

    def __init__(self, batch: ColumnBatch, keys: np.ndarray):
        self.batch = batch
        self.min_key = keys[0]
        self.max_key = keys[-1]
        self.core: Optional[_JoinCore] = None

    def ensure_core(self, right_keys: Sequence[int]) -> "_JoinCore":
        if self.core is None:
            self.core = _JoinCore(self.batch, list(right_keys))
        return self.core

    def matched_rows(self) -> np.ndarray:
        """Host bool mask of window rows some probe matched (valid after
        the entry's last emit_pairs)."""
        if self.core is None:
            return np.zeros(self.batch.num_rows, dtype=bool)
        return np.asarray(self.core.matched_build)[
            : self.batch.num_rows
        ]


def _key_matrix(cb: ColumnBatch, key_idx: Sequence[int]) -> np.ndarray:
    """(num_rows, n_keys) host array of key values for range bookkeeping
    (tiny D2H: keys only)."""
    cols = []
    for i in key_idx:
        c = cb.columns[i]
        cols.append(np.asarray(c.values)[: cb.num_rows])
    return np.stack(cols, axis=1) if cols else np.zeros((cb.num_rows, 0))


def _tuple_lt(a: np.ndarray, b: np.ndarray) -> bool:
    """Lexicographic a < b for 1-D key tuples."""
    for x, y in zip(a, b):
        if x < y:
            return True
        if x > y:
            return False
    return False


class StreamingSortMergeJoinExec(PhysicalOp):
    def __init__(self, left: PhysicalOp, right: PhysicalOp,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 join_type: JoinType = JoinType.INNER):
        if join_type is JoinType.LEFT_ANTI_NULL_AWARE:
            raise NotImplementedError(
                "null-aware anti join needs the whole build side (any "
                "NULL key empties the result) - materializing SMJ only"
            )
        self.children = [left, right]
        self.left_keys = [left.schema.index_of(k) for k in left_keys]
        self.right_keys = [right.schema.index_of(k) for k in right_keys]
        for side, idxs in ((left, self.left_keys),
                           (right, self.right_keys)):
            for i in idxs:
                if side.schema.fields[i].dtype.is_string_like:
                    raise NotImplementedError(
                        "streaming SMJ needs ordered fixed-width keys; "
                        "string-keyed joins use the materializing SMJ"
                    )
        self.join_type = join_type
        self._schema = _joined_schema(left.schema, right.schema, join_type)

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return (f"{self.join_type.name};l={self.left_keys};"
                f"r={self.right_keys}")

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return self.children[0].partition_count

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        left, right = self.children
        jt = self.join_type
        right_it = right.execute(partition, ctx)
        window: List[_WindowEntry] = []
        right_done = False

        def pull_right() -> bool:
            nonlocal right_done
            if right_done:
                return False
            for rb in right_it:
                rb = ensure_compacted(rb)
                if rb.num_rows == 0:
                    continue
                window.append(
                    _WindowEntry(rb, _key_matrix(rb, self.right_keys))
                )
                return True
            right_done = True
            return False

        def evict(before_key: Optional[np.ndarray]
                  ) -> Iterator[ColumnBatch]:
            """Drop window batches wholly below `before_key` (None = all),
            emitting their unmatched rows for RIGHT/FULL."""
            keep = []
            for entry in window:
                if before_key is None or _tuple_lt(
                    entry.max_key, before_key
                ):
                    if jt in (JoinType.RIGHT, JoinType.FULL):
                        matched = entry.matched_rows()
                        if not matched.all():
                            yield self._right_unmatched(
                                entry.batch, matched
                            )
                else:
                    keep.append(entry)
            window[:] = keep

        for lb in left.execute(partition, ctx):
            lb = ensure_compacted(lb)
            if lb.num_rows == 0:
                continue
            lkeys = _key_matrix(lb, self.left_keys)
            lmin, lmax = lkeys[0], lkeys[-1]
            # widen window until the right stream passes lmax
            while (not window
                   or not _tuple_lt(lmax, window[-1].max_key)) \
                    and pull_right():
                pass
            # shrink: whole batches below lmin can never match again
            yield from evict(lmin)
            yield from self._join_left_batch(lb, lmax, window)
        # final flush of never-matched right rows
        yield from evict(None)
        if jt in (JoinType.RIGHT, JoinType.FULL) and not right_done:
            for rb in right_it:
                rb = ensure_compacted(rb)
                if rb.num_rows:
                    yield self._right_unmatched(
                        rb, np.zeros(rb.num_rows, dtype=bool)
                    )

    # ------------------------------------------------------------------
    def _join_left_batch(self, lb: ColumnBatch, lmax: np.ndarray,
                         window: List[_WindowEntry]
                         ) -> Iterator[ColumnBatch]:
        """Probe the left batch against each range-overlapping window
        entry's PERSISTENT core (each core is hash+sorted exactly once,
        when its batch enters probing range - the re-concat + re-sort
        per left batch this replaces was O(window x batches)). lmax
        arrives from execute()'s single key readback per batch; entries
        below the left range were already evicted."""
        import jax.numpy as jnp

        right = self.children[1]
        jt = self.join_type
        emit = jt in (JoinType.INNER, JoinType.LEFT, JoinType.RIGHT,
                      JoinType.FULL)
        probe = lb  # already compacted by execute()
        matched_any = None
        for entry in window:
            # entries wholly above the left range cannot match (below-
            # range entries were evicted before this call)
            if _tuple_lt(lmax, entry.min_key):
                continue
            core = entry.ensure_core(self.right_keys)
            state = core.probe(probe, self.left_keys)
            probe = state[1]
            out_cols, valid, pair_cap, matched_p = core.emit_pairs(
                state,
                entry.batch.columns if emit else [],
                probe.columns if emit else [],
                build_first=False,
            )
            matched_any = (
                matched_p if matched_any is None
                else matched_any | matched_p
            )
            if emit:
                yield ColumnBatch(
                    self._schema, out_cols, pair_cap, valid
                )
        live_p = row_mask(probe.num_rows, probe.capacity)
        if matched_any is None:
            matched_any = jnp.zeros(probe.capacity, dtype=jnp.bool_)
        if emit:
            if jt in (JoinType.LEFT, JoinType.FULL):
                un = live_p & ~matched_any
                rnull = _null_side(right.schema.fields, probe.capacity)
                yield ColumnBatch(
                    self._schema, list(probe.columns) + rnull,
                    probe.num_rows, un,
                )
        elif jt is JoinType.LEFT_SEMI:
            yield ColumnBatch(
                self._schema, list(probe.columns), probe.num_rows,
                live_p & matched_any,
            )
        elif jt is JoinType.LEFT_ANTI:
            yield ColumnBatch(
                self._schema, list(probe.columns), probe.num_rows,
                live_p & ~matched_any,
            )

    def _right_unmatched(self, rb: ColumnBatch, matched: np.ndarray
                         ) -> ColumnBatch:
        import jax.numpy as jnp

        left = self.children[0]
        un = np.zeros(rb.capacity, dtype=bool)
        un[: rb.num_rows] = ~matched
        lnull = _null_side(left.schema.fields, rb.capacity)
        return ColumnBatch(
            self._schema, lnull + list(rb.columns), rb.num_rows,
            jnp.asarray(un),
        )
