"""Streaming sort-merge join for pre-sorted inputs.

The reference's flagship custom operator streams both sorted sides with
single-row cursors (sort_merge_join_exec.rs:293-601). Row cursors are
hostile to vectorization (SURVEY 7 hard parts), so this operator streams
at BATCH granularity instead: a sliding window of right-side batches is
kept only as wide as the current left batch's key range requires
(sorted-input invariant: once the left stream has passed a key, right rows
below it can never match again), and each left batch joins against the
window with the shared vectorized core. Memory is O(window), not O(side).

Contract: both inputs sorted ascending by their join keys (the planner
guarantees this the same way Spark does for SMJ - sort nodes under the
join). All six join types supported; RIGHT/FULL emit evicted-unmatched
window rows incrementally.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch, row_mask
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.joins import (
    JoinType,
    _JoinCore,
    _joined_schema,
    _null_side,
)
from blaze_tpu.ops.util import concat_batches, ensure_compacted


def _key_matrix(cb: ColumnBatch, key_idx: Sequence[int]) -> np.ndarray:
    """(num_rows, n_keys) host array of key values for range bookkeeping
    (tiny D2H: keys only)."""
    cols = []
    for i in key_idx:
        c = cb.columns[i]
        cols.append(np.asarray(c.values)[: cb.num_rows])
    return np.stack(cols, axis=1) if cols else np.zeros((cb.num_rows, 0))


def _tuple_lt(a: np.ndarray, b: np.ndarray) -> bool:
    """Lexicographic a < b for 1-D key tuples."""
    for x, y in zip(a, b):
        if x < y:
            return True
        if x > y:
            return False
    return False


class StreamingSortMergeJoinExec(PhysicalOp):
    def __init__(self, left: PhysicalOp, right: PhysicalOp,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 join_type: JoinType = JoinType.INNER):
        if join_type is JoinType.LEFT_ANTI_NULL_AWARE:
            raise NotImplementedError(
                "null-aware anti join needs the whole build side (any "
                "NULL key empties the result) - materializing SMJ only"
            )
        self.children = [left, right]
        self.left_keys = [left.schema.index_of(k) for k in left_keys]
        self.right_keys = [right.schema.index_of(k) for k in right_keys]
        for side, idxs in ((left, self.left_keys),
                           (right, self.right_keys)):
            for i in idxs:
                if side.schema.fields[i].dtype.is_string_like:
                    raise NotImplementedError(
                        "streaming SMJ needs ordered fixed-width keys; "
                        "string-keyed joins use the materializing SMJ"
                    )
        self.join_type = join_type
        self._schema = _joined_schema(left.schema, right.schema, join_type)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return self.children[0].partition_count

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        left, right = self.children
        jt = self.join_type
        right_it = right.execute(partition, ctx)
        # window entries: (batch, matched np.bool_[num_rows], max_key)
        window: List[List] = []
        right_done = False

        def pull_right() -> bool:
            nonlocal right_done
            if right_done:
                return False
            for rb in right_it:
                rb = ensure_compacted(rb)
                if rb.num_rows == 0:
                    continue
                keys = _key_matrix(rb, self.right_keys)
                window.append(
                    [rb, np.zeros(rb.num_rows, dtype=bool), keys[-1]]
                )
                return True
            right_done = True
            return False

        def evict(before_key: Optional[np.ndarray]
                  ) -> Iterator[ColumnBatch]:
            """Drop window batches wholly below `before_key` (None = all),
            emitting their unmatched rows for RIGHT/FULL."""
            keep = []
            for entry in window:
                rb, matched, maxk = entry
                if before_key is None or _tuple_lt(maxk, before_key):
                    if jt in (JoinType.RIGHT, JoinType.FULL) and \
                            not matched.all():
                        yield self._right_unmatched(rb, matched)
                else:
                    keep.append(entry)
            window[:] = keep

        for lb in left.execute(partition, ctx):
            lb = ensure_compacted(lb)
            if lb.num_rows == 0:
                continue
            lkeys = _key_matrix(lb, self.left_keys)
            lmin, lmax = lkeys[0], lkeys[-1]
            # widen window until the right stream passes lmax
            while (not window or not _tuple_lt(lmax, window[-1][2])) \
                    and pull_right():
                pass
            # shrink: whole batches below lmin can never match again
            yield from evict(lmin)
            yield from self._join_left_batch(lb, window)
        # final flush of never-matched right rows
        yield from evict(None)
        if jt in (JoinType.RIGHT, JoinType.FULL) and not right_done:
            for rb in right_it:
                rb = ensure_compacted(rb)
                if rb.num_rows:
                    yield self._right_unmatched(
                        rb, np.zeros(rb.num_rows, dtype=bool)
                    )

    # ------------------------------------------------------------------
    def _join_left_batch(self, lb: ColumnBatch, window: List[List]
                         ) -> Iterator[ColumnBatch]:
        left, right = self.children
        jt = self.join_type
        build = concat_batches(
            [e[0] for e in window], schema=right.schema
        )
        core = _JoinCore(build, self.right_keys)
        state = core.probe(lb, self.left_keys)
        probe = state[0]
        emit = jt in (JoinType.INNER, JoinType.LEFT, JoinType.RIGHT,
                      JoinType.FULL)
        out_cols, valid, pair_cap, matched_p = core.emit_pairs(
            state,
            build.columns if emit else [],
            probe.columns if emit else [],
            build_first=False,
        )
        live_p = row_mask(probe.num_rows, probe.capacity)
        # fold this probe's build-side matches back into window bookkeeping
        mb = np.asarray(core.matched_build)
        off = 0
        for entry in window:
            n = entry[0].num_rows
            entry[1] |= mb[off: off + n]
            off += n
        if emit:
            yield ColumnBatch(self._schema, out_cols, pair_cap, valid)
            if jt in (JoinType.LEFT, JoinType.FULL):
                import jax.numpy as jnp

                un = live_p & ~matched_p
                rnull = _null_side(right.schema.fields, probe.capacity)
                yield ColumnBatch(
                    self._schema, list(probe.columns) + rnull,
                    probe.num_rows, un,
                )
        elif jt is JoinType.LEFT_SEMI:
            yield ColumnBatch(
                self._schema, list(probe.columns), probe.num_rows,
                live_p & matched_p,
            )
        elif jt is JoinType.LEFT_ANTI:
            yield ColumnBatch(
                self._schema, list(probe.columns), probe.num_rows,
                live_p & ~matched_p,
            )

    def _right_unmatched(self, rb: ColumnBatch, matched: np.ndarray
                         ) -> ColumnBatch:
        import jax.numpy as jnp

        left = self.children[0]
        un = np.zeros(rb.capacity, dtype=bool)
        un[: rb.num_rows] = ~matched
        lnull = _null_side(left.schema.fields, rb.capacity)
        return ColumnBatch(
            self._schema, lnull + list(rb.columns), rb.num_rows,
            jnp.asarray(un),
        )
