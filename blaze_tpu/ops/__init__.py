"""Physical operators.

TPU-native equivalents of the reference's native execution tier: the custom
datafusion-ext operators (ShuffleWriter, SortMergeJoin, IpcReader/Writer,
RenameColumns, Debug, EmptyPartitions - SURVEY 2.1) plus the DataFusion
operators the reference reuses (Scan, Filter, Project, Sort, Union, HashJoin,
HashAggregate - SURVEY 2.1 note).

Execution model: a host-side stream of ColumnBatch per partition (the
reference streams Arrow RecordBatches through tokio, exec.rs:196-255); device
compute is jit-compiled per (operator fingerprint, shape bucket). Stateless
chains fuse into one XLA program via ops.pipeline; pipeline breakers
materialize device-resident state.
"""

from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.memory_scan import MemoryScanExec
from blaze_tpu.ops.project import ProjectExec
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.sort import SortExec, SortKey
from blaze_tpu.ops.union import CoalescePartitionsExec, UnionExec
from blaze_tpu.ops.limit import LimitExec
from blaze_tpu.ops.rename import RenameColumnsExec
from blaze_tpu.ops.empty import EmptyPartitionsExec
from blaze_tpu.ops.debug import DebugExec
from blaze_tpu.ops.hash_aggregate import AggMode, HashAggregateExec
from blaze_tpu.ops.joins import HashJoinExec, JoinType, SortMergeJoinExec
from blaze_tpu.ops.streaming_smj import StreamingSortMergeJoinExec
from blaze_tpu.ops.shuffle_writer import ShuffleWriterExec
from blaze_tpu.ops.ipc_reader import FileSegment, IpcReaderExec, IpcReadMode
from blaze_tpu.ops.ipc_writer import IpcWriterExec, collect_ipc

__all__ = [
    "ExecContext",
    "PhysicalOp",
    "MemoryScanExec",
    "ProjectExec",
    "FilterExec",
    "SortExec",
    "SortKey",
    "UnionExec",
    "CoalescePartitionsExec",
    "LimitExec",
    "RenameColumnsExec",
    "EmptyPartitionsExec",
    "DebugExec",
    "AggMode",
    "HashAggregateExec",
    "HashJoinExec",
    "JoinType",
    "SortMergeJoinExec",
    "StreamingSortMergeJoinExec",
    "ShuffleWriterExec",
    "FileSegment",
    "IpcReaderExec",
    "IpcReadMode",
    "IpcWriterExec",
    "collect_ipc",
]
