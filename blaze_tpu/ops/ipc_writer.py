"""IPC writer: collect a partition's batches as compressed IPC parts.

Reference counterpart: IpcWriterExec (ipc_writer_exec.rs, 196 LoC) -
coalesces to batch_size rows and hands length-prefixed zstd IPC parts to a
consumer (there a JVM lambda via direct ByteBuffer; here the context
resource registry). Feeds broadcast exchange collection (SURVEY 3.4)."""

from __future__ import annotations

from typing import Iterator, List

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.io.ipc import encode_ipc_segment
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.util import ensure_compacted


class IpcWriterExec(PhysicalOp):
    def __init__(self, child: PhysicalOp, resource_id: str):
        self.children = [child]
        self.resource_id = resource_id

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        sink = ctx.resources.setdefault(self.resource_id, [])
        nbytes = 0
        for cb in self.children[0].execute(partition, ctx):
            cb = ensure_compacted(cb)
            if cb.num_rows == 0:
                continue
            part = encode_ipc_segment(
                cb.to_arrow(), ctx.config.ipc_compression_level
            )
            nbytes += len(part)
            sink.append(part)
        ctx.metrics.add("ipc_bytes_written", nbytes)
        return iter(())


def collect_ipc(child: PhysicalOp, ctx: ExecContext) -> List[bytes]:
    """Run all partitions through an IpcWriter and return the parts - the
    engine-side analog of the reference's broadcast collect
    (ArrowBroadcastExchangeExec.scala:178-222)."""
    rid = f"collect-{id(child):x}"
    op = IpcWriterExec(child, rid)
    ctx.resources[rid] = []
    for p in range(child.partition_count):
        for _ in op.execute(p, ctx):
            pass
    return ctx.resources.pop(rid)
