"""Pass-through batch logger - the plan-level tracing facility
(reference DebugExec, debug_exec.rs:44-58)."""

from __future__ import annotations

import logging
from typing import Iterator

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import ExecContext, PhysicalOp

log = logging.getLogger("blaze_tpu.debug")


class DebugExec(PhysicalOp):
    def __init__(self, child: PhysicalOp, debug_id: str):
        self.children = [child]
        self.debug_id = debug_id

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        for i, b in enumerate(self.children[0].execute(partition, ctx)):
            log.info(
                "[%s] partition=%d batch=%d rows=%d:\n%s",
                self.debug_id, partition, i, b.num_rows,
                b.to_arrow().to_pandas().head(20),
            )
            yield b
