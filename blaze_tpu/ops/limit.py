"""Per-partition limit (Spark CollectLimit/LocalLimit analog)."""

from __future__ import annotations

from typing import Iterator

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.util import ensure_compacted


class LimitExec(PhysicalOp):
    def __init__(self, child: PhysicalOp, limit: int):
        self.children = [child]
        self.limit = limit

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return str(self.limit)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        remaining = self.limit
        for cb in self.children[0].execute(partition, ctx):
            if remaining <= 0:
                return
            cb = ensure_compacted(cb)
            if cb.num_rows > remaining:
                cb = ColumnBatch(
                    cb.schema, cb.columns, remaining, None
                )
            remaining -= cb.num_rows
            yield cb
