"""Shared batch utilities for operators: device gather/compact, host-side
dictionary unification, batch concatenation."""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.config import get_config
from blaze_tpu.types import Schema, TypeId
from blaze_tpu.batch import Column, ColumnBatch, row_mask


@jax.jit
def _take_many(arrays, indices):
    # one dispatch for the whole batch instead of one per column buffer
    return [jnp.take(a, indices, axis=0) for a in arrays]


def take_batch(cb: ColumnBatch, indices: jax.Array, num_rows: int
               ) -> ColumnBatch:
    """Gather rows by index (device). `indices` length defines capacity."""
    bufs = []
    slots = []
    for c in cb.columns:
        slots.append((len(bufs), c.validity is not None))
        bufs.append(c.values)
        if c.validity is not None:
            bufs.append(c.validity)
    taken = _take_many(bufs, indices)
    cols = []
    for c, (i, has_m) in zip(cb.columns, slots):
        cols.append(
            Column(c.dtype, taken[i],
                   taken[i + 1] if has_m else None, c.dictionary)
        )
    return ColumnBatch(cb.schema, cols, num_rows)


@partial(jax.jit, static_argnames=("capacity",))
def _compact_indices(mask: jax.Array, capacity: int):
    idx = jnp.nonzero(mask, size=capacity, fill_value=0)[0]
    return idx, jnp.sum(mask.astype(jnp.int32))


def compact(cb: ColumnBatch, mask: Optional[jax.Array] = None) -> ColumnBatch:
    """Keep rows where mask (AND the batch's own selection) is True, packed
    to the front (one D2H sync for the surviving row count)."""
    live = cb.live_mask()
    if mask is not None:
        live = live & mask
    idx, n = _compact_indices(live, cb.capacity)
    return take_batch(cb, idx, int(n))


def ensure_compacted(cb: ColumnBatch) -> ColumnBatch:
    """Materialize a pending selection vector (no-op when none)."""
    if cb.selection is None:
        return cb
    return compact(cb)


def unify_dictionaries(batches: List[ColumnBatch]) -> List[ColumnBatch]:
    """Rewrite all batches so every string column shares one dictionary.

    Host-side (pyarrow) dictionary merge + device-side code remap via
    jnp.take of the old->new mapping. Required before any cross-batch
    compute on string codes (sort, group-by, join): per-batch dictionaries
    are not comparable. TPU-first normalization per SURVEY 7: all device
    string compute happens on unified int32 codes.
    """
    import pyarrow as pa

    if not batches:
        return batches
    schema = batches[0].schema
    string_cols = [
        i for i, f in enumerate(schema)
        if f.dtype.is_dictionary_encoded
    ]
    if not string_cols:
        return batches
    out = [list(b.columns) for b in batches]
    for ci in string_cols:
        dicts = []
        for b in batches:
            d = b.columns[ci].dictionary
            dicts.append(d if d is not None else pa.array([], type=pa.utf8()))
        unified = pa.concat_arrays(
            [d.cast(dicts[0].type) for d in dicts]
        ).unique()
        # old-code -> new-code mapping per batch
        for bi, b in enumerate(batches):
            old = dicts[bi]
            if len(old) == 0:
                mapping = np.zeros(1, dtype=np.int32)
            else:
                mapping = np.asarray(
                    pa.compute.index_in(old, value_set=unified).fill_null(0)
                ).astype(np.int32)
            # pad the mapping to a power-of-two capacity so the remap
            # program depends only on (bucket, codes-shape), not the
            # exact dictionary size — otherwise every distinct
            # dictionary length compiles a fresh XLA executable
            # (hundreds over a TPC-DS run; jaxlib's CPU client
            # segfaults after enough cumulative compilations).
            pad_cap = 1 << max(0, (len(mapping) - 1)).bit_length()
            if pad_cap > len(mapping):
                mapping = np.pad(mapping, (0, pad_cap - len(mapping)))
            c = b.columns[ci]
            new_codes = jnp.take(
                jnp.asarray(mapping),
                jnp.clip(c.values, 0, len(mapping) - 1),
                axis=0,
            )
            out[bi][ci] = Column(c.dtype, new_codes, c.validity, unified)
    return [
        ColumnBatch(b.schema, cols, b.num_rows)
        for b, cols in zip(batches, out)
    ]


def concat_batches(batches: List[ColumnBatch],
                   schema: Optional[Schema] = None) -> ColumnBatch:
    """Concatenate live rows of many batches into one padded batch
    (pipeline-breaker materialization). Unifies string dictionaries."""
    batches = [ensure_compacted(b) for b in batches]
    batches = [b for b in batches if b.num_rows > 0]
    if not batches:
        from blaze_tpu.batch import empty_batch

        assert schema is not None, "empty concat needs an explicit schema"
        return empty_batch(schema)
    batches = unify_dictionaries(batches)
    schema = batches[0].schema
    total = sum(b.num_rows for b in batches)
    cap = get_config().bucket_for(total)
    if len(batches) == 1 and batches[0].capacity == cap:
        return batches[0]  # already compact at the right bucket
    ncols = len(schema)
    any_mask = [
        any(b.columns[ci].validity is not None for b in batches)
        for ci in range(ncols)
    ]
    values_in = [[b.columns[ci].values for b in batches]
                 for ci in range(ncols)]
    masks_in = [
        [
            b.columns[ci].validity
            if b.columns[ci].validity is not None
            else None
            for b in batches
        ]
        if any_mask[ci]
        else None
        for ci in range(ncols)
    ]
    lengths = jnp.asarray(
        np.array([b.num_rows for b in batches], dtype=np.int32)
    )
    vs, ms = _concat_many(
        values_in, masks_in, lengths, cap, tuple(any_mask)
    )
    cols: List[Column] = []
    for ci in range(ncols):
        ref = batches[0].columns[ci]
        cols.append(
            Column(ref.dtype, vs[ci], ms[ci] if any_mask[ci] else None,
                   ref.dictionary)
        )
    return ColumnBatch(schema, cols, total)


@partial(jax.jit, static_argnames=("cap", "any_mask"))
def _concat_many(values_in, masks_in, lengths, cap: int, any_mask):
    """Concatenate all columns of all batches in one dispatch.

    Row counts (`lengths`) stay TRACED: a filter upstream makes them
    data-dependent, and baking them in statically would recompile this
    program for every distinct combination. Instead each part scatters its
    live rows to a dynamic offset (dead/pad rows land in a dump slot), so
    one compile covers every batch mix with the same shapes/layout."""
    offsets = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32),
         jnp.cumsum(lengths)[:-1].astype(jnp.int32)]
    )
    vs = []
    ms = []
    for ci, parts in enumerate(values_in):
        # trailing dims (e.g. wide-decimal limb pairs) ride along
        out = jnp.zeros(
            (cap + 1,) + parts[0].shape[1:], dtype=parts[0].dtype
        )
        mout = jnp.zeros(cap + 1, dtype=jnp.bool_)
        for i, p in enumerate(parts):
            pos = jnp.arange(p.shape[0], dtype=jnp.int32)
            keep = pos < lengths[i]
            tgt = jnp.where(keep, offsets[i] + pos, cap)
            out = out.at[tgt].set(p, mode="drop")
            if any_mask[ci]:
                mp = masks_in[ci][i]
                mv = (
                    mp if mp is not None
                    else jnp.ones(p.shape[0], dtype=jnp.bool_)
                )
                mout = mout.at[tgt].set(mv, mode="drop")
        vs.append(out[:cap])
        ms.append(mout[:cap] if any_mask[ci] else None)
    return vs, ms


def slice_to_batches(cb: ColumnBatch, batch_size: int) -> List[ColumnBatch]:
    """Split a large materialized batch back into bucket-sized batches."""
    if cb.num_rows <= batch_size:
        return [cb]
    out = []
    for start in range(0, cb.num_rows, batch_size):
        n = min(batch_size, cb.num_rows - start)
        cap = get_config().bucket_for(n)
        cols = []
        for c in cb.columns:
            v = jax.lax.dynamic_slice_in_dim(c.values, start, cap) \
                if start + cap <= c.capacity else \
                jnp.pad(c.values[start:start + n], (0, cap - n))
            m = None
            if c.validity is not None:
                m = jax.lax.dynamic_slice_in_dim(c.validity, start, cap) \
                    if start + cap <= c.capacity else \
                    jnp.pad(c.validity[start:start + n], (0, cap - n))
            cols.append(Column(c.dtype, v, m, c.dictionary))
        out.append(ColumnBatch(cb.schema, cols, n))
    return out


def _order_key_u32(v: jax.Array, asc: bool) -> jax.Array:
    """Map a <=32-bit value lane to a u32 whose unsigned order equals the
    requested SQL order: ints sign-flip; floats use the sign-magnitude
    flip with NaN normalized to canonical +NaN (Spark: NaN greatest) and
    -0.0 to +0.0 (Spark: equal); descending bit-inverts."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        f = v.astype(jnp.float32)
        f = jnp.where(f == 0.0, jnp.float32(0.0), f)  # -0.0 == 0.0
        bits = jax.lax.bitcast_convert_type(f, jnp.uint32)
        bits = jnp.where(
            jnp.isnan(f), jnp.uint32(0x7FC00000), bits
        )
        neg = (bits >> jnp.uint32(31)).astype(jnp.bool_)
        u = bits ^ jnp.where(
            neg, jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000)
        )
    elif v.dtype == jnp.bool_:
        u = v.astype(jnp.uint32)
    elif jnp.issubdtype(v.dtype, jnp.unsignedinteger):
        # already in unsigned order: no sign flip. The signed path's
        # astype(int32) would wrap values >= 2^31 and the flip would
        # then order them BELOW small values. (types.py defines no
        # unsigned TypeId today, so this is future-proofing, but the
        # packed-sort eligibility gate admits any <=4-byte integer.)
        u = v.astype(jnp.uint32)
    else:
        u = v.astype(jnp.int32).astype(jnp.uint32) ^ jnp.uint32(
            0x80000000
        )
    if not asc:
        u = ~u
    return u


def _sort_indices_packed(keys, num_rows, capacity: int) -> jax.Array:
    """One u64 VALUE sort per key instead of a 3-lane index lexsort per
    key plus a final padding argsort: each pass packs
    (null-rank:2 | order-key:32 | position:posbits) into a u64 and
    sorts it; the low bits carry the permutation, so the pass is stable
    by construction and padding rows (rank 3) always sink to the end.
    ~5x faster than the lexsort ladder on XLA:CPU at 8M rows."""
    posbits = max(1, (capacity - 1).bit_length())
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    pos = jnp.arange(capacity, dtype=jnp.uint64)
    posmask = jnp.uint64((1 << posbits) - 1)
    idx = None
    for values, validity, asc, nulls_first in reversed(list(keys)):
        v = values if idx is None else jnp.take(values, idx, axis=0)
        u = _order_key_u32(v, asc)
        lv = live if idx is None else jnp.take(live, idx)
        if validity is not None:
            mv = (
                validity if idx is None
                else jnp.take(validity, idx)
            )
            rank = jnp.where(
                mv, jnp.uint64(1),
                jnp.uint64(0 if nulls_first else 2),
            )
            # NULL rows carry arbitrary payload values; zero them so
            # the null run keeps the previous pass's (stable) order
            # instead of shuffling by garbage
            u = jnp.where(mv, u, jnp.uint32(0))
        else:
            rank = jnp.uint64(1)
        rank = jnp.where(lv, rank, jnp.uint64(3))
        lane = (
            ((rank << jnp.uint64(32)) | u.astype(jnp.uint64))
            << jnp.uint64(posbits)
        ) | pos
        order = (jnp.sort(lane) & posmask).astype(jnp.int32)
        idx = order if idx is None else jnp.take(idx, order)
    if idx is None:  # no keys: padding-last identity
        idx = jnp.argsort(
            jnp.where(live, 0, 1).astype(jnp.int8), stable=True
        ).astype(jnp.int32)
    return idx


def sort_indices(
    keys: Sequence[Tuple[jax.Array, Optional[jax.Array], bool, bool]],
    num_rows,
    capacity: int,
) -> jax.Array:
    """Stable multi-key argsort. keys = [(values, validity, ascending,
    nulls_first)]; padding rows always sort last.

    Keys whose values fit 32 bits (ints, f32, dict codes, dates, bool)
    take the packed-u64 path; wider keys (i64, f64, timestamps) fall
    back to iterated stable sorts from the least-significant key
    (classic radix-style lexsort) - every pass is one XLA sort op.
    """
    from blaze_tpu.config import get_config, resolve_core_choice

    packed_ok = (
        resolve_core_choice("BLAZE_SORT_CORE", get_config().sort_core)
        == "scatter"
    )
    if packed_ok and capacity < (1 << 30) and all(
        v.ndim == 1
        and (
            v.dtype == jnp.bool_
            or (
                jnp.issubdtype(v.dtype, jnp.integer)
                and v.dtype.itemsize <= 4
            )
            or v.dtype == jnp.float32
        )
        for v, _, _, _ in keys
    ):
        return _sort_indices_packed(keys, num_rows, capacity)
    idx = jnp.arange(capacity, dtype=jnp.int32)
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    for values, validity, asc, nulls_first in reversed(list(keys)):
        v = jnp.take(values, idx, axis=0)
        lv = jnp.take(live.astype(jnp.int8), idx, axis=0)
        if jnp.issubdtype(v.dtype, jnp.floating):
            # Spark ordering: NaN sorts greater than any value
            nan = jnp.isnan(v)
            v = jnp.where(nan, jnp.inf, v)
            tie = nan.astype(jnp.int8)
        else:
            tie = jnp.zeros_like(v, dtype=jnp.int8)
        if not asc:
            v = _invert_order(v)
            tie = -tie
        # null ranking: 0 = nulls first, 2 = nulls last, live padding > all
        if validity is not None:
            mv = jnp.take(validity, idx, axis=0)
            rank = jnp.where(mv, 1, 0 if nulls_first else 2)
            # NULL rows carry arbitrary payload values: neutralize the
            # value and tie lanes so the null run keeps the previous
            # pass's (stable) order instead of shuffling by garbage
            zero = jnp.zeros_like(v[:1])[0]
            v = jnp.where(mv, v, zero)
            tie = jnp.where(mv, tie, jnp.int8(0))
        else:
            rank = jnp.ones_like(v, dtype=jnp.int32)
        rank = jnp.where(lv.astype(bool), rank, 3)
        order = jnp.lexsort((tie, v, rank))
        idx = jnp.take(idx, order, axis=0)
    # final pass: push padding to the end while keeping everything stable
    lv = jnp.take(live.astype(jnp.int8), idx, axis=0)
    order = jnp.argsort(-lv, stable=True)
    return jnp.take(idx, order, axis=0)


def _invert_order(v: jax.Array) -> jax.Array:
    if jnp.issubdtype(v.dtype, jnp.floating):
        return -v
    if v.dtype == jnp.bool_:
        return ~v
    # bitwise NOT (-v - 1) is an order-reversing bijection on two's-
    # complement ints with no overflow: plain negation maps INT64_MIN to
    # itself and would sort it first in a descending sort
    return jnp.bitwise_not(v.astype(jnp.int64))
