"""Hash aggregate with PARTIAL / FINAL / COMPLETE modes.

Reference counterpart: DataFusion AggregateExec built from proto
(from_proto.rs:452-545) with Spark's two-phase mode mapping
(NativeHashAggregateExec.scala:98-161). Supported functions mirror the
reference's converter surface: MIN/MAX/SUM/AVG/COUNT/VAR/STDDEV
(NativeConverters.scala:491-501) plus FIRST/LAST.

TPU-first design (SURVEY 7): instead of a row-at-a-time hash table, grouping
is a sort-based segmented reduction - one stable multi-key sort pass, group
boundaries by comparing adjacent sorted keys (SQL semantics: NULL groups
with NULL), then `jax.ops.segment_*` reductions with a static segment count
(the batch capacity), so every step is one fused XLA program with static
shapes. Variance/stddev state is (count, sum, sum-of-squares) so every
merge is a plain segment_sum.

PARTIAL mode streams: each input batch aggregates independently (bounded
state, like the reference's partial aggregation). FINAL/COMPLETE are
pipeline breakers that materialize the partition.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.config import get_config
from blaze_tpu.types import DataType, Field, Schema, TypeId
from blaze_tpu.batch import Column, ColumnBatch, row_mask
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.ir import AggExpr, AggFn
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.host_lower import lower_strings_host
from blaze_tpu.ops.project import _unflatten_cvs
from blaze_tpu.ops.util import concat_batches, sort_indices
from blaze_tpu.runtime.dispatch import cached_kernel, host_int


class AggMode(enum.Enum):
    PARTIAL = "partial"
    FINAL = "final"
    COMPLETE = "complete"


def _group_core_choice() -> str:
    """Grouping-core knob (config.group_core / env BLAZE_GROUP_CORE)."""
    from blaze_tpu.config import resolve_core_choice

    return resolve_core_choice(
        "BLAZE_GROUP_CORE", get_config().group_core
    )


class _SchemaStub:
    """Placeholder child carrying only a schema (internal op wiring)."""

    def __init__(self, schema: Schema):
        self.children = []
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return 1


@dataclasses.dataclass(frozen=True)
class NamedAgg:
    agg: AggExpr
    name: str


def _decimal_chunks(cv):
    """Split decimal unscaled values into four 32-bit chunk columns so
    segment sums never overflow i64: value = sum(c_k * 2^(32k)), c3
    carries the sign. Narrow input is a 1-D i64 array; wide input is the
    (capacity, 2) [lo-bit-pattern, hi] limb pair (types.is_wide_decimal,
    the reference's 16-byte decimal slot, shuffle_writer_exec.rs:
    196-220)."""
    mask = jnp.int64(0xFFFFFFFF)
    if cv.ndim == 1:
        c0 = cv & mask
        c1 = cv >> 32  # arithmetic: carries the sign
        z = jnp.zeros_like(cv)
        return [c0, c1, z, z]
    lo = cv[:, 0]
    hi = cv[:, 1]
    lo_u = lo.astype(jnp.uint64)
    c0 = (lo_u & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
    c1 = (lo_u >> jnp.uint64(32)).astype(jnp.int64)
    c2 = hi & mask
    c3 = hi >> 32  # arithmetic: the 128-bit sign
    return [c0, c1, c2, c3]


def run_grouped_kernel(base_key, build, args, fetch_n, gcap,
                       scatter_class: bool = False,
                       span: str = "group_dispatch"):
    """Dispatch a grouped-aggregate kernel under the sentinel-retry
    ladder shared by HashAggregateExec and FusedAggregateExec:

    - n_groups == -1: narrow-key hash collision between DIFFERENT keys
      (vanishingly rare) -> re-run the exact full-width lexsort kernel.
    - n_groups > tier: more groups than static output slots -> climb
      the capacity ladder (small tier -> configured cap -> unsliced).
      Correctness never depends on the slot guess; most aggregates
      resolve to a few thousand groups, so the first attempt uses a
      small scatter domain + transfer and only genuinely wide keys pay
      a retry.

    `build(force_lexsort, group_cap)` returns the python kernel to jit;
    `fetch_n(outs, n_groups) -> (outs', n)` owns the host sync policy.

    `scatter_class` rides through to cached_kernel for the variants
    that actually run the scatter core (the force_lexsort retry is
    sort-dominated and always compiles under the default runtime);
    `span` names the obs span so phases.py can band group/join
    dispatches separately."""
    import os

    force_lex = False
    # BLAZE_AGG_TIER1 <= 0 disables the small first tier (one fewer
    # compiled kernel variant per aggregate shape): the test suite sets
    # it because jaxlib's CPU client segfaults under cumulative
    # compile volume (docs/JAXLIB_SEGFAULT.md) and the ladder's extra
    # variants pushed the largest exchange-tier query over the cliff
    tier1 = int(os.environ.get("BLAZE_AGG_TIER1", "4096"))
    if gcap is None:
        tiers = [None]
    elif tier1 <= 0 or tier1 >= gcap:
        tiers = [gcap, None]
    else:
        tiers = [tier1, gcap, None]
    ti = 0
    while True:
        gc = tiers[ti]
        fn = cached_kernel(
            base_key + (force_lex, gc),
            lambda fl=force_lex, g=gc: build(fl, g),
            scatter_class=scatter_class and not force_lex,
            span=span,
        )
        outs, n_groups = fn(*args)
        host_outs, n = fetch_n(outs, n_groups)
        if n < 0 and not force_lex:
            force_lex = True
            continue
        if gc is not None and n > gc:
            ti += 1
            continue
        return host_outs, n


class _SegOps:
    """Segmented reductions sized to the group-slot capacity (out_cap),
    not the row capacity. The keyless single-group case collapses to
    plain masked reductions - an XLA reduce instead of a scatter, which
    matters enormously on TPU where scatters serialize."""

    def __init__(self, gid, out_cap: int, keyless: bool,
                 domain: int = None, compact_slots=None):
        import os

        self.gid = gid
        self.out_cap = out_cap
        self.scalar = keyless and out_cap == 1
        # scatter-core fast path: `gid` may be RAW table slots (domain =
        # table size) instead of dense group ids - reductions scatter
        # into `domain` segments and only the tiny per-group result is
        # compacted to out_cap by gathering at the occupied slots. This
        # skips the dense-id pass (an extra full-row gather) entirely;
        # dead rows carry arbitrary in-range slots, which is safe
        # because every caller masks contributions to the reduction's
        # neutral element first.
        self.domain = out_cap if domain is None else domain
        self.compact_slots = compact_slots
        # opt-in MXU path: the one-hot-contraction Pallas kernel
        # (ops/kernels/segreduce_pallas.py) replaces the XLA scatter
        # for f32 min/max over bounded key domains. Default off until
        # the end-of-round bench's tpu_core_probe validates it on a
        # real chip (scatters serialize on TPU; the contraction rides
        # the MXU).
        self._pallas = os.environ.get("BLAZE_SEGREDUCE") == "pallas"

    def _pallas_ok(self, x) -> bool:
        if not self._pallas or self.scalar or x.ndim != 1:
            return False
        if x.dtype != jnp.float32:
            return False
        from blaze_tpu.ops.kernels import segreduce_pallas as sr

        return sr.supports(x.shape[0], self.domain)

    def _finish(self, r):
        if self.compact_slots is not None:
            r = jnp.take(r, self.compact_slots, axis=0)
        return r

    def sum(self, x):
        if self.scalar:
            return jnp.sum(x, axis=0, keepdims=True)
        return self._finish(jax.ops.segment_sum(
            x, self.gid, num_segments=self.domain
        ))

    def min(self, x):
        if self.scalar:
            return jnp.min(x, axis=0, keepdims=True)
        if self._pallas_ok(x):
            from blaze_tpu.ops.kernels import segreduce_pallas as sr

            return self._finish(sr.segment_minmax(
                self.gid, x, self.domain, is_min=True
            ))
        return self._finish(jax.ops.segment_min(
            x, self.gid, num_segments=self.domain
        ))

    def max(self, x):
        if self.scalar:
            return jnp.max(x, axis=0, keepdims=True)
        if self._pallas_ok(x):
            from blaze_tpu.ops.kernels import segreduce_pallas as sr

            return self._finish(sr.segment_minmax(
                self.gid, x, self.domain, is_min=False
            ))
        return self._finish(jax.ops.segment_max(
            x, self.gid, num_segments=self.domain
        ))


_DEC38_MAX = 10**38 - 1
_U64 = (1 << 64) - 1


def _reassemble_decimal(chunk_cols: List[np.ndarray],
                        any_v: Optional[np.ndarray],
                        count: Optional[np.ndarray],
                        scale: int, avg: bool,
                        n_live: Optional[int] = None):
    """Host-exact reassembly of chunked decimal sums -> (values, mask,
    DataType). SUM overflowing decimal(38) nulls out (Spark non-ANSI);
    AVG divides at scale+4 with HALF_UP using full-precision ints.
    Python-bigint work is O(n_live groups), not O(padded capacity):
    results zero-pad back to the buffer length."""
    cap = len(chunk_cols[0])
    n = cap if n_live is None else min(n_live, cap)
    total = (
        chunk_cols[0][:n].astype(object)
        + (chunk_cols[1][:n].astype(object) << 32)
        + (chunk_cols[2][:n].astype(object) << 64)
        + (chunk_cols[3][:n].astype(object) << 96)
    )
    out_scale = scale
    if avg:
        out_scale = min(scale + 4, 38)
        mul = 10 ** (out_scale - scale)
        safe = np.maximum(count[:n], 1).astype(object)
        num = total * mul
        q = num // safe
        r = num - q * safe
        half_up = np.where(num >= 0, 2 * r >= safe, 2 * r > safe)
        total = q + half_up.astype(object)
    overflow = np.abs(total) > _DEC38_MAX
    mask = np.zeros(cap, dtype=bool)
    mask[:n] = (
        any_v[:n] if any_v is not None else True
    ) & ~overflow
    safe_total = np.where(overflow, 0, total)
    t_mod = np.mod(safe_total, 1 << 128)  # two's complement 128
    lo = t_mod & _U64
    hi = t_mod >> 64
    to_i64 = lambda x: np.where(
        x >= (1 << 63), x - (1 << 64), x
    ).astype(np.int64)
    limbs = np.zeros((cap, 2), dtype=np.int64)
    limbs[:n] = np.stack([to_i64(lo), to_i64(hi)], axis=1)
    return limbs, mask, DataType.decimal(38, out_scale)


def _state_fields(agg: AggExpr, name: str, in_schema: Schema) -> List[Field]:
    fn = agg.fn
    if fn in (AggFn.COUNT, AggFn.COUNT_STAR):
        return [Field(f"{name}#count", DataType.int64(), False)]
    ct = infer_dtype(agg.child, in_schema)
    if fn in (AggFn.SUM, AggFn.AVG) and ct.id is TypeId.DECIMAL:
        # chunked 128-bit-exact sum state; the scale rides in the field
        # name so the FINAL side (which only sees the partial schema,
        # e.g. across a shuffle) can finalize exactly
        fields = [
            Field(
                f"{name}#dsum{ct.scale}_c{k}", DataType.int64(),
                k == 0,
            )
            for k in range(4)
        ]
        if fn is AggFn.AVG:
            fields.append(
                Field(f"{name}#count", DataType.int64(), False)
            )
        return fields
    if fn is AggFn.SUM:
        return [Field(f"{name}#sum", _sum_type(ct), True)]
    if fn in (AggFn.MIN, AggFn.MAX, AggFn.FIRST, AggFn.LAST):
        return [Field(f"{name}#{fn.value}", ct, True)]
    if fn is AggFn.AVG:
        return [
            Field(f"{name}#sum", _sum_type(ct), True),
            Field(f"{name}#count", DataType.int64(), False),
        ]
    # var/stddev family: plain-summable moments
    return [
        Field(f"{name}#n", DataType.float64(), False),
        Field(f"{name}#s1", DataType.float64(), False),
        Field(f"{name}#s2", DataType.float64(), False),
    ]


def _state_width(fn: AggFn, chunked: bool) -> int:
    """Positional state width per aggregate (immune to duplicate output
    aliases - the layout is deterministic given fn + whether the first
    state field carries the chunked-decimal #dsum marker)."""
    if fn in (AggFn.COUNT, AggFn.COUNT_STAR, AggFn.MIN, AggFn.MAX,
              AggFn.FIRST, AggFn.LAST):
        return 1
    if fn is AggFn.SUM:
        return 4 if chunked else 1
    if fn is AggFn.AVG:
        return 5 if chunked else 2
    return 3  # var/stddev moments


def _parse_dsum_scale(field_name: str) -> Optional[int]:
    """Scale encoded in a chunked-decimal state field name, or None."""
    marker = "#dsum"
    i = field_name.find(marker)
    if i < 0:
        return None
    rest = field_name[i + len(marker):]
    j = rest.find("_c")
    if j <= 0:
        return None
    try:
        return int(rest[:j])
    except ValueError:
        return None


def _sum_type(ct: DataType) -> DataType:
    if ct.is_integer:
        return DataType.int64()
    if ct.id is TypeId.DECIMAL:
        return DataType.decimal(38, ct.scale)
    return DataType.float64()


class HashAggregateExec(PhysicalOp):
    def __init__(
        self,
        child: PhysicalOp,
        keys: Sequence[Tuple[ir.Expr, str]],
        aggs: Sequence[Tuple[AggExpr, str]],
        mode: AggMode = AggMode.COMPLETE,
    ):
        self.children = [child]
        self.mode = mode
        in_schema = child.schema
        self.keys = [(bind_opt(e, in_schema), n) for e, n in keys]
        if mode is AggMode.FINAL:
            # child refs are ignored in FINAL mode; states are located
            # positionally in the partial output (keys first, then states
            # in agg order) - mirror of the reference's partial/final
            # column splice (NativeHashAggregateExec.scala:98-161).
            # Widths come from the partial schema's "{name}#..." field
            # names, which also carry the chunked-decimal scale marker.
            self.aggs = []
            self._final_widths: List[int] = []
            pos = len(self.keys)
            fields = in_schema.fields
            for a, n in aggs:
                chunked = (
                    _parse_dsum_scale(fields[pos].name) is not None
                )
                width = _state_width(a.fn, chunked)
                first_state = fields[pos]
                self.aggs.append(
                    (AggExpr(a.fn, ir.BoundCol(pos, first_state.dtype)), n)
                )
                self._final_widths.append(width)
                pos += width
        else:
            self.aggs = [
                (
                    AggExpr(
                        a.fn,
                        bind_opt(a.child, in_schema)
                        if a.child is not None
                        else None,
                    ),
                    n,
                )
                for a, n in aggs
            ]
        for a, n in self.aggs:
            if a.fn in (AggFn.MIN, AggFn.MAX) and a.child is not None:
                if infer_dtype(a.child, in_schema).is_string_like:
                    raise NotImplementedError(
                        "MIN/MAX over strings is host-tier work (planned)"
                    )
            if (
                mode is not AggMode.FINAL
                and a.child is not None
                and a.fn not in (AggFn.SUM, AggFn.AVG, AggFn.COUNT,
                                 AggFn.FIRST, AggFn.LAST)
                and infer_dtype(a.child, in_schema).is_wide_decimal
            ):
                # 128-bit ordering/moments need host math; SUM/AVG use
                # the chunked state, FIRST/LAST/COUNT are passthrough
                raise NotImplementedError(
                    f"{a.fn.value} over decimal(>18) is host-tier work"
                )
        key_fields = [
            Field(n, infer_dtype(e, in_schema), True) for e, n in self.keys
        ]
        for f in key_fields:
            if f.dtype.is_wide_decimal:
                raise NotImplementedError(
                    "group keys of decimal(>18) are host-tier work"
                )
        if mode is AggMode.PARTIAL:
            state_fields: List[Field] = []
            for a, n in self.aggs:
                state_fields += _state_fields(a, n, in_schema)
            self._schema = Schema(key_fields + state_fields)
        else:
            self._schema = Schema(
                key_fields
                + [
                    Field(n, _result_type(a, in_schema, mode), True)
                    for a, n in self.aggs
                ]
            )

    @property
    def schema(self) -> Schema:
        return self._schema

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        keys = ";".join(f"{n}={e!r}" for e, n in self.keys)
        aggs = ";".join(f"{n}={a!r}" for a, n in self.aggs)
        return f"{self.mode.name};keys[{keys}];aggs[{aggs}]"

    # ------------------------------------------------------------------
    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        child_it = self.children[0].execute(partition, ctx)
        if self.mode is AggMode.PARTIAL:
            for cb in child_it:
                out = self._aggregate_batch(cb)
                if out.num_rows > 0:
                    yield out
            return
        from blaze_tpu.ops.external import bucket_stream, collect_until

        batches, exceeded = collect_until(
            child_it, ctx.config.max_materialize_rows
        )
        if exceeded:
            yield from self._execute_external(batches, child_it, ctx)
            return
        cb = concat_batches(batches, schema=self.children[0].schema)
        if cb.num_rows == 0 and self.keys:
            return
        out = self._aggregate_batch(cb)
        if cb.num_rows == 0 and not self.keys:
            # global aggregate over empty input still emits one row
            yield _empty_global_row(self)
            return
        yield out

    def _execute_external(self, head, rest, ctx: ExecContext
                          ) -> Iterator[ColumnBatch]:
        """Grace aggregation for oversized inputs (ops/external.py): every
        group lands wholly in one hash bucket, so buckets aggregate
        independently. The keyless case folds per-batch partial states
        instead (one state row per batch, always bounded)."""
        in_schema = self.children[0].schema
        if not self.keys:
            if self.mode is AggMode.FINAL:
                # keyless FINAL consumes tiny partial-state rows (one per
                # upstream batch); crossing the row cap here implies an
                # absurd upstream batch count - concat is still bounded
                batches = list(head) + list(rest)
                yield self._aggregate_batch(
                    concat_batches(batches, schema=in_schema)
                )
                return
            # keyless COMPLETE: fold per-batch partial states, then one
            # final merge (one state row per input batch)
            partial = HashAggregateExec(
                self.children[0],
                keys=[],
                aggs=[(a, n) for a, n in self.aggs],
                mode=AggMode.PARTIAL,
            )
            partials = []
            for cb in list(head) + list(rest):
                p = partial._aggregate_batch(cb)
                if p.num_rows:
                    partials.append(p)
            if not partials:
                yield _empty_global_row(self)
                return
            final = HashAggregateExec(
                _SchemaStub(partial.schema),
                keys=[],
                aggs=[(a, n) for a, n in self.aggs],
                mode=AggMode.FINAL,
            )
            yield final._aggregate_batch(
                concat_batches(partials, schema=partial.schema)
            )
            return
        key_exprs = [e for e, _ in self.keys]
        from blaze_tpu.runtime.memory import (
            batch_device_bytes,
            choose_external_bucket_count,
            get_device_tracker,
        )

        head_bytes = sum(batch_device_bytes(b) for b in head)
        tracker = get_device_tracker()
        track_key = (id(self), ctx.partition_id)
        tracker.track(track_key, head_bytes)
        try:
            n_b = choose_external_bucket_count(
                2 * head_bytes, ctx.config
            )
            yield from self._grace_agg(
                rest, head, ctx, in_schema, n_b, depth=0
            )
        finally:
            tracker.release(track_key)

    _MAX_GRACE_DEPTH = 2
    _GRACE_FANOUT = 4

    def _grace_agg(self, rest, head, ctx: ExecContext, in_schema,
                   n_b: int, depth: int,
                   modulus: Optional[int] = None
                   ) -> Iterator[ColumnBatch]:
        """One grace level. Overflowing buckets re-bucket recursively by
        the next hash bits (splits many-distinct-key overflow); at max
        depth - a single hot key - COMPLETE mode aggregates the bucket
        CHUNK-WISE (partial per sub-chunk + one final merge), which a
        hash split can never achieve."""
        from blaze_tpu.ops.external import (
            bucket_stream,
            collect_until,
            subdivide_pid_fn,
        )

        key_exprs = [e for e, _ in self.keys]
        if modulus is None:
            modulus = n_b
            pid = None
        else:
            pid = subdivide_pid_fn(key_exprs, modulus, n_b)
            modulus *= n_b
        bucketed = bucket_stream(
            rest, key_exprs, n_b, ctx, in_schema, head=head, pid_fn=pid,
        )
        ctx.metrics.add("external_agg_buckets", n_b)
        try:
            limit = ctx.config.max_materialize_rows
            for b in range(n_b):
                it = bucketed.bucket(b)
                chunk, exceeded = collect_until(it, limit)
                if not chunk:
                    continue
                if exceeded and depth < self._MAX_GRACE_DEPTH:
                    ctx.metrics.add("external_agg_rebuckets", 1)
                    yield from self._grace_agg(
                        it, chunk, ctx, in_schema,
                        self._GRACE_FANOUT, depth + 1, modulus,
                    )
                    continue
                if exceeded and self.mode is AggMode.COMPLETE:
                    ctx.metrics.add("external_agg_hot_buckets", 1)
                    yield from self._aggregate_chunked(
                        chunk, it, in_schema, limit
                    )
                    continue
                chunk += list(it)  # exceeded FINAL: states stay mergeable
                out = self._aggregate_batch(
                    concat_batches(chunk, schema=in_schema)
                )
                if out.num_rows:
                    yield out
        finally:
            bucketed.cleanup()

    def _aggregate_chunked(self, head, rest, in_schema, limit
                           ) -> Iterator[ColumnBatch]:
        """Partial-per-chunk + final-merge for one oversized bucket."""
        partial = HashAggregateExec(
            self.children[0],
            keys=[(e, n) for e, n in self.keys],
            aggs=[(a, n) for a, n in self.aggs],
            mode=AggMode.PARTIAL,
        )
        partials: List[ColumnBatch] = []

        def drain(batches):
            chunk: List[ColumnBatch] = []
            rows = 0
            for cb in batches:
                chunk.append(cb)
                rows += cb.num_rows
                if rows > limit:
                    p = partial._aggregate_batch(
                        concat_batches(chunk, schema=in_schema)
                    )
                    if p.num_rows:
                        partials.append(p)
                    chunk, rows = [], 0
            if chunk:
                p = partial._aggregate_batch(
                    concat_batches(chunk, schema=in_schema)
                )
                if p.num_rows:
                    partials.append(p)

        import itertools

        # STREAM the bucket: materializing it here would re-create the
        # exact blow-up this path exists to avoid
        drain(itertools.chain(head, rest))
        if not partials:
            return
        final = HashAggregateExec(
            _SchemaStub(partial.schema),
            keys=[
                (ir.BoundCol(i, partial.schema.fields[i].dtype), n)
                for i, (_, n) in enumerate(self.keys)
            ],
            aggs=[(a, n) for a, n in self.aggs],
            mode=AggMode.FINAL,
        )
        out = final._aggregate_batch(
            concat_batches(partials, schema=partial.schema)
        )
        if out.num_rows:
            yield out

    # ------------------------------------------------------------------
    def _aggregate_batch(self, cb: ColumnBatch) -> ColumnBatch:
        merging = self.mode is AggMode.FINAL
        key_exprs = [e for e, _ in self.keys]
        child_exprs: List[ir.Expr] = []
        for a, _ in self.aggs:
            if merging:
                continue
            if a.child is not None:
                child_exprs.append(a.child)
        exprs, _, aug = lower_strings_host(key_exprs + child_exprs, cb)
        key_exprs_l = exprs[: len(key_exprs)]
        child_map = {}
        if not merging:
            it = iter(exprs[len(key_exprs):])
            for i, (a, _) in enumerate(self.aggs):
                if a.child is not None:
                    child_map[i] = next(it)

        base_key = ("hashagg", self.mode.value,
                    tuple((a.fn, a.child) for a, _ in self.aggs),
                    tuple(key_exprs_l), tuple(child_map.items()),
                    aug.layout(), merging, _group_core_choice())
        gcap = (1 if not self.keys
                else min(aug.capacity, get_config().agg_group_capacity))
        if gcap >= aug.capacity:
            gcap = None
        outs, n = run_grouped_kernel(
            base_key,
            lambda fl, gc: self._build_kernel(
                aug.schema, aug.capacity, key_exprs_l, child_map,
                merging, aug.layout(), force_lexsort=fl, group_cap=gc,
            ),
            (aug.device_buffers(), aug.selection,
             None if aug.num_rows == aug.capacity else aug.num_rows),
            # keyless: exactly one group, no collision/overflow retry -
            # skip the blocking scalar sync (a tunnel round trip each)
            (lambda o, ng: (o, 1)) if not self.keys
            else (lambda o, ng: (o, host_int(ng))),
            gcap,
            scatter_class=self._scatter_core_hint(
                aug.schema, key_exprs_l
            ),
        )
        cols: List[Column] = []
        # recover dictionaries for string key passthroughs
        for (v, m), field, e in zip(
            outs[: len(self.keys)],
            self._schema.fields[: len(self.keys)],
            key_exprs_l,
        ):
            dictionary = None
            if field.dtype.is_dictionary_encoded and isinstance(
                e, ir.BoundCol
            ):
                dictionary = aug.columns[e.index].dictionary
            cols.append(Column(field.dtype, v, m, dictionary))
        agg_fields = self._schema.fields[len(self.keys):]
        it = iter(outs[len(self.keys):])
        if self.mode is AggMode.PARTIAL:
            # state fields align 1:1 with kernel outputs
            for (v, m), field in zip(it, agg_fields):
                cols.append(Column(field.dtype, v, m, None))
        else:
            staged = []
            fetch_list: List = []
            for (a, _), field in zip(self.aggs, agg_fields):
                spec = self._agg_spec(a, aug.schema)
                if spec[0] == "plain":
                    staged.append((spec, field, next(it)))
                    continue
                # chunked decimal: stage the chunk arrays; ALL of them
                # fetch in one packed transfer below
                pairs = [next(it) for _ in range(4)]
                count = next(it)[0] if spec[0] == "dec_avg" else None
                staged.append((spec, field, (pairs, count)))
                fetch_list.extend(v for v, _ in pairs)
                fetch_list.append(pairs[0][1])
                if count is not None:
                    fetch_list.append(count)
            if fetch_list:
                from blaze_tpu.runtime.pack import get_packed

                host_it = iter(get_packed(fetch_list))
            for spec, field, payload in staged:
                if spec[0] == "plain":
                    v, m = payload
                    cols.append(Column(field.dtype, v, m, None))
                    continue
                _, count = payload
                chunks = [np.asarray(next(host_it)) for _ in range(4)]
                any_np = np.asarray(next(host_it))
                count_np = (
                    np.asarray(next(host_it)) if count is not None
                    else None
                )
                limbs, mask, dt = _reassemble_decimal(
                    chunks, any_np, count_np, spec[1],
                    spec[0] == "dec_avg", n_live=n,
                )
                assert dt == field.dtype, (dt, field.dtype)
                cols.append(Column(field.dtype, limbs, mask, None))
        return ColumnBatch(self._schema, cols, n)

    # ------------------------------------------------------------------
    def _scatter_core_hint(self, in_schema, key_exprs) -> bool:
        """Mirror of _build_kernel's use_scatter gate, evaluated at
        dispatch time: True when the kernel variant about to build will
        run the scatter grouping core, so cached_kernel can route it to
        the scatter-friendly CPU runtime (dispatch._scatter_jit_kwargs).
        A wrong guess only costs runtime choice, never correctness."""
        return (
            bool(key_exprs)
            and _group_core_choice() == "scatter"
            and self._narrow_key_dtypes(
                in_schema, key_exprs, allow_floats=True
            )
            is not None
        )

    def _narrow_key_dtypes(self, in_schema, key_exprs,
                           allow_floats: bool = False):
        """Hash dtypes for the narrow-key grouping fast path, or None
        when ineligible. Eligible: fixed-width non-float keys (ints,
        dates, timestamps, bool, decimal<=18, dictionary codes) - the
        sort then runs on ONE i32 hash lane instead of K emulated-64-bit
        lanes (ROADMAP 'aggregate/sort key widths'). Floats keep the
        lexsort path there (NaN/-0.0 normalization), but the SCATTER
        core compares exact key values (_pairwise_eq groups NaN with
        NaN; cheap_hash normalizes -0.0/NaN payloads), so it passes
        allow_floats=True."""
        from blaze_tpu.exprs.hashing import device_hash_supported

        dtypes = []
        for e in key_exprs:
            dt = infer_dtype(e, in_schema)
            if dt.is_dictionary_encoded:
                dt = DataType.int32()  # group equality == code equality
            if dt.id in (TypeId.FLOAT32, TypeId.FLOAT64):
                if not allow_floats:
                    return None
                dtypes.append(dt)
                continue
            if dt.is_wide_decimal or not device_hash_supported(dt):
                return None
            dtypes.append(dt)
        return dtypes

    def _build_kernel(self, in_schema, capacity, key_exprs, child_map,
                      merging, layout, force_lexsort: bool = False,
                      group_cap=None):
        from blaze_tpu.exprs.hashing import hash_columns_device

        aggs = self.aggs
        n_keys = len(key_exprs)
        state_offsets = self._state_offsets(in_schema) if merging else None
        use_scatter = False
        if not force_lexsort and _group_core_choice() == "scatter":
            # the scatter core's exact-equality probing also handles
            # float keys (NaN groups with NaN, -0.0 == 0.0), which the
            # hash-lane sort cannot
            use_scatter = (
                self._narrow_key_dtypes(
                    in_schema, key_exprs, allow_floats=True
                )
                is not None
            )
        # hash-lane dtypes only matter when the scatter gate fails
        hash_dtypes = (
            None if force_lexsort or use_scatter
            else self._narrow_key_dtypes(in_schema, key_exprs)
        )

        # Segment-output capacity: with a small static group bound the
        # reductions scatter into out_cap slots instead of `capacity`
        # (keyless aggregates collapse to plain masked reductions), so
        # both the compute AND the transfer scale with groups, not rows.
        out_cap = (
            group_cap
            if group_cap is not None and group_cap < capacity
            else capacity
        )

        def kernel(bufs, selection, num_rows):
            cols = _unflatten_cvs(layout, bufs)
            ev = DeviceEvaluator(in_schema, cols, capacity)
            # num_rows=None: FULL batch (host-known at dispatch). The
            # constant-true mask folds every downstream where() away,
            # letting XLA fuse expensive projections (log/sqrt chains)
            # straight into the reductions instead of materializing
            # them for a masked select (8M expr_chain: 254ms -> 140ms)
            live = (
                jnp.ones(capacity, dtype=jnp.bool_)
                if num_rows is None
                else jnp.arange(capacity, dtype=jnp.int32) < num_rows
            )
            if selection is not None:
                live = live & selection

            keys_cv = [ev.evaluate(e) for e in key_exprs]
            collision = jnp.asarray(False)
            if n_keys and use_scatter:
                # ---- group ids by hash-table insertion (sort-free) ----
                # every live row resolves to a slot via exact-key probing
                # (ops/hash_table.py), so unlike the hash-lane sort path
                # there is no collision sentinel: equality is verified,
                # not inferred from hash adjacency
                from blaze_tpu.ops import hash_table as ht

                # table sized to the group-slot capacity, not the row
                # capacity: dense_group_ids scans the whole table, so a
                # row-capacity table costs ~0.5s/8M rows in cumsum+
                # nonzero alone. More distinct keys than the small
                # table holds trips `overflow`, which reuses the
                # group-capacity retry (re-run unsliced -> full table).
                full_t = ht.table_size_for(capacity)
                small_t = ht.table_size_for(min(capacity, 2 * out_cap))
                tsize = min(small_t, full_t)
                slot, rep_tab, overflow = ht.group_slots(
                    [(v, m) for v, m in keys_cv],
                    live,
                    capacity,
                    tsize,
                    max_rounds=16 if tsize < full_t else None,
                )
                # reductions run on RAW slots (domain = tsize); only
                # the (out_cap,)-sized states compact through the
                # occupied-slot gather below, skipping dense_group_ids'
                # extra full-row gather (8M rows / 4k groups: the whole
                # group stage drops ~35%). Dead rows keep arbitrary
                # in-range slots - every reduction masks their
                # contribution to its neutral element.
                occupied = rep_tab != jnp.int32(capacity)
                n_groups = jnp.sum(occupied.astype(jnp.int32))
                occ_slots = jnp.nonzero(
                    occupied, size=out_cap, fill_value=0
                )[0]
                bpos = jnp.clip(
                    jnp.take(rep_tab, occ_slots), 0, capacity - 1
                )
                gid_sorted = slot
                seg_domain = tsize
                seg_compact = occ_slots
                n_groups = jnp.where(
                    overflow, jnp.int32(out_cap + 1), n_groups
                )
                idx = None  # identity: rows stay in input order
                s_live = live
            # ---- group ids by stable sort + boundary detection ----
            elif n_keys and hash_dtypes is not None:
                # narrow-key fast path: ONE stable i32 sort by the key
                # hash; true-key boundary detection below splits hash
                # collisions into correct runs, and a collision between
                # DIFFERENT keys (which could scatter one key across
                # runs) is detected and reported via the n_groups
                # sentinel so the caller re-runs the lexsort kernel
                h = hash_columns_device(
                    [
                        (v, m, dt)
                        for (v, m), dt in zip(keys_cv, hash_dtypes)
                    ],
                    capacity,
                ).astype(jnp.int32)
                order = jnp.lexsort(
                    (h, jnp.where(live, 0, 1).astype(jnp.int8))
                )
                idx = order
                sh = jnp.take(h, idx)
                shp = jnp.concatenate([sh[:1], sh[:-1]])
                hash_neq = sh != shp
            elif n_keys:
                # sort priority: live rows first, then per key a (validity,
                # value) pair so NULL forms its own ordering class and never
                # interleaves with the dtype-extreme sentinel values
                priority = [jnp.where(live, 0, 1).astype(jnp.int8)]
                for v, m in keys_cv:
                    if m is not None:
                        priority.append(
                            jnp.where(m, jnp.int8(1), jnp.int8(0))
                        )
                    priority.append(_null_last_key(v, m))
                    if jnp.issubdtype(v.dtype, jnp.floating):
                        # NaN encodes as +inf for ordering; this extra
                        # component keeps the NaN run adjacent but
                        # SEPARATE from a real +inf run
                        priority.append(jnp.isnan(v).astype(jnp.int8))
                # jnp.lexsort: last key is the primary -> reverse
                order = jnp.lexsort(tuple(reversed(priority)))
                idx = order
                hash_neq = None
            if n_keys and not use_scatter:
                s_live = jnp.take(live, idx)
                prev_live = jnp.concatenate(
                    [jnp.zeros(1, dtype=jnp.bool_), s_live[:-1]]
                )
                first_live = s_live & ~prev_live
                diff = jnp.zeros(capacity, dtype=jnp.bool_)
                for v, m in keys_cv:
                    if jnp.issubdtype(v.dtype, jnp.floating):
                        # group NaN with NaN (Spark normalizes NaN keys);
                        # the isnan flag separates it from real +inf
                        nanf = jnp.take(
                            jnp.isnan(v).astype(jnp.int8), idx
                        )
                        sv = jnp.take(
                            jnp.where(jnp.isnan(v), jnp.inf, v), idx
                        )
                        nanp = jnp.concatenate([nanf[:1], nanf[:-1]])
                        extra = nanf != nanp
                    else:
                        sv = jnp.take(v, idx)
                        extra = jnp.zeros(capacity, dtype=jnp.bool_)
                    svp = jnp.concatenate([sv[:1], sv[:-1]])
                    neq = (sv != svp) | extra
                    if m is not None:
                        sm = jnp.take(m, idx)
                        smp = jnp.concatenate([sm[:1], sm[:-1]])
                        neq = jnp.where(
                            sm & smp, neq, sm != smp
                        )
                    diff = diff | neq
                if hash_neq is not None:
                    # a same-hash adjacency between DIFFERENT keys means
                    # equal keys may be scattered across runs - bail to
                    # the lexsort kernel via the n_groups sentinel
                    collision = jnp.any(
                        s_live & prev_live & ~hash_neq & diff
                    )
                boundary = s_live & (diff | first_live)
                gid_sorted = jnp.cumsum(boundary.astype(jnp.int32)) - 1
                # dead rows park in the last segment; every reduction
                # masks them to its neutral element so they never count
                gid_sorted = jnp.where(s_live, gid_sorted, out_cap - 1)
                n_groups = jnp.where(
                    collision,
                    jnp.int32(-1),
                    jnp.sum(boundary.astype(jnp.int32)),
                )
                # boundary row index per group, padded
                bpos = jnp.nonzero(
                    boundary, size=out_cap, fill_value=0
                )[0]
            elif not n_keys:
                idx = None
                s_live = live
                gid_sorted = jnp.where(live, 0, out_cap - 1)
                n_groups = jnp.asarray(1, jnp.int32)
                bpos = jnp.zeros(out_cap, dtype=jnp.int32)

            if not (n_keys and use_scatter):
                seg_domain = None
                seg_compact = None

            outs = []
            for (v, m) in keys_cv:
                sv = _tk(v, idx)
                kv = jnp.take(sv, bpos)
                km = None
                if m is not None:
                    km = jnp.take(_tk(m, idx), bpos)
                outs.append((kv, km))

            segops = _SegOps(
                gid_sorted, out_cap, n_keys == 0,
                domain=seg_domain, compact_slots=seg_compact,
            )
            for i, (a, name) in enumerate(aggs):
                outs.extend(
                    self._agg_state(
                        a, i, ev, idx, s_live, segops, capacity,
                        child_map, merging, state_offsets, cols,
                    )
                )
            return outs, n_groups

        return kernel

    def _state_offsets(self, in_schema: Schema):
        """In FINAL mode, locate each agg's state columns positionally:
        keys first, then state columns in agg order (widths were scanned
        from the partial schema's field names at construction)."""
        offs = {}
        pos = len(self.keys)
        for i, (a, n) in enumerate(self.aggs):
            width = self._final_widths[i]
            offs[i] = (pos, width)
            pos += width
        return offs

    def _agg_spec(self, a: AggExpr, in_schema: Schema):
        """Output classification: ("plain", None) or
        ("dec_sum"|"dec_avg", scale) for chunked-exact decimal
        aggregation whose result reassembles on the host."""
        if a.fn not in (AggFn.SUM, AggFn.AVG):
            return ("plain", None)
        if self.mode is AggMode.FINAL:
            s = _parse_dsum_scale(in_schema.fields[a.child.index].name)
            if s is not None:
                return (
                    "dec_avg" if a.fn is AggFn.AVG else "dec_sum", s
                )
            return ("plain", None)
        ct = infer_dtype(a.child, in_schema)
        if ct.id is TypeId.DECIMAL:
            return ("dec_avg" if a.fn is AggFn.AVG else "dec_sum",
                    ct.scale)
        return ("plain", None)

    def _agg_state(self, a, i, ev, idx, s_live, segops, capacity,
                   child_map, merging, state_offsets, cols):
        """Emit the output (value, validity) columns for one aggregate."""
        fn = a.fn
        seg = segops.sum
        live_f = s_live

        if merging:
            pos, width = state_offsets[i]
            states = [
                (_tk(cols[pos + k][0], idx),
                 _tk(cols[pos + k][1], idx)
                 if cols[pos + k][1] is not None else None)
                for k in range(width)
            ]
            spec = self._agg_spec(a, ev.schema)
            return self._merge_states(
                a, states, segops, live_f, capacity, spec
            )

        # raw input -> state/result
        if fn is AggFn.COUNT_STAR:
            c = seg(live_f.astype(jnp.int64))
            return [(c, None)]
        cv, cm = ev.evaluate(child_map[i])
        cv = _tk(cv, idx)
        cm_s = _tk(cm, idx) if cm is not None else None
        contrib = live_f if cm_s is None else (live_f & cm_s)
        if fn is AggFn.COUNT:
            return [(seg(contrib.astype(jnp.int64)), None)]
        if fn in (AggFn.SUM, AggFn.AVG):
            st = _sum_type(infer_dtype_of(a, ev.schema))
            if st.id is TypeId.DECIMAL:
                # chunked 128-bit-exact sum; result reassembles on host
                chunks = _decimal_chunks(cv)
                sums = [
                    seg(jnp.where(contrib, c, jnp.zeros_like(c)))
                    for c in chunks
                ]
                any_v = seg(contrib.astype(jnp.int64)) > 0
                out = [(sums[0], any_v)] + [
                    (c, None) for c in sums[1:]
                ]
                if fn is AggFn.AVG:
                    out.append((seg(contrib.astype(jnp.int64)), None))
                return out
            acc = jnp.where(contrib, cv, jnp.zeros_like(cv)).astype(
                st.physical_dtype()
            )
            s = seg(acc)
            any_v = seg(contrib.astype(jnp.int64)) > 0
            if fn is AggFn.SUM:
                return [(s, any_v)]
            cnt = seg(contrib.astype(jnp.int64))
            if self.mode is AggMode.PARTIAL:
                return [(s, any_v), (cnt, None)]
            safe = jnp.maximum(cnt, 1)
            return [(s / safe.astype(jnp.float64), any_v)]
        if fn in (AggFn.MIN, AggFn.MAX):
            phys = cv.dtype
            if jnp.issubdtype(phys, jnp.floating):
                neutral = jnp.inf if fn is AggFn.MIN else -jnp.inf
            elif phys == jnp.bool_:
                cv = cv.astype(jnp.int8)
                neutral = 1 if fn is AggFn.MIN else 0
                phys = jnp.int8
            else:
                info = jnp.iinfo(phys)
                neutral = info.max if fn is AggFn.MIN else info.min
            acc = jnp.where(contrib, cv, jnp.asarray(neutral, phys))
            red = segops.min if fn is AggFn.MIN else segops.max
            m = red(acc)
            any_v = seg(contrib.astype(jnp.int64)) > 0
            return [(m, any_v)]
        if fn in (AggFn.FIRST, AggFn.LAST):
            pos_in = jnp.arange(capacity, dtype=jnp.int32)
            big = capacity + 1
            if fn is AggFn.FIRST:
                rank = jnp.where(contrib, pos_in, big)
                best = segops.min(rank)
            else:
                rank = jnp.where(contrib, pos_in, -1)
                best = segops.max(rank)
            has = (best >= 0) & (best < big)
            safe_best = jnp.clip(best, 0, capacity - 1)
            vals = jnp.take(cv, safe_best, axis=0)
            return [(vals, has)]
        # var/stddev family: moments
        x = jnp.where(contrib, cv, jnp.zeros_like(cv)).astype(jnp.float64)
        n = seg(contrib.astype(jnp.float64))
        s1 = seg(x)
        s2 = seg(x * x)
        if self.mode is AggMode.PARTIAL:
            return [(n, None), (s1, None), (s2, None)]
        return [_finalize_var(a.fn, n, s1, s2)]

    def _merge_states(self, a, states, segops, live_f, capacity,
                      spec=("plain", None)):
        fn = a.fn
        seg = segops.sum
        if spec[0] in ("dec_sum", "dec_avg"):
            # chunk sums merge by plain segment addition
            c0, m0 = states[0]
            contrib = live_f if m0 is None else (live_f & m0)
            sums = [
                seg(jnp.where(live_f, c, jnp.zeros_like(c)))
                for c, _ in states[:4]
            ]
            any_v = seg(contrib.astype(jnp.int64)) > 0
            out = [(sums[0], any_v)] + [(c, None) for c in sums[1:]]
            if spec[0] == "dec_avg":
                cnt, _ = states[4]
                out.append(
                    (seg(jnp.where(live_f, cnt, jnp.zeros_like(cnt))),
                     None)
                )
            return out
        if fn in (AggFn.COUNT, AggFn.COUNT_STAR):
            v, _ = states[0]
            return [(seg(jnp.where(live_f, v, 0)), None)]
        if fn is AggFn.SUM:
            v, m = states[0]
            contrib = live_f if m is None else (live_f & m)
            s = seg(jnp.where(contrib, v, jnp.zeros_like(v)))
            any_v = seg(contrib.astype(jnp.int64)) > 0
            return [(s, any_v)]
        if fn in (AggFn.MIN, AggFn.MAX):
            v, m = states[0]
            contrib = live_f if m is None else (live_f & m)
            phys = v.dtype
            if jnp.issubdtype(phys, jnp.floating):
                neutral = jnp.inf if fn is AggFn.MIN else -jnp.inf
            else:
                info = jnp.iinfo(phys)
                neutral = info.max if fn is AggFn.MIN else info.min
            acc = jnp.where(contrib, v, jnp.asarray(neutral, phys))
            red = segops.min if fn is AggFn.MIN else segops.max
            out = red(acc)
            any_v = seg(contrib.astype(jnp.int64)) > 0
            return [(out, any_v)]
        if fn is AggFn.AVG:
            (sv, sm), (cv2, _) = states
            contrib = live_f if sm is None else (live_f & sm)
            s = seg(jnp.where(contrib, sv, jnp.zeros_like(sv)))
            c = seg(jnp.where(live_f, cv2, jnp.zeros_like(cv2)))
            any_v = c > 0
            safe = jnp.maximum(c, 1)
            # decimal AVG runs on the chunked path above; this is the
            # int/float double AVG
            return [(s.astype(jnp.float64)
                     / safe.astype(jnp.float64), any_v)]
        if fn in (AggFn.FIRST, AggFn.LAST):
            v, m = states[0]
            contrib = live_f if m is None else (live_f & m)
            pos_in = jnp.arange(capacity, dtype=jnp.int32)
            big = capacity + 1
            if fn is AggFn.FIRST:
                rank = jnp.where(contrib, pos_in, big)
                best = segops.min(rank)
            else:
                rank = jnp.where(contrib, pos_in, -1)
                best = segops.max(rank)
            has = (best >= 0) & (best < big)
            vals = jnp.take(v, jnp.clip(best, 0, capacity - 1), axis=0)
            return [(vals, has)]
        # moments merge
        (nv, _), (s1v, _), (s2v, _) = states
        n = seg(jnp.where(live_f, nv, 0.0))
        s1 = seg(jnp.where(live_f, s1v, 0.0))
        s2 = seg(jnp.where(live_f, s2v, 0.0))
        return [_finalize_var(fn, n, s1, s2)]


def _tk(x, idx):
    """Permute by the grouping order; `idx is None` means identity (the
    scatter core keeps rows in input order - skipping the gather saves a
    full-capacity pass per aggregated column)."""
    if idx is None:
        return x
    return jnp.take(x, idx, axis=0)


def _null_last_key(v, m):
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jnp.where(jnp.isnan(v), jnp.inf, v)
    if m is None:
        return v
    # nulls group first: shift valid values up by using a rank pair trick -
    # lexsort handles composite keys, so encode null rank into the value
    # domain where possible; use where() with dtype extremes
    if jnp.issubdtype(v.dtype, jnp.floating):
        return jnp.where(m, v, -jnp.inf)
    if v.dtype == jnp.bool_:
        return jnp.where(m, v.astype(jnp.int8), jnp.int8(-1))
    info = jnp.iinfo(v.dtype)
    return jnp.where(m, v, info.min)


def _finalize_var(fn: AggFn, n, s1, s2):
    mean = s1 / jnp.maximum(n, 1.0)
    m2 = s2 - s1 * mean  # sum((x-mean)^2) = s2 - s1^2/n
    pop = fn in (AggFn.VAR_POP, AggFn.STDDEV_POP)
    denom = jnp.maximum(n if pop else n - 1.0, 1.0)
    var = jnp.maximum(m2, 0.0) / denom
    valid = n > (0.0 if pop else 1.0)
    out = var
    if fn in (AggFn.STDDEV_SAMP, AggFn.STDDEV_POP):
        out = jnp.sqrt(var)
    return (out, valid)


def _result_type(a: AggExpr, in_schema: Schema, mode: AggMode) -> DataType:
    if mode is AggMode.FINAL:
        # child is a BoundCol at the first state column (see __init__)
        if a.fn in (AggFn.COUNT, AggFn.COUNT_STAR):
            return DataType.int64()
        dscale = _parse_dsum_scale(in_schema.fields[a.child.index].name)
        if dscale is not None:
            if a.fn is AggFn.AVG:
                return DataType.decimal(38, min(dscale + 4, 38))
            return DataType.decimal(38, dscale)
        st = a.child.dtype
        if a.fn is AggFn.SUM or a.fn in (
            AggFn.MIN, AggFn.MAX, AggFn.FIRST, AggFn.LAST
        ):
            return st
        if a.fn is AggFn.AVG:
            return DataType.float64()
        return DataType.float64()  # var/stddev
    return infer_dtype(a, in_schema)


def infer_dtype_of(a: AggExpr, schema: Schema) -> DataType:
    return infer_dtype(a.child, schema)


def _empty_global_row(op: HashAggregateExec) -> ColumnBatch:
    """Global aggregate of an empty stream: COUNT=0, others NULL."""
    cols = []
    cap = get_config().shape_buckets[0]
    for field, (a, _) in zip(op.schema.fields, op.aggs):
        phys = field.dtype.physical_dtype()
        shape = (cap, 2) if field.dtype.is_wide_decimal else (cap,)
        v = jnp.zeros(shape, dtype=phys)
        if a.fn in (AggFn.COUNT, AggFn.COUNT_STAR):
            cols.append(Column(field.dtype, v, None, None))
        else:
            cols.append(
                Column(
                    field.dtype, v, jnp.zeros(cap, dtype=jnp.bool_), None
                )
            )
    return ColumnBatch(op.schema, cols, 1)
