"""Equi-joins: broadcast hash join and sort-merge join.

Reference counterparts: DataFusion HashJoinExec CollectLeft (from_proto.rs:
349-428, wrapper NativeBroadcastHashJoinExec.scala:96-123) and the custom
streaming SortMergeJoinExec (sort_merge_join_exec.rs, 1897 LoC incl. 20
tests; wrapper NativeSortMergeJoinExec.scala:87-121). Join conditions are
not evaluated inside the join - the Spark-side converter plants a
NativeFilter above (BlazeConverters.scala:244-301) - and we keep that
contract.

TPU-first core (SURVEY 7 "hard parts"): instead of row-at-a-time hash
probing / single-row merge cursors, both joins share one vectorized kernel:

  1. unify string-key dictionaries (host) so key equality == code equality
  2. hash build keys on device (any consistent hash works intra-engine;
     uses the murmur3 lanes), sort build rows by hash
  3. per probe row, binary-search the sorted hash run [lo, hi)
  4. expand candidate pairs by run length (one cumsum + gather, static
     output capacity; one host sync for the pair count)
  5. verify true key equality (hash collisions + NULL keys never match)
  6. outer/semi/anti variants come from matched-flag segment reductions

The sorted-input property of SMJ inputs is exploited by sorting only once
per partition; output order follows the streamed (left) side like the
reference's streaming merge.
"""

from __future__ import annotations

import enum
from functools import partial
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.config import get_config
from blaze_tpu.types import DataType, Field, Schema, TypeId
from blaze_tpu.batch import Column, ColumnBatch, row_mask
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.hashing import hash_columns_device
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.util import (
    compact,
    concat_batches,
    ensure_compacted,
    take_batch,
)
from blaze_tpu.runtime.dispatch import cached_kernel, host_int


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"
    # Spark's NOT IN semantics (null-aware anti join): if the build side
    # contains any NULL key the result is empty; probe rows with NULL keys
    # never qualify either
    LEFT_ANTI_NULL_AWARE = "left_anti_null_aware"


def _joined_schema(left: Schema, right: Schema, jt: JoinType) -> Schema:
    if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
              JoinType.LEFT_ANTI_NULL_AWARE):
        return left
    nullable_left = jt in (JoinType.RIGHT, JoinType.FULL)
    nullable_right = jt in (JoinType.LEFT, JoinType.FULL)
    fields = [
        Field(f.name, f.dtype, f.nullable or nullable_left) for f in left
    ] + [
        Field(f.name, f.dtype, f.nullable or nullable_right) for f in right
    ]
    return Schema(fields)


def _unify_key_pair(bcol: Column, pcol: Column) -> Tuple[Column, Column]:
    """Remap a (build, probe) string key pair onto one dictionary."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if not bcol.dtype.is_dictionary_encoded:
        return bcol, pcol
    bd = bcol.dictionary if bcol.dictionary is not None else pa.array(
        [], type=pa.utf8())
    pd_ = pcol.dictionary if pcol.dictionary is not None else pa.array(
        [], type=pa.utf8())
    unified = pa.concat_arrays(
        [bd.cast(pa.utf8()), pd_.cast(pa.utf8())]
    ).unique()

    def remap(col: Column, old) -> Column:
        if len(old) == 0:
            return Column(col.dtype, col.values, col.validity, unified)
        mapping = np.asarray(
            pc.index_in(old, value_set=unified).fill_null(0)
        ).astype(np.int32)
        codes = jnp.take(
            jnp.asarray(mapping),
            jnp.clip(col.values, 0, len(mapping) - 1),
            axis=0,
        )
        return Column(col.dtype, codes, col.validity, unified)

    return remap(bcol, bd), remap(pcol, pd_)


def _key_hash_cols(cols: List[Column]) -> List[Tuple]:
    """(values, validity, dtype) triples for device hashing; string codes
    hash as int32 (valid intra-engine: equality is code equality after
    dictionary unification)."""
    out = []
    for c in cols:
        if c.dtype.is_wide_decimal:
            raise NotImplementedError(
                "join keys of decimal(>18) are host-tier work"
            )
        dt = c.dtype
        if dt.is_dictionary_encoded:
            dt = DataType.int32()
        if dt.id is TypeId.FLOAT64:
            # avoid the TPU f64-bitcast limitation inside joins: compare
            # hashes of the f32 narrowing only as a *bucketing* step - true
            # equality is verified on the full values afterwards
            out.append(
                (c.values.astype(jnp.float32), c.validity,
                 DataType.float32())
            )
        else:
            out.append((c.values, c.validity, dt))
    return out


def _join_core_choice() -> str:
    """Join-core knob (config.join_core / env BLAZE_JOIN_CORE)."""
    from blaze_tpu.config import resolve_core_choice

    return resolve_core_choice(
        "BLAZE_JOIN_CORE", get_config().join_core
    )


class _JoinCore:
    """Shared vectorized equi-join over one materialized build batch.

    Two cores behind one interface:

    - "table" (unique build keys): the build relation inserts into an
      open-addressing hash table (ops/hash_table.py, one bounded
      scatter/gather probe loop); each probe batch then runs ONE lookup
      kernel (no sort, no searchsorted, no pair expansion, and NO
      blocking host sync - output capacity is statically the probe
      capacity) and ONE emission kernel that only gathers the build
      side: probe columns pass through untouched. Duplicate build keys
      are detected at insert time (one scalar sync per build relation)
      and demote to the sorted core.
    - "sorted": build rows sort by key hash; per probe batch ONE
      counting kernel + ONE blocking scalar readback (the dynamic pair
      count picks the static output bucket) + ONE emission kernel that
      expands candidate runs, verifies equality and gathers both sides.

    Either way the dispatch budget per probe batch is O(1) kernels
    (the tunnel-RTT model of runtime/dispatch.py) - instead of the ~20
    eager ops a naive translation of the reference's cursor loop
    would dispatch."""

    def __init__(self, build: ColumnBatch, build_keys: List[int]):
        import threading

        self.build = build
        self.build_keys = build_keys
        self.matched_build = jnp.zeros(build.capacity, dtype=jnp.bool_)
        self._index = None
        # a core may be shared across concurrently executing probe
        # partitions (fused.py caches it on the join op); index
        # (re)builds and downgrades mutate self._index, so they run
        # under this lock and readers capture a local snapshot
        self._index_lock = threading.Lock()
        # remembered demotion: duplicate build keys mean the table core
        # can never apply to this build relation - don't re-attempt (and
        # re-pay the insert pass + blocking dup sync) per probe batch
        # when dictionary-encoded keys force an index rebuild
        self._table_demoted = False
        # kr -> generic downgrade (probe key wider than the 32-bit kr
        # encoding); remembered for the same reason
        self._force_generic = False

    def _ensure_index(self, build_cols: List[Column]):
        # the index is probe-invariant unless a build key is
        # dictionary-encoded (dictionary unification re-maps build codes
        # per probe batch); cache it so multi-batch probes pay the index
        # kernel once
        if self._index is not None and not any(
            c.dtype.is_dictionary_encoded for c in build_cols
        ):
            return
        cap = self.build.capacity

        # one eligibility decision for both table attempts below: when
        # True, the first block always runs and defines eq_layout /
        # tsize / kr / ht for the second
        scatter_ok = (
            not self._table_demoted
            and _join_core_choice() == "scatter"
            # wide-decimal keys are host-tier work either way; the
            # sorted path below carries the NotImplementedError guard
            and not any(c.dtype.is_wide_decimal for c in build_cols)
        )
        if scatter_ok:
            from blaze_tpu.ops import hash_table as ht

            eq_layout = _eq_layout(build_cols)
            # size off the LIVE row count (host-known), not the padded
            # shape-bucket capacity: a 131k-row dim table in a 1M
            # bucket would otherwise get an 8M-slot table whose random
            # gathers fall out of cache
            tsize = ht.probe_table_size(
                max(1, int(self.build.num_rows))
            )

            kr = _kr_eligible(build_cols) and not self._force_generic

            # dense-domain dimension keys (TPC-DS surrogate keys are
            # near-contiguous ints; Spark's LongHashedRelation has the
            # same dense-array fast path): replace the hash table with
            # a direct key->row array. Probing drops from hash + probe
            # rounds over an 8x-oversized u64 table to ONE gather into
            # a 4-byte-per-slot array that fits in L2 (measured at
            # 131k keys / 8M probes on XLA:CPU: 398ms -> 47ms).
            if (
                kr
                and len(build_cols) == 1
                and jnp.issubdtype(
                    build_cols[0].values.dtype, jnp.integer
                )
                # dictionary-encoded keys rebuild the index per probe
                # batch (per-batch code unification): the extra kmin/
                # kmax host sync per batch would outweigh the direct
                # table's probe win on a tunnel-RTT dispatch model
                and not build_cols[0].dtype.is_dictionary_encoded
                and int(self.build.num_rows) > 0
            ):
                def build_span():
                    def kernel(eq_bufs, num_rows):
                        live = (
                            jnp.arange(cap, dtype=jnp.int32) < num_rows
                        )
                        ((v, m),) = _unflatten_eq(eq_layout, eq_bufs)
                        if m is not None:
                            live = live & m
                        info = jnp.iinfo(v.dtype)
                        kmin = jnp.min(jnp.where(live, v, info.max))
                        kmax = jnp.max(jnp.where(live, v, info.min))
                        return jnp.stack(
                            [kmin.astype(jnp.int64),
                             kmax.astype(jnp.int64)]
                        )

                    return kernel

                span_fn = cached_kernel(
                    ("join_keyspan", eq_layout, cap), build_span,
                    span="join_dispatch",
                )
                kmin, kmax = (
                    int(x) for x in np.asarray(
                        span_fn(
                            _flatten_cols(build_cols),
                            self.build.num_rows,
                        )
                    )
                )
                span = kmax - kmin + 1
                nrows = int(self.build.num_rows)
                # sparse domains would waste memory and cache; beyond
                # 8x the row count (or 16M slots) the u64 table wins
                if 0 < span <= min(1 << 24, max(4096, 8 * nrows)):
                    tsize_d = ht.direct_table_size(span)

                    def build_direct():
                        def kernel(eq_bufs, base, num_rows):
                            live = (
                                jnp.arange(cap, dtype=jnp.int32)
                                < num_rows
                            )
                            ((v, m),) = _unflatten_eq(
                                eq_layout, eq_bufs
                            )
                            if m is not None:
                                live = live & m
                            return ht.insert_direct(
                                v, live, cap, base, tsize_d
                            )

                        return kernel

                    dfn = cached_kernel(
                        ("join_table_direct", eq_layout, cap, tsize_d),
                        build_direct,
                        scatter_class=True, span="join_dispatch",
                    )
                    base = jnp.asarray(kmin, jnp.int64)
                    tab, dup = dfn(
                        _flatten_cols(build_cols), base,
                        self.build.num_rows,
                    )
                    if not host_int(dup):
                        self._index = (
                            "table_direct",
                            (tab, base, jnp.asarray(span, jnp.int64)),
                        )
                        return
                    # duplicate build keys: no single-row table core
                    # applies - demote straight to the sorted core
                    # (don't re-pay an insert + sync on the kr table)
                    self._table_demoted = True

        if scatter_ok and not self._table_demoted:
            def build_table():
                def kernel(eq_bufs, num_rows):
                    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
                    key_cols = _unflatten_eq(eq_layout, eq_bufs)
                    # NULL join keys never match: keep them (and the
                    # shape-bucket padding rows) out of the table
                    for _, m in key_cols:
                        if m is not None:
                            live = live & m
                    h = ht.cheap_hash(key_cols, cap)
                    if kr:
                        # fused (key32|row) entries: probes need ONE
                        # gather per round instead of table->row->key
                        k32 = ht.key_u32(*key_cols[0])
                        tab, dup = ht.insert_kr(
                            k32, h, live, cap, tsize
                        )
                        return tab, dup
                    _slot, tab, dup, _ovf = ht.insert(
                        h, key_cols, live, cap, tsize,
                        null_equal=False,
                    )
                    return tab, dup

                return kernel

            fn = cached_kernel(
                ("join_table", eq_layout, cap, tsize, kr), build_table,
                scatter_class=True, span="join_dispatch",
            )
            tab, dup = fn(
                _flatten_cols(build_cols),
                self.build.num_rows,
            )
            # one blocking scalar per build relation: unique keys take
            # the table core; duplicates demote to the sorted core
            if not host_int(dup):
                self._index = ("table_kr" if kr else "table", tab)
                return
            self._table_demoted = True

        bufs = _key_hash_cols(build_cols)
        dtypes = tuple(d for _, _, d in bufs)

        def build():
            def kernel(values, valids, num_rows):
                cols = list(zip(values, valids, dtypes))
                h = hash_columns_device(cols, cap).astype(jnp.int32)
                # NULL keys hash like values and are rejected later by
                # the equality check, so collisions only cost
                # verification work. Padding rows must not enter the
                # index: a build table well under its shape bucket
                # would otherwise contribute cap-num_rows phantom
                # candidates per probe row whose key equals the padding
                # value (observed 11x pair expansion on a 131k-row dim
                # table in a 1M bucket). INT32_MAX herds them into one
                # run at the top; a genuine probe hash there still
                # verifies by exact key + liveness in emit_pairs.
                live = jnp.arange(cap, dtype=jnp.int32) < num_rows
                h = jnp.where(live, h, jnp.int32(0x7FFFFFFF))
                order = jnp.argsort(h, stable=True)
                return jnp.take(h, order), order

            return kernel

        fn = cached_kernel(
            ("join_index", dtypes, cap), build, span="join_dispatch"
        )
        h_sorted, order = fn(
            tuple(v for v, _, _ in bufs), tuple(m for _, m, _ in bufs),
            self.build.num_rows,
        )
        self._index = ("sorted", h_sorted, order)

    def _check_probe_dtypes(self, unified_b, unified_p):
        """The kr table's 32-bit key encoding cannot express a wider
        probe key (i64/f64 vs an i32/f32 build): rebuild as a GENERIC
        table, whose cheap_hash is value-consistent across widths and
        whose equality check promotes - mixed-width keys then join
        correctly (the sorted core's murmur3 is dtype-semantic, Spark
        hashInt vs hashLong, and would silently miss them)."""
        if self._index[0] == "table_direct":
            # the direct lookup subtracts in int64, so ANY integer
            # probe width is exact; a non-integer probe (float-unified
            # keys) would truncate and must rebuild generic
            if all(
                jnp.issubdtype(p.values.dtype, jnp.integer)
                for p in unified_p
            ):
                return
            self._force_generic = True
            self._index = None
            self._ensure_index(unified_b)
            return
        if self._index[0] != "table_kr":
            return
        if all(
            b.values.dtype == p.values.dtype
            for b, p in zip(unified_b, unified_p)
        ):
            return
        self._force_generic = True
        self._index = None
        self._ensure_index(unified_b)

    def table_state(self, probe_cb: ColumnBatch,
                    probe_keys: List[int]):
        """Table-core state WITHOUT dispatching the lookup kernel, for
        callers that fuse the lookup into their own program (the fused
        join+aggregate path). Returns ((probe_cb, unified_b, unified_p,
        tab, mode) | None, probe_cb): `mode` is "table" (row-index
        table, ht.lookup), "table_kr" (fused key|row u64 entries,
        ht.lookup_kr), or "table_direct" (dense-domain key->row array,
        ht.lookup_direct, tab = (array, base, span));
        None means the core resolved to sorted
        (duplicate keys or the sort knob) and the caller should use
        probe()/emit_pairs()."""
        probe_cb = ensure_compacted(probe_cb)
        build_cols = [self.build.columns[i] for i in self.build_keys]
        probe_cols = [probe_cb.columns[i] for i in probe_keys]
        unified_b, unified_p = [], []
        for bc, pc_ in zip(build_cols, probe_cols):
            b2, p2 = _unify_key_pair(bc, pc_)
            unified_b.append(b2)
            unified_p.append(p2)
        with self._index_lock:
            self._ensure_index(unified_b)
            self._check_probe_dtypes(unified_b, unified_p)
            index = self._index
        if index[0] not in ("table", "table_kr", "table_direct"):
            return None, probe_cb
        return (
            (probe_cb, unified_b, unified_p, index[1], index[0]),
            probe_cb,
        )

    def table_state_static(self, probe_keys: List[int],
                           probe_schema: Schema):
        """Table-core state WITHOUT a materialized probe batch, for the
        probe-chain-folded fused join: the probe keys are evaluated
        INSIDE the consumer's kernel, so eligibility must be decided
        from static probe dtypes alone. Dictionary-encoded keys on
        either side are out (unification needs host key values); the
        kr/direct width checks mirror _check_probe_dtypes using the
        probe fields' physical dtypes (the engine-wide invariant that
        evaluated buffers carry their field's physical dtype - the
        folded kernel asserts it at trace time). Returns (mode, tab) or
        None (sorted core / ineligible shape); None means the caller
        should materialize the probe batch and use table_state()."""
        build_cols = [self.build.columns[i] for i in self.build_keys]
        if any(c.dtype.is_dictionary_encoded for c in build_cols):
            return None
        p_fields = [probe_schema.fields[i] for i in probe_keys]
        if any(
            f.dtype.is_dictionary_encoded
            or f.dtype.is_string_like
            or f.dtype.is_wide_decimal
            for f in p_fields
        ):
            return None
        p_dtypes = [
            np.dtype(f.dtype.physical_dtype()) for f in p_fields
        ]
        with self._index_lock:
            self._ensure_index(build_cols)
            # width demotions, statically (mirror _check_probe_dtypes)
            if self._index[0] == "table_direct" and not all(
                np.issubdtype(dt, np.integer) for dt in p_dtypes
            ):
                self._force_generic = True
                self._index = None
                self._ensure_index(build_cols)
            elif self._index[0] == "table_kr" and not all(
                b.values.dtype == dt
                for b, dt in zip(build_cols, p_dtypes)
            ):
                self._force_generic = True
                self._index = None
                self._ensure_index(build_cols)
            index = self._index
        if index[0] not in ("table", "table_kr", "table_direct"):
            return None
        return index[0], index[1]

    def probe(self, probe_cb: ColumnBatch, probe_keys: List[int]):
        """Hash the probe keys and size the pair expansion (one host
        sync). Returns the state tuple for emit_pairs(); emission - and
        the matched_build update - happens only when emit_pairs() runs,
        so read core.matched_build only after that call."""
        probe_cb = ensure_compacted(probe_cb)
        build_cols = [self.build.columns[i] for i in self.build_keys]
        probe_cols = [probe_cb.columns[i] for i in probe_keys]
        unified_b, unified_p = [], []
        for bc, pc_ in zip(build_cols, probe_cols):
            b2, p2 = _unify_key_pair(bc, pc_)
            unified_b.append(b2)
            unified_p.append(p2)
        with self._index_lock:
            self._ensure_index(unified_b)
            self._check_probe_dtypes(unified_b, unified_p)
            index = self._index
        pcap = probe_cb.capacity

        if index[0] in ("table", "table_kr", "table_direct"):
            mode = index[0]
            tab = index[1]
            bcap = self.build.capacity
            b_eq_layout = _eq_layout(unified_b)
            p_eq_layout = _eq_layout(unified_p)

            def build_lookup():
                def kernel(b_eq, p_eq, tab, num_rows):
                    # num_rows=None: full probe batch (constant mask
                    # folds into the downstream selects)
                    live = (
                        jnp.ones(pcap, dtype=jnp.bool_)
                        if num_rows is None
                        else jnp.arange(pcap, dtype=jnp.int32)
                        < num_rows
                    )
                    pkeys = _unflatten_eq(p_eq_layout, p_eq)
                    for _, m in pkeys:
                        if m is not None:
                            live = live & m  # NULL never matches
                    return _table_lookup(
                        mode, tab, pkeys,
                        _unflatten_eq(b_eq_layout, b_eq),
                        live, bcap,
                    )

                return kernel

            fn = cached_kernel(
                ("join_lookup", mode, b_eq_layout, p_eq_layout, bcap,
                 pcap),
                build_lookup,
                span="join_dispatch",
            )
            match_idx, matched = fn(
                _flatten_cols(unified_b),
                _flatten_cols(unified_p),
                tab,
                None if probe_cb.num_rows == pcap
                else probe_cb.num_rows,
            )
            # NO host sync: output capacity is statically the probe
            # capacity (each probe row matches at most one build row)
            return (
                "table", probe_cb, match_idx, matched, pcap
            )

        _tag, h_sorted, order = index
        # hash-time cast for mixed-width keys: murmur3 is dtype-semantic
        # (Spark hashInt != hashLong for equal values), so a wider probe
        # key hashes into the wrong run and silently misses. Casting the
        # probe to the build dtype FOR BUCKETING ONLY is safe: values
        # outside the build dtype's range wrap into some run whose
        # candidates the emit kernel's exact (promoting) equality check
        # rejects, and in-range/representable values cast losslessly.
        hash_p = [
            p2 if p2.values.dtype == b2.values.dtype
            or p2.dtype.is_dictionary_encoded
            else Column(
                b2.dtype, p2.values.astype(b2.values.dtype),
                p2.validity, p2.dictionary,
            )
            for b2, p2 in zip(unified_b, unified_p)
        ]
        pbufs = _key_hash_cols(hash_p)
        pdtypes = tuple(d for _, _, d in pbufs)

        def build_counts():
            def kernel(values, valids, h_sorted, num_rows):
                cols = list(zip(values, valids, pdtypes))
                h = hash_columns_device(cols, pcap).astype(jnp.int32)
                lo = jnp.searchsorted(h_sorted, h, side="left")
                hi = jnp.searchsorted(h_sorted, h, side="right")
                counts = (hi - lo).astype(jnp.int32)
                live = jnp.arange(pcap, dtype=jnp.int32) < num_rows
                counts = jnp.where(live, counts, 0)
                return counts, lo.astype(jnp.int32), jnp.sum(counts)

            return kernel

        fn = cached_kernel(
            ("join_counts", pdtypes, pcap), build_counts,
            span="join_dispatch",
        )
        counts, lo, total_dev = fn(
            tuple(v for v, _, _ in pbufs),
            tuple(m for _, m, _ in pbufs),
            h_sorted,
            probe_cb.num_rows,
        )
        total = host_int(total_dev)
        pair_cap = max(get_config().bucket_for(total), 1)
        return (
            "sorted", probe_cb, unified_b, unified_p, counts, lo,
            order, pair_cap,
        )

    def emit_pairs(self, probe_state, out_build_cols: List[Column],
                   out_probe_cols: List[Column], build_first: bool):
        """ONE kernel: expand candidate pairs, verify key equality, gather
        both sides' output columns, fold matched flags. Returns
        (out_columns, valid, pair_cap, matched_probe) and updates
        matched_build."""
        if probe_state[0] == "table":
            return self._emit_table(
                probe_state, out_build_cols, out_probe_cols,
                build_first,
            )
        (_tag, probe_cb, unified_b, unified_p, counts, lo, order,
         pair_cap) = probe_state
        bcap = self.build.capacity
        pcap = probe_cb.capacity
        b_layout = _eq_layout(out_build_cols)
        p_layout = _eq_layout(out_probe_cols)
        k_layout = tuple(
            (b2.values.dtype.str, b2.validity is not None,
             p2.values.dtype.str, p2.validity is not None)
            for b2, p2 in zip(unified_b, unified_p)
        )
        n_b = len(out_build_cols)
        n_p = len(out_probe_cols)

        def build_emit():
            def kernel(counts, lo, order, bkey_bufs, pkey_bufs,
                       bout_bufs, pout_bufs, build_rows, probe_rows,
                       matched_build):
                # ---- expand ----
                offsets = jnp.cumsum(counts) - counts
                ends = jnp.cumsum(counts)
                total = jnp.sum(counts)
                pos = jnp.arange(pair_cap, dtype=jnp.int32)
                pair_p = jnp.searchsorted(ends, pos, side="right")
                pair_p = jnp.clip(
                    pair_p, 0, counts.shape[0] - 1
                ).astype(jnp.int32)
                within = pos - jnp.take(offsets, pair_p)
                sorted_pos = jnp.take(lo, pair_p) + within
                sorted_pos = jnp.clip(sorted_pos, 0, order.shape[0] - 1)
                pair_b = jnp.take(order, sorted_pos)
                valid = pos < total
                # ---- verify true key equality ----
                live_b = jnp.arange(bcap, dtype=jnp.int32) < build_rows
                valid = valid & jnp.take(live_b, pair_b)
                ki = iter(zip(bkey_bufs, pkey_bufs))
                for _ in k_layout:
                    bv_all, (pv_all, bmask, pmask) = next(ki)
                    bv = jnp.take(bv_all, pair_b)
                    pv = jnp.take(pv_all, pair_p)
                    eq = bv == pv
                    if jnp.issubdtype(bv.dtype, jnp.floating):
                        eq = eq | (jnp.isnan(bv) & jnp.isnan(pv))
                    if bmask is not None:
                        eq = eq & jnp.take(bmask, pair_b)
                    if pmask is not None:
                        eq = eq & jnp.take(pmask, pair_p)
                    valid = valid & eq
                # ---- matched flags ----
                live_p = jnp.arange(pcap, dtype=jnp.int32) < probe_rows
                mp = (
                    jax.ops.segment_sum(
                        valid.astype(jnp.int32),
                        jnp.clip(pair_p, 0, pcap - 1),
                        num_segments=pcap,
                    ) > 0
                ) & live_p
                mb = matched_build | (
                    jax.ops.segment_sum(
                        valid.astype(jnp.int32),
                        jnp.clip(pair_b, 0, bcap - 1),
                        num_segments=bcap,
                    ) > 0
                )
                # ---- gather output columns ----
                def gather(bufs, layout, idx, cap_in):
                    out = []
                    it = iter(bufs)
                    ci = jnp.clip(idx, 0, cap_in - 1)
                    for _, has_m in layout:
                        v = next(it)
                        out.append(jnp.take(v, ci, axis=0))
                        if has_m:
                            out.append(jnp.take(next(it), ci, axis=0))
                        else:
                            out.append(None)
                    return out

                bout = gather(bout_bufs, b_layout, pair_b, bcap)
                pout = gather(pout_bufs, p_layout, pair_p, pcap)
                return bout, pout, valid, mp, mb

            return kernel

        fn = cached_kernel(
            ("join_emit", k_layout, b_layout, p_layout, bcap, pcap,
             pair_cap, n_b, n_p),
            build_emit,
            scatter_class=True, span="join_dispatch",
        )
        bkey_bufs = tuple(b2.values for b2 in unified_b)
        pkey_bufs = tuple(
            (p2.values, b2.validity, p2.validity)
            for b2, p2 in zip(unified_b, unified_p)
        )
        bout_bufs = _flatten_cols(out_build_cols)
        pout_bufs = _flatten_cols(out_probe_cols)
        bout, pout, valid, matched_p, mb = fn(
            counts, lo, order, bkey_bufs, pkey_bufs, bout_bufs,
            pout_bufs, self.build.num_rows, probe_cb.num_rows,
            self.matched_build,
        )
        self.matched_build = mb
        bcols = _rewrap_cols(out_build_cols, bout)
        pcols = _rewrap_cols(out_probe_cols, pout)
        if build_first:
            out_cols = bcols + pcols
        else:
            out_cols = pcols + bcols
        return out_cols, valid, pair_cap, matched_p

    def _emit_table(self, probe_state, out_build_cols: List[Column],
                    out_probe_cols: List[Column], build_first: bool):
        """Table-core emission: output row i IS probe row i (unique
        build keys guarantee at most one match per probe row), so the
        probe columns pass through untouched and only the build side
        gathers - plus one scatter to fold matched-build flags."""
        _tag, probe_cb, match_idx, matched, pair_cap = probe_state
        bcap = self.build.capacity
        pcap = probe_cb.capacity
        b_layout = _eq_layout(out_build_cols)

        def build_emit():
            def kernel(match_idx, matched, bout_bufs, probe_rows,
                       matched_build):
                live_p = (
                    jnp.arange(pcap, dtype=jnp.int32) < probe_rows
                )
                valid = matched & live_p
                pair_b = jnp.clip(match_idx, 0, bcap - 1)
                mb = matched_build | (
                    jnp.zeros(bcap, jnp.int32)
                    .at[pair_b]
                    .add(valid.astype(jnp.int32), mode="drop")
                    > 0
                )
                out = []
                it = iter(bout_bufs)
                for _, has_m in b_layout:
                    v = next(it)
                    out.append(jnp.take(v, pair_b, axis=0))
                    if has_m:
                        out.append(
                            jnp.take(next(it), pair_b, axis=0)
                        )
                    else:
                        out.append(None)
                return out, valid, mb

            return kernel

        fn = cached_kernel(
            ("join_emit_table", b_layout, bcap, pcap,
             len(out_build_cols)),
            build_emit,
            scatter_class=True, span="join_dispatch",
        )
        bout, valid, mb = fn(
            match_idx, matched, _flatten_cols(out_build_cols),
            probe_cb.num_rows, self.matched_build,
        )
        self.matched_build = mb
        bcols = _rewrap_cols(out_build_cols, bout)
        pcols = list(out_probe_cols)
        if build_first:
            out_cols = bcols + pcols
        else:
            out_cols = pcols + bcols
        return out_cols, valid, pair_cap, valid


def _eq_layout(cols: List[Column]) -> Tuple:
    """Hashable layout of (values dtype, has-validity) per key column -
    MUST stay the single source for both kernel cache keys and
    _unflatten_eq buffer reconstruction."""
    return tuple(
        (c.values.dtype.str, c.validity is not None) for c in cols
    )


def _kr_eligible(cols: List[Column]) -> bool:
    """Single narrow key -> the fused (key|row) u64 table applies."""
    if len(cols) != 1:
        return False
    dt = cols[0].values.dtype
    return bool(
        dt == jnp.bool_
        or dt == jnp.float32
        or (jnp.issubdtype(dt, jnp.integer) and dt.itemsize <= 4)
    )


def _table_lookup(mode, tab, pkeys, bkeys, live, bcap):
    """Mode-dispatched table probe shared by the standalone lookup
    kernel and the fused join+aggregate kernel."""
    from blaze_tpu.ops import hash_table as ht

    if mode == "table_direct":
        # no hash, no probe rounds: callers already folded NULL masks
        # into `live`
        tab_arr, base, span = tab
        return ht.lookup_direct(tab_arr, base, span, pkeys[0][0], live)
    h = ht.cheap_hash(pkeys, live.shape[0])
    if mode == "table_kr":
        k32 = ht.key_u32(*pkeys[0])
        return ht.lookup_kr(tab, k32, h, live)
    return ht.lookup(
        tab, h, pkeys, bkeys, live, bcap, null_equal=False
    )


def _unflatten_eq(layout, bufs):
    """Inverse of _flatten_cols for (values, validity) key pairs."""
    out = []
    it = iter(bufs)
    for _, has_m in layout:
        v = next(it)
        m = next(it) if has_m else None
        out.append((v, m))
    return out


def _flatten_cols(cols: List[Column]):
    bufs = []
    for c in cols:
        bufs.append(c.values)
        if c.validity is not None:
            bufs.append(c.validity)
    return tuple(bufs)


def _rewrap_cols(cols: List[Column], flat) -> List[Column]:
    out = []
    it = iter(flat)
    for c in cols:
        v = next(it)
        m = next(it)
        out.append(Column(c.dtype, v, m, c.dictionary))
    return out


def _null_side(schema_fields, capacity: int) -> List[Column]:
    # numpy zeros: all-NULL padding columns cost no device dispatch; they
    # upload lazily only if a downstream kernel actually consumes them
    cols = []
    for f in schema_fields:
        phys = f.dtype.physical_dtype()
        shape = (capacity, 2) if f.dtype.is_wide_decimal else (capacity,)
        cols.append(
            Column(
                f.dtype,
                np.zeros(shape, dtype=phys),
                np.zeros(capacity, dtype=bool),
                None,
            )
        )
    return cols


class HashJoinExec(PhysicalOp):
    """Broadcast hash join, CollectLeft: the LEFT child is materialized
    (broadcast relation), the RIGHT child streams (reference
    from_proto.rs:349-428 PartitionMode::CollectLeft)."""

    # join types whose build-side epilogue (unmatched-build padding,
    # semi/anti output) depends on matched state across ALL probe
    # partitions. Probes still run per-partition in parallel; each
    # partition OR-merges its local matched-build bitmap into a shared
    # accumulator and the LAST partition to finish emits the epilogue
    # (reference CollectLeft probes per-partition the same way,
    # from_proto.rs:349-428)
    _BUILD_EMITTING = frozenset(
        {JoinType.LEFT, JoinType.FULL, JoinType.LEFT_SEMI,
         JoinType.LEFT_ANTI, JoinType.LEFT_ANTI_NULL_AWARE}
    )

    def __init__(self, left: PhysicalOp, right: PhysicalOp,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 join_type: JoinType = JoinType.INNER):
        if join_type is JoinType.LEFT_ANTI_NULL_AWARE:
            raise NotImplementedError(
                "null-aware anti join runs through SortMergeJoinExec"
            )
        self.children = [left, right]
        self.left_keys = [left.schema.index_of(k) for k in left_keys]
        self.right_keys = [right.schema.index_of(k) for k in right_keys]
        self.join_type = join_type
        self._schema = _joined_schema(
            left.schema, right.schema, join_type
        )
        self._build: Optional[ColumnBatch] = None
        import threading

        self._build_lock = threading.Lock()
        # epilogue coordination (epoch-reset so a plan object can run
        # more than once, e.g. benchmark warmup loops). A SET of
        # completed partition ids - not a counter - so abandoned
        # generators (LimitExec early return, sampling passes) and
        # partition re-runs stay idempotent
        self._epi_lock = threading.Lock()
        self._epi_matched = None
        self._epi_parts: set = set()

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return (f"{self.join_type.name};l={self.left_keys};"
                f"r={self.right_keys}")

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return self.children[1].partition_count

    def _collect_build(self, ctx: ExecContext) -> ColumnBatch:
        """Collect the build relation ONCE and share it across probe
        partitions (reference CollectLeft collects one shared build)."""
        with self._build_lock:
            if self._build is None:
                left = self.children[0]
                if getattr(left, "is_broadcast", False):
                    # a broadcast child replays the FULL relation from any
                    # one partition; collecting all would duplicate rows
                    batches = list(left.execute(0, ctx))
                else:
                    batches = [
                        b
                        for p in range(left.partition_count)
                        for b in left.execute(p, ctx)
                    ]
                self._build = concat_batches(
                    batches, schema=left.schema
                )
            return self._build

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        left, right = self.children
        jt = self.join_type
        build = self._collect_build(ctx)
        core = _JoinCore(build, self.left_keys)
        emit_pairs = jt in (
            JoinType.INNER, JoinType.LEFT, JoinType.RIGHT, JoinType.FULL
        )
        for pb in right.execute(partition, ctx):
            state = core.probe(pb, self.right_keys)
            pb = state[1]
            bcols = build.columns if emit_pairs else []
            pcols = pb.columns if emit_pairs else []
            out_cols, valid, pair_cap, matched_p = core.emit_pairs(
                state, bcols, pcols, build_first=True
            )
            if emit_pairs:
                yield ColumnBatch(
                    self._schema, out_cols, pair_cap, valid
                )
            if jt in (JoinType.RIGHT, JoinType.FULL):
                un = row_mask(pb.num_rows, pb.capacity) & ~matched_p
                lnull = _null_side(left.schema.fields, pb.capacity)
                yield ColumnBatch(
                    self._schema, lnull + list(pb.columns),
                    pb.num_rows, un,
                )
        if jt in self._BUILD_EMITTING:
            yield from self._build_epilogue(
                core.matched_build, build, partition,
                right.partition_count,
            )

    def _build_epilogue(self, local_matched, build: ColumnBatch,
                        partition: int, n_parts: int
                        ) -> Iterator[ColumnBatch]:
        """OR-merge this partition's matched-build bitmap; the run that
        completes the partition set emits the build-side output, then
        resets the epoch so the plan object can run again."""
        left, right = self.children
        jt = self.join_type
        with self._epi_lock:
            if self._epi_matched is None:
                self._epi_matched = local_matched
            else:
                self._epi_matched = self._epi_matched | local_matched
            self._epi_parts.add(partition)
            if len(self._epi_parts) < n_parts:
                return
            matched = self._epi_matched
            self._epi_matched = None
            self._epi_parts = set()
        live_b = row_mask(build.num_rows, build.capacity)
        if jt in (JoinType.LEFT, JoinType.FULL):
            un = live_b & ~matched
            rnull = _null_side(right.schema.fields, build.capacity)
            yield ColumnBatch(
                self._schema, list(build.columns) + rnull,
                build.num_rows, un,
            )
        elif jt is JoinType.LEFT_SEMI:
            yield ColumnBatch(
                self._schema, list(build.columns), build.num_rows,
                live_b & matched,
            )
        elif jt is JoinType.LEFT_ANTI:
            yield ColumnBatch(
                self._schema, list(build.columns), build.num_rows,
                live_b & ~matched,
            )


class SortMergeJoinExec(PhysicalOp):
    """Sort-merge join over co-partitioned sorted inputs.

    The reference streams both sides with single-row cursors
    (sort_merge_join_exec.rs:293-601); that shape is hostile to
    vectorization (SURVEY 7 hard parts), so here each partition pair is
    materialized and joined with the shared vectorized core - the LEFT
    (streamed) side's order is preserved in the output, matching the
    reference's emission order. Semi/Anti are left-side like the
    reference's join_semi (sort_merge_join_exec.rs:603)."""

    def __init__(self, left: PhysicalOp, right: PhysicalOp,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 join_type: JoinType = JoinType.INNER):
        self.children = [left, right]
        self.left_keys = [left.schema.index_of(k) for k in left_keys]
        self.right_keys = [right.schema.index_of(k) for k in right_keys]
        self.join_type = join_type
        self._schema = _joined_schema(left.schema, right.schema, join_type)

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return (f"{self.join_type.name};l={self.left_keys};"
                f"r={self.right_keys}")

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return self.children[0].partition_count

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.ops.external import bucket_stream, collect_until

        left, right = self.children
        limit = ctx.config.max_materialize_rows
        r_it = right.execute(partition, ctx)
        r_head, r_exc = collect_until(r_it, limit)
        l_it = left.execute(partition, ctx)
        l_head, l_exc = collect_until(l_it, limit)
        if self.join_type is JoinType.LEFT_ANTI_NULL_AWARE:
            # "any build NULL -> empty result" is a GLOBAL property, so
            # NAAJ cannot bucket; NOT-IN subquery build sides are small
            l_head += list(l_it)
            r_head += list(r_it)
            yield from self._join_bucket(l_head, r_head)
            return
        if not (r_exc or l_exc):
            yield from self._join_bucket(l_head, r_head)
            return
        # grace join: co-bucket both sides on the join keys; equal keys
        # land in the same bucket, so every join type is correct per
        # bucket. Bucket count comes from the HBM budget: one bucket's
        # materialization must fit the device headroom (the collected
        # heads are at the materialize cap, so 2x them estimates the
        # stream)
        from blaze_tpu.runtime.memory import (
            batch_device_bytes,
            choose_external_bucket_count,
            get_device_tracker,
        )

        head_bytes = sum(batch_device_bytes(b) for b in l_head) + sum(
            batch_device_bytes(b) for b in r_head
        )
        est = 2 * head_bytes
        tracker = get_device_tracker()
        # key includes the partition: concurrent partitions of one op
        # account (and release) independently
        track_key = (id(self), ctx.partition_id)
        tracker.track(track_key, head_bytes)
        try:
            n_b = choose_external_bucket_count(est, ctx.config)
            yield from self._grace_join(
                l_it, r_it, l_head, r_head, ctx, n_b, depth=0
            )
        finally:
            tracker.release(track_key)

    _MAX_GRACE_DEPTH = 2
    _GRACE_FANOUT = 4

    def _grace_join(self, l_it, r_it, l_head, r_head, ctx: ExecContext,
                    n_b: int, depth: int, modulus: Optional[int] = None
                    ) -> Iterator[ColumnBatch]:
        """One grace level: bucket both sides, join fitting buckets; a
        bucket still over the materialize cap RE-BUCKETS recursively by
        the NEXT hash bits (fanout-way split of just that bucket -
        splits many-key overflow; a single hot key can't split and is
        joined materialized at max depth)."""
        from blaze_tpu.ops.external import (
            bucket_stream,
            collect_until,
            subdivide_pid_fn,
        )

        left, right = self.children
        lkeys = [
            ir.BoundCol(i, left.schema.fields[i].dtype)
            for i in self.left_keys
        ]
        rkeys = [
            ir.BoundCol(i, right.schema.fields[i].dtype)
            for i in self.right_keys
        ]
        if modulus is None:
            modulus = n_b
            l_pid = r_pid = None
        else:
            l_pid = subdivide_pid_fn(lkeys, modulus, n_b)
            r_pid = subdivide_pid_fn(rkeys, modulus, n_b)
            modulus *= n_b
        bl = br = None
        try:
            bl = bucket_stream(l_it, lkeys, n_b, ctx, left.schema,
                               head=l_head, pid_fn=l_pid)
            br = bucket_stream(r_it, rkeys, n_b, ctx, right.schema,
                               head=r_head, pid_fn=r_pid)
            ctx.metrics.add("external_join_buckets", n_b)
            limit = ctx.config.max_materialize_rows
            for b in range(n_b):
                lb_it = bl.bucket(b)
                rb_it = br.bucket(b)
                lb_head, l_exc = collect_until(lb_it, limit)
                rb_head, r_exc = collect_until(rb_it, limit)
                if (l_exc or r_exc) and depth < self._MAX_GRACE_DEPTH:
                    ctx.metrics.add("external_join_rebuckets", 1)
                    yield from self._grace_join(
                        lb_it, rb_it, lb_head, rb_head, ctx,
                        self._GRACE_FANOUT, depth + 1, modulus,
                    )
                    continue
                if l_exc or r_exc:
                    # single hot key survives every re-bucket; join it
                    # materialized (correct, memory-heavy) and record it
                    ctx.metrics.add("external_join_hot_buckets", 1)
                    lb_head += list(lb_it)
                    rb_head += list(rb_it)
                if lb_head or rb_head:
                    yield from self._join_bucket(lb_head, rb_head)
        finally:
            if bl is not None:
                bl.cleanup()
            if br is not None:
                br.cleanup()

    def _join_bucket(self, left_batches, right_batches
                     ) -> Iterator[ColumnBatch]:
        left, right = self.children
        jt = self.join_type
        build = concat_batches(right_batches, schema=right.schema)
        core = _JoinCore(build, self.right_keys)
        probe = concat_batches(left_batches, schema=left.schema)
        state = core.probe(probe, self.left_keys)
        probe = state[1]
        emit = jt in (JoinType.INNER, JoinType.LEFT, JoinType.RIGHT,
                      JoinType.FULL)
        bcols = build.columns if emit else []
        pcols = probe.columns if emit else []
        out_cols, valid, pair_cap, matched_p = core.emit_pairs(
            state, bcols, pcols, build_first=False
        )
        live_p = row_mask(probe.num_rows, probe.capacity)
        if emit:
            yield ColumnBatch(self._schema, out_cols, pair_cap, valid)
            if jt in (JoinType.LEFT, JoinType.FULL):
                un = live_p & ~matched_p
                rnull = _null_side(right.schema.fields, probe.capacity)
                yield ColumnBatch(
                    self._schema, list(probe.columns) + rnull,
                    probe.num_rows, un,
                )
            if jt in (JoinType.RIGHT, JoinType.FULL):
                live_b = row_mask(build.num_rows, build.capacity)
                un = live_b & ~core.matched_build
                lnull = _null_side(left.schema.fields, build.capacity)
                yield ColumnBatch(
                    self._schema, lnull + list(build.columns),
                    build.num_rows, un,
                )
        elif jt is JoinType.LEFT_SEMI:
            yield ColumnBatch(
                self._schema, list(probe.columns), probe.num_rows,
                live_p & matched_p,
            )
        elif jt is JoinType.LEFT_ANTI:
            yield ColumnBatch(
                self._schema, list(probe.columns), probe.num_rows,
                live_p & ~matched_p,
            )
        elif jt is JoinType.LEFT_ANTI_NULL_AWARE:
            # NOT IN: probe rows with NULL keys never qualify, and any
            # NULL key on the build side empties the result entirely
            def keys_valid(cb, idxs, live):
                ok = jnp.ones(cb.capacity, dtype=jnp.bool_)
                for i in idxs:
                    c = cb.columns[i]
                    if c.validity is not None:
                        ok = ok & c.validity
                return ok

            live_b = row_mask(build.num_rows, build.capacity)
            build_has_null = jnp.any(
                live_b & ~keys_valid(build, self.right_keys, live_b)
            )
            probe_ok = keys_valid(probe, self.left_keys, live_p)
            sel = (
                live_p & ~matched_p & probe_ok & ~build_has_null
            )
            yield ColumnBatch(
                self._schema, list(probe.columns), probe.num_rows, sel
            )
