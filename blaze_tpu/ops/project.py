"""Projection: evaluate expressions into a new column set.

The expression trees compile straight into XLA (reference counterpart:
DataFusion ProjectionExec built from proto, from_proto.rs:173-192; wrapper
NativeProjectExec.scala:61-77). One jitted function per (expr tuple, batch
layout); elementwise work fuses with upstream/downstream device ops.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from blaze_tpu.types import Field, Schema
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.host_lower import lower_strings_host
from blaze_tpu.runtime.dispatch import cached_kernel


class ProjectExec(PhysicalOp):
    def __init__(self, child: PhysicalOp,
                 exprs: Sequence[Tuple[ir.Expr, str]]):
        self.children = [child]
        from blaze_tpu.exprs.typing import expr_computes_wide_decimal

        self.exprs = [(bind_opt(e, child.schema), name) for e, name in exprs]
        for e, _ in self.exprs:
            if expr_computes_wide_decimal(e, child.schema):
                raise NotImplementedError(
                    "compute on decimal(>18) is host-tier work"
                )
        self._schema = Schema(
            [
                Field(name, infer_dtype(e, child.schema), True)
                for e, name in self.exprs
            ]
        )

    @property
    def schema(self) -> Schema:
        return self._schema

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return ";".join(f"{n}={e!r}" for e, n in self.exprs)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        child = self.children[0]
        m = ctx.metrics
        for cb in child.execute(partition, ctx):
            yield self._project(cb)

    def _project(self, cb: ColumnBatch) -> ColumnBatch:
        # split string-typed subtrees out to the host tier
        exprs, host_cols, aug = lower_strings_host(
            [e for e, _ in self.exprs], cb
        )
        in_schema = aug.schema
        cap = aug.capacity
        layout = aug.layout()

        def build():
            def run(bufs):
                cols = _unflatten_cvs(layout, bufs)
                ev = DeviceEvaluator(in_schema, cols, cap)
                out = []
                for e in exprs:
                    v, mm = ev.evaluate(e)
                    out.append((v, mm))
                return out

            return run

        fn = cached_kernel(("project", tuple(exprs), layout), build)
        results = fn(aug.device_buffers())
        out_cols: List[Column] = []
        for (e, (_, name)), (v, mm) in zip(
            zip(exprs, self.exprs), results
        ):
            dt = infer_dtype(e, aug.schema)
            dictionary = None
            if dt.is_dictionary_encoded:
                # string passthrough: recover the dictionary by column ref
                dictionary = _passthrough_dictionary(e, aug)
            out_cols.append(Column(dt, v, mm, dictionary))
        return ColumnBatch(
            self._schema, out_cols, cb.num_rows, cb.selection
        )


def _unflatten_cvs(layout, bufs):
    _, col_layout = layout
    out = []
    it = iter(bufs)
    for tid, prec, scale, has_mask in col_layout:
        v = next(it)
        m = next(it) if has_mask else None
        out.append((v, m))
    return out


def _passthrough_dictionary(e: ir.Expr, cb: ColumnBatch):
    if isinstance(e, ir.BoundCol):
        return cb.columns[e.index].dictionary
    return None
