"""Device open-addressing hash table: sort-free grouping and join lookup.

Why: the engine's original grouping/join cores are sort-based (one lexsort
of the full input per aggregate, argsort+searchsorted per join build/probe).
Sorts are O(n log n) with a large constant on XLA:CPU (an 8M-row argsort
measures ~3.5s on one core vs ~0.03s for a scatter over the same rows) and
the sort result is only used to assign group ids / locate matches. This
module replaces that with the classic vectorized open-addressing scheme,
built entirely from scatter/gather primitives that XLA executes in O(n):

  insert:  every live row hashes to a home slot in a power-of-two table;
           rounds of `table.at[slot].min(row_index)` claim empty slots
           (ties resolved by the min), a gather-back + exact key
           comparison resolves rows whose key already owns the slot, and
           unresolved rows advance to the next slot (linear probing)
           inside one `lax.while_loop`. Occupied slots are never
           overwritten, so the linear-probe invariant (no empty slot
           between a key's home and its resting slot) holds and lookups
           may stop at the first empty slot.
  lookup:  probe rows walk the same chain, comparing true key values at
           each step - hash collisions cost extra steps, never wrong
           answers.

Equality is exact (not hash equality): NaN matches NaN (Spark normalizes
NaN keys), and NULL handling is caller-chosen: grouping treats NULL as a
key value (SQL GROUP BY: NULL groups with NULL), joins never match NULL.

Reference counterpart: the DataFusion hash-join/hash-aggregate RawTable
paths the reference reuses (from_proto.rs:349-545). The design here is
deliberately not a row-cursor translation: every step is a whole-array
scatter/gather so one XLA program handles the entire batch.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def table_size_for(capacity: int) -> int:
    """Power-of-two table with load factor <= 0.5 at full capacity, so
    insertion always terminates (an empty slot exists on every probe
    chain) and expected chains stay O(1)."""
    t = 1024
    while t < 2 * capacity:
        t <<= 1
    return t


def _pairwise_eq(av, am, bv, bm, null_equal: bool):
    """Exact equality of key values gathered from two row sets.

    `av/am` and `bv/bm` are aligned (already gathered) value/validity
    arrays. NaN == NaN; NULL semantics per `null_equal`."""
    eq = av == bv
    if jnp.issubdtype(av.dtype, jnp.floating):
        eq = eq | (jnp.isnan(av) & jnp.isnan(bv))
    if am is None and bm is None:
        return eq
    at = am if am is not None else jnp.ones(av.shape[0], jnp.bool_)
    bt = bm if bm is not None else jnp.ones(bv.shape[0], jnp.bool_)
    if null_equal:
        # (both valid and equal) or (both null)
        return jnp.where(at & bt, eq, at == bt)
    return eq & at & bt


def _keys_at(key_cols, idx):
    """Gather (values, validity) of every key column at row indices."""
    out = []
    for v, m in key_cols:
        out.append(
            (
                jnp.take(v, idx, axis=0),
                jnp.take(m, idx) if m is not None else None,
            )
        )
    return out


def insert(
    h: jax.Array,
    key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    live: jax.Array,
    capacity: int,
    table_size: int,
    null_equal: bool,
    max_rounds: Optional[int] = None,
):
    """Insert all live rows; equal keys share one slot.

    `max_rounds` bounds the probe loop for UNDERSIZED tables (a table
    smaller than 2*capacity cannot guarantee an empty slot on every
    chain, so insertion of more distinct keys than fit would never
    terminate): when the bound trips, the leftover rows surface in the
    `overflow` flag and the caller re-runs with a full-size table (the
    same ladder that handles group-capacity overflow).

    Returns (slot, rep_tab, dup, overflow):
      slot     i32[capacity]  resolved slot per row (undefined for dead)
      rep_tab  i32[table_size] first (minimum) row index owning each
               slot; `capacity` marks an empty slot
      dup      bool scalar    any live row's key was already present
               (its representative is a different row)
      overflow bool scalar    rows left unresolved by the round bound
    """
    cap = capacity
    mask = jnp.uint32(table_size - 1)
    rowidx = jnp.arange(cap, dtype=jnp.int32)
    empty = jnp.int32(cap)
    slot0 = jnp.asarray(
        h.astype(jnp.uint32) & mask, dtype=jnp.int32
    )

    def keys_match(rep, self_keys):
        reps = jnp.clip(rep, 0, cap - 1)
        rep_keys = _keys_at(key_cols, reps)
        ok = jnp.ones(cap, dtype=jnp.bool_)
        for (bv, bm), (sv, sm) in zip(rep_keys, self_keys):
            ok = ok & _pairwise_eq(sv, sm, bv, bm, null_equal)
        return ok

    self_keys = [(v, m) for v, m in key_cols]

    def cond(state):
        _, _, _, active, _, rounds = state
        more = jnp.any(active)
        if max_rounds is not None:
            more = more & (rounds < max_rounds)
        return more

    def body(state):
        tab, slot, final_slot, active, dup, rounds = state
        occupant = jnp.take(tab, slot)
        # claim only EMPTY slots: occupied slots are immutable, which
        # preserves the linear-probe invariant lookups depend on
        cand = jnp.where(
            active & (occupant == empty), rowidx, empty
        )
        tab = tab.at[slot].min(cand, mode="drop")
        rep = jnp.take(tab, slot)
        found = active & (rep != empty) & keys_match(rep, self_keys)
        dup = dup | jnp.any(found & (rep != rowidx))
        final_slot = jnp.where(found, slot, final_slot)
        active = active & ~found
        nxt = jnp.asarray(
            (slot.astype(jnp.uint32) + jnp.uint32(1)) & mask,
            dtype=jnp.int32,
        )
        slot = jnp.where(active, nxt, slot)
        return tab, slot, final_slot, active, dup, rounds + 1

    tab0 = jnp.full(table_size, empty, dtype=jnp.int32)
    state = (
        tab0,
        slot0,
        jnp.zeros(cap, dtype=jnp.int32),
        live,
        jnp.asarray(False),
        jnp.asarray(0, jnp.int32),
    )
    tab, _, final_slot, active, dup, _ = lax.while_loop(
        cond, body, state
    )
    return final_slot, tab, dup, jnp.any(active)


def group_slots(
    h: jax.Array,
    key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    live: jax.Array,
    capacity: int,
    table_size: int,
    max_rounds: Optional[int] = None,
):
    """Slot assignment for GROUPING (null_equal semantics).

    Single-integer-key inputs get a direct-indexing branch: when the
    live value range fits the table (dictionary codes, `x % N` bucket
    ids, narrow ints - the overwhelmingly common TPC-DS group keys),
    slot = value - min(value) with one reserved slot for NULL, skipping
    the probe loop entirely (one scatter instead of ~2 rounds of
    scatter+gather+compare). The branch decision is data-dependent, so
    both variants compile under one `lax.cond`; out-of-range or
    multi-key inputs take the hash-insert path.

    Returns (slot, rep_tab, overflow)."""
    cap = capacity
    single_int = (
        len(key_cols) == 1
        and key_cols[0][0].ndim == 1
        and jnp.issubdtype(key_cols[0][0].dtype, jnp.integer)
    )
    if not single_int:
        slot, tab, _dup, overflow = insert(
            h, key_cols, live, cap, table_size, True, max_rounds
        )
        return slot, tab, overflow

    v, m = key_cols[0]
    valid = live if m is None else (live & m)
    vv = v.astype(jnp.int64)
    big = jnp.int64(1) << jnp.int64(62)
    kmin = jnp.min(jnp.where(valid, vv, big))
    kmax = jnp.max(jnp.where(valid, vv, -big))
    diff = kmax - kmin
    # reserve one slot for the NULL group when the key is nullable.
    # int64 wrap on an astronomically wide range makes diff negative,
    # which the >= 0 guard rejects (a true range >= 2^63 can never wrap
    # into [0, table_size))
    need = diff + (2 if m is not None else 1)
    in_range = (diff >= 0) & (need <= table_size) & jnp.any(valid)

    def direct(_):
        raw = jnp.clip(vv - kmin, 0, table_size - 1)
        null_slot = jnp.clip(diff + 1, 0, table_size - 1)
        slot = jnp.where(valid, raw, null_slot).astype(jnp.int32)
        cand = jnp.where(
            live, jnp.arange(cap, dtype=jnp.int32), jnp.int32(cap)
        )
        tab = jnp.full(table_size, cap, dtype=jnp.int32)
        tab = tab.at[slot].min(cand, mode="drop")
        return slot, tab, jnp.asarray(False)

    def hashed(_):
        slot, tab, _dup, overflow = insert(
            h, key_cols, live, cap, table_size, True, max_rounds
        )
        return slot, tab, overflow

    return lax.cond(in_range, direct, hashed, operand=None)


def lookup(
    rep_tab: jax.Array,
    h_probe: jax.Array,
    probe_key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    build_key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    probe_live: jax.Array,
    build_capacity: int,
    null_equal: bool = False,
):
    """Find each probe row's matching build row (first inserted row of
    the equal key), walking the probe chain to the first empty slot.

    Returns (match_idx i32[pcap] - build row index, clip-safe garbage
    when unmatched - and matched bool[pcap])."""
    table_size = rep_tab.shape[0]
    mask = jnp.uint32(table_size - 1)
    pcap = h_probe.shape[0]
    empty = jnp.int32(build_capacity)
    slot0 = jnp.asarray(
        h_probe.astype(jnp.uint32) & mask, dtype=jnp.int32
    )

    def keys_match(rep):
        reps = jnp.clip(rep, 0, build_capacity - 1)
        rep_keys = _keys_at(build_key_cols, reps)
        ok = jnp.ones(pcap, dtype=jnp.bool_)
        for (bv, bm), (pv, pm) in zip(rep_keys, probe_key_cols):
            ok = ok & _pairwise_eq(pv, pm, bv, bm, null_equal)
        return ok

    def cond(state):
        _, active, _, _ = state
        return jnp.any(active)

    def body(state):
        slot, active, match, matched = state
        rep = jnp.take(rep_tab, slot)
        is_empty = rep == empty
        hit = active & ~is_empty & keys_match(rep)
        match = jnp.where(hit, rep, match)
        matched = matched | hit
        active = active & ~is_empty & ~hit
        nxt = jnp.asarray(
            (slot.astype(jnp.uint32) + jnp.uint32(1)) & mask,
            dtype=jnp.int32,
        )
        slot = jnp.where(active, nxt, slot)
        return slot, active, match, matched

    state = (
        slot0,
        probe_live,
        jnp.zeros(pcap, dtype=jnp.int32),
        jnp.zeros(pcap, dtype=jnp.bool_),
    )
    _, _, match, matched = lax.while_loop(cond, body, state)
    return match, matched


def dense_group_ids(
    slot: jax.Array,
    rep_tab: jax.Array,
    live: jax.Array,
    capacity: int,
    out_cap: int,
):
    """Compact occupied slots to dense group ids [0, n_groups).

    Returns (row_gid i32[capacity] - dead rows park in out_cap-1,
    n_groups i32 scalar, bpos i32[out_cap] - representative row index
    per group, zero-padded)."""
    occupied = rep_tab != jnp.int32(capacity)
    gid_of_slot = jnp.cumsum(occupied.astype(jnp.int32)) - 1
    row_gid = jnp.where(
        live,
        jnp.take(gid_of_slot, slot),
        jnp.int32(out_cap - 1),
    )
    n_groups = jnp.sum(occupied.astype(jnp.int32))
    occ_slots = jnp.nonzero(
        occupied, size=out_cap, fill_value=0
    )[0]
    bpos = jnp.clip(
        jnp.take(rep_tab, occ_slots), 0, capacity - 1
    )
    return row_gid, n_groups, bpos
