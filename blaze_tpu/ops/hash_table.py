"""Device open-addressing hash table: sort-free grouping and join lookup.

Why: the engine's original grouping/join cores are sort-based (one lexsort
of the full input per aggregate, argsort+searchsorted per join build/probe).
Sorts are O(n log n) with a large constant on XLA:CPU (an 8M-row argsort
measures ~3.5s on one core vs ~0.03s for a scatter over the same rows) and
the sort result is only used to assign group ids / locate matches. This
module replaces that with the classic vectorized open-addressing scheme,
built entirely from scatter/gather primitives that XLA executes in O(n):

  insert:  every live row hashes to a home slot in a power-of-two table;
           rounds of `table.at[slot].min(row_index)` claim empty slots
           (ties resolved by the min), a gather-back + exact key
           comparison resolves rows whose key already owns the slot, and
           unresolved rows advance to the next slot of their triangular
           (quadratic) probe sequence inside one `lax.while_loop`.
           Occupied slots are never overwritten, so the probe-sequence
           invariant (no empty slot EARLIER in a key's triangular
           sequence than its resting slot) holds and lookups may stop
           at the first empty slot they encounter on that sequence.
  lookup:  probe rows walk the same chain, comparing true key values at
           each step - hash collisions cost extra steps, never wrong
           answers.

Equality is exact (not hash equality): NaN matches NaN (Spark normalizes
NaN keys), and NULL handling is caller-chosen: grouping treats NULL as a
key value (SQL GROUP BY: NULL groups with NULL), joins never match NULL.

Reference counterpart: the DataFusion hash-join/hash-aggregate RawTable
paths the reference reuses (from_proto.rs:349-545). The design here is
deliberately not a row-cursor translation: every step is a whole-array
scatter/gather so one XLA program handles the entire batch.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax


def table_size_for(capacity: int) -> int:
    """Power-of-two table with load factor <= 0.5 at full capacity, so
    insertion always terminates (an empty slot exists on every probe
    chain) and expected chains stay O(1)."""
    t = 1024
    while t < 2 * capacity:
        t <<= 1
    return t


def probe_table_size(capacity: int) -> int:
    """Table sizing for JOIN probes: the lookup while_loop runs one
    full-probe-array pass per round until the LONGEST chain resolves,
    so load factor directly multiplies probe cost (measured at 131k
    build keys / 8M probes on XLA:CPU: 1.51s at load 0.5, 0.36s at
    load 0.125). Aim for 8x the build size, capped at 2^23 slots
    (32MB) so giant builds degrade to the guaranteed-terminating 2x.
    Grouping keeps the 2x table: dense_group_ids scans the whole
    table, so oversizing it costs more than the shorter chains save."""
    t = table_size_for(capacity)
    while t < 8 * capacity and t < (1 << 23):
        t <<= 1
    return t


def cheap_hash(
    key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    capacity: int,
) -> jax.Array:
    """Fast intra-engine mixer for PRIVATE table slots (Fibonacci
    multiply + xorshift finalizer, ~3x cheaper than the full murmur3
    pipeline at 8M rows). NOT for shuffle partitioning - row placement
    across executors is a bit-compat contract that must stay
    spark-murmur3 (exprs/hashing.py). Collisions only cost extra probe
    rounds, never wrong answers (exact-key verification)."""
    phi = jnp.uint32(0x9E3779B9)
    acc = jnp.full(capacity, jnp.uint32(0x243F6A88))
    for v, m in key_cols:
        if jnp.issubdtype(v.dtype, jnp.floating):
            # narrow to normalized f32 bits: -0.0 == 0.0 and NaN
            # payloads collapse so equal keys hash equal; f64 pairs
            # distinct only beyond f32 precision merely share a chain
            # (exact comparison still separates them)
            f32 = v.astype(jnp.float32)
            f32 = jnp.where(f32 == 0.0, jnp.float32(0.0), f32)
            f32 = jnp.where(
                jnp.isnan(f32), jnp.float32(jnp.nan), f32
            )
            u = jax.lax.bitcast_convert_type(f32, jnp.uint32)
        elif v.dtype == jnp.bool_:
            u = v.astype(jnp.uint32)
        else:
            # ALL integer widths route through the int64 fold so the
            # hash is a function of the VALUE, not the storage width:
            # an i32 build key then hashes identically to an equal i64
            # probe key and the generic table joins mixed-width keys
            # correctly (equality already promotes)
            b = v.astype(jnp.int64).astype(jnp.uint64)
            u = (b ^ (b >> jnp.uint64(32))).astype(jnp.uint32)
        u = u * phi
        if m is not None:
            u = jnp.where(m, u, jnp.uint32(0x85EBCA6B))
        acc = ((acc << jnp.uint32(5)) | (acc >> jnp.uint32(27))) ^ u
    acc = acc ^ (acc >> jnp.uint32(16))
    acc = acc * jnp.uint32(0x85EBCA6B)
    acc = acc ^ (acc >> jnp.uint32(13))
    return acc.astype(jnp.int32)


def _tri_slot(u0, r, mask):
    """Probe slot r of the triangular (quadratic) sequence
    h, h+1, h+3, h+6, ... (offsets r(r+1)/2). Triangular offsets visit
    every slot of a power-of-two table exactly once per period, so
    termination guarantees carry over from linear probing, but probe
    sequences from clustered home slots diverge immediately - measured
    max chain at 131k keys / 1M slots drops from 8 (linear) to ~4."""
    off = (r * (r + jnp.uint32(1))) >> jnp.uint32(1)
    return jnp.asarray((u0 + off) & mask, dtype=jnp.int32)


def _pairwise_eq(av, am, bv, bm, null_equal: bool):
    """Exact equality of key values gathered from two row sets.

    `av/am` and `bv/bm` are aligned (already gathered) value/validity
    arrays. NaN == NaN; NULL semantics per `null_equal`."""
    eq = av == bv
    if jnp.issubdtype(av.dtype, jnp.floating):
        eq = eq | (jnp.isnan(av) & jnp.isnan(bv))
    if am is None and bm is None:
        return eq
    at = am if am is not None else jnp.ones(av.shape[0], jnp.bool_)
    bt = bm if bm is not None else jnp.ones(bv.shape[0], jnp.bool_)
    if null_equal:
        # (both valid and equal) or (both null)
        return jnp.where(at & bt, eq, at == bt)
    return eq & at & bt


def _keys_at(key_cols, idx):
    """Gather (values, validity) of every key column at row indices."""
    out = []
    for v, m in key_cols:
        out.append(
            (
                jnp.take(v, idx, axis=0),
                jnp.take(m, idx) if m is not None else None,
            )
        )
    return out


def insert(
    h: jax.Array,
    key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    live: jax.Array,
    capacity: int,
    table_size: int,
    null_equal: bool,
    max_rounds: Optional[int] = None,
):
    """Insert all live rows; equal keys share one slot.

    `max_rounds` bounds the probe loop for UNDERSIZED tables (a table
    smaller than 2*capacity cannot guarantee an empty slot on every
    chain, so insertion of more distinct keys than fit would never
    terminate): when the bound trips, the leftover rows surface in the
    `overflow` flag and the caller re-runs with a full-size table (the
    same ladder that handles group-capacity overflow).

    Returns (slot, rep_tab, dup, overflow):
      slot     i32[capacity]  resolved slot per row (undefined for dead)
      rep_tab  i32[table_size] first (minimum) row index owning each
               slot; `capacity` marks an empty slot
      dup      bool scalar    any live row's key was already present
               (its representative is a different row)
      overflow bool scalar    rows left unresolved by the round bound
    """
    cap = capacity
    mask = jnp.uint32(table_size - 1)
    rowidx = jnp.arange(cap, dtype=jnp.int32)
    empty = jnp.int32(cap)
    slot0 = jnp.asarray(
        h.astype(jnp.uint32) & mask, dtype=jnp.int32
    )

    def keys_match(rep, self_keys):
        reps = jnp.clip(rep, 0, cap - 1)
        rep_keys = _keys_at(key_cols, reps)
        ok = jnp.ones(cap, dtype=jnp.bool_)
        for (bv, bm), (sv, sm) in zip(rep_keys, self_keys):
            ok = ok & _pairwise_eq(sv, sm, bv, bm, null_equal)
        return ok

    self_keys = [(v, m) for v, m in key_cols]

    # lean carry: the probing slot is DERIVED from the round counter
    # (triangular probing: slot_r = home + r(r+1)/2); only the resolved
    # slot, activity and the table ride the carry
    u0 = slot0.astype(jnp.uint32)

    def cond(state):
        _, _, active, _, rounds = state
        more = jnp.any(active)
        if max_rounds is not None:
            more = more & (rounds < jnp.uint32(max_rounds))
        return more

    def body(state):
        tab, final_slot, active, dup, rounds = state
        slot = _tri_slot(u0, rounds, mask)
        occupant = jnp.take(tab, slot)
        # claim only EMPTY slots: occupied slots are immutable, which
        # preserves the probe-sequence invariant lookups depend on
        cand = jnp.where(
            active & (occupant == empty), rowidx, empty
        )
        tab = tab.at[slot].min(cand, mode="drop")
        rep = jnp.take(tab, slot)
        found = active & (rep != empty) & keys_match(rep, self_keys)
        dup = dup | jnp.any(found & (rep != rowidx))
        final_slot = jnp.where(found, slot, final_slot)
        active = active & ~found
        return tab, final_slot, active, dup, rounds + jnp.uint32(1)

    tab0 = jnp.full(table_size, empty, dtype=jnp.int32)
    state = (
        tab0,
        jnp.zeros(cap, dtype=jnp.int32),
        live,
        jnp.asarray(False),
        jnp.uint32(0),
    )
    tab, final_slot, active, dup, _ = lax.while_loop(
        cond, body, state
    )
    return final_slot, tab, dup, jnp.any(active)


def key_u32(v: jax.Array, m) -> Optional[jax.Array]:
    """Exact 32-bit encoding of a single narrow join key, or None when
    the dtype doesn't fit. Equality of encodings == SQL equality of
    keys: floats normalize -0.0 to +0.0 and every NaN payload to the
    canonical quiet NaN (Spark joins match NaN with NaN)."""
    if v.ndim != 1:
        return None
    if v.dtype == jnp.float32:
        # f64 is NOT eligible: narrowing would merge keys distinct
        # beyond f32 precision, and unlike hashing this encoding IS the
        # equality check
        f = jnp.where(v == 0.0, jnp.float32(0.0), v)
        bits = jax.lax.bitcast_convert_type(f, jnp.uint32)
        return jnp.where(jnp.isnan(f), jnp.uint32(0x7FC00000), bits)
    if v.dtype == jnp.bool_:
        return v.astype(jnp.uint32)
    if jnp.issubdtype(v.dtype, jnp.integer) and v.dtype.itemsize <= 4:
        return v.astype(jnp.int32).astype(jnp.uint32)
    return None


# numpy scalar, NOT jnp: a concrete jnp array at module level gets
# lifted into every closing jaxpr as a runtime input, which breaks
# re-execution of cached kernels (jit fastpath supplies one fewer
# buffer than the compiled program expects)
_KR_EMPTY = _np.uint64(0xFFFFFFFFFFFFFFFF)


def insert_kr(
    k32: jax.Array,
    h: jax.Array,
    live: jax.Array,
    capacity: int,
    table_size: int,
):
    """Single-narrow-key insert into a fused (key32 << 32 | row) u64
    table: each probe round is ONE gather + compare (no second
    indirection through build-key columns), which matters because the
    while_loop runs for the LONGEST chain and every round is a full
    pass over the input. Returns (tab u64[table_size], dup).

    Caveat: a key whose encoding is 0xFFFFFFFF with row index
    0xFFFFFFFF would alias the EMPTY sentinel; row indices are < 2^31,
    so no live entry can equal EMPTY."""
    cap = capacity
    mask = jnp.uint32(table_size - 1)
    rowidx = jnp.arange(cap, dtype=jnp.uint32)
    entries = (k32.astype(jnp.uint64) << jnp.uint64(32)) | (
        rowidx.astype(jnp.uint64)
    )
    u0 = h.astype(jnp.uint32) & mask

    def cond(state):
        _, active, _, _ = state
        return jnp.any(active)

    def body(state):
        tab, active, dup, r = state
        slot = _tri_slot(u0, r, mask)
        occupant = jnp.take(tab, slot)
        cand = jnp.where(
            active & (occupant == _KR_EMPTY), entries, _KR_EMPTY
        )
        tab = tab.at[slot].min(cand, mode="drop")
        entry = jnp.take(tab, slot)
        same_key = (entry >> jnp.uint64(32)).astype(
            jnp.uint32
        ) == k32
        found = active & (entry != _KR_EMPTY) & same_key
        dup = dup | jnp.any(
            found
            & ((entry & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
               != rowidx)
        )
        active = active & ~found
        return tab, active, dup, r + jnp.uint32(1)

    tab0 = jnp.full(table_size, _KR_EMPTY, dtype=jnp.uint64)
    tab, _, dup, _ = lax.while_loop(
        cond, body, (tab0, live, jnp.asarray(False), jnp.uint32(0))
    )
    return tab, dup


def lookup_kr(
    tab: jax.Array,
    k32: jax.Array,
    h: jax.Array,
    probe_live: jax.Array,
):
    """Probe a fused key-row table: one gather + one compare per round.
    Returns (match_idx i32 - -1-clipped garbage when unmatched - and
    matched bool)."""
    table_size = tab.shape[0]
    mask = jnp.uint32(table_size - 1)
    pcap = k32.shape[0]
    u0 = h.astype(jnp.uint32) & mask

    def round_(r, u0_, k32_, active, match):
        slot = _tri_slot(u0_, r, mask)
        entry = jnp.take(tab, slot)
        is_empty = entry == _KR_EMPTY
        hit = active & ~is_empty & (
            (entry >> jnp.uint64(32)).astype(jnp.uint32) == k32_
        )
        match = jnp.where(
            hit,
            (entry & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32),
            match,
        )
        active = active & ~is_empty & ~hit
        return active, match

    # unrolled head rounds: the vast majority of probes resolve within
    # two steps (hit or empty slot) as straight-line code with no
    # loop-carry traffic
    active = probe_live
    match = jnp.full(pcap, -1, dtype=jnp.int32)
    active, match = round_(jnp.uint32(0), u0, k32, active, match)
    active, match = round_(jnp.uint32(1), u0, k32, active, match)

    # compacted tail: the ~1% of probes still active (clustered or
    # displaced keys) gather into a pcap/16 sub-problem so the
    # remaining rounds touch 16x less memory; if the stragglers ever
    # exceed the buffer (adversarial clustering), fall back to
    # full-width rounds - correctness never depends on the estimate
    tail_cap = max(1024, pcap // 16)
    n_active = jnp.sum(active)

    def full_width(args):
        active_, match_ = args

        def cond(state):
            _, a, _ = state
            return jnp.any(a)

        def body(state):
            r, a, m = state
            a, m = round_(r, u0, k32, a, m)
            return r + jnp.uint32(1), a, m

        _, _, m = lax.while_loop(
            cond, body, (jnp.uint32(2), active_, match_)
        )
        return m

    def compacted(args):
        active_, match_ = args
        idxs = jnp.nonzero(
            active_, size=tail_cap, fill_value=pcap
        )[0]
        safe = jnp.clip(idxs, 0, pcap - 1)
        s_u0 = jnp.take(u0, safe)
        s_k32 = jnp.take(k32, safe)
        s_act = idxs < pcap
        s_match = jnp.full(tail_cap, -1, dtype=jnp.int32)

        def cond(state):
            _, a, _ = state
            return jnp.any(a)

        def body(state):
            r, a, m = state
            a, m = round_(r, s_u0, s_k32, a, m)
            return r + jnp.uint32(1), a, m

        _, _, s_match = lax.while_loop(
            cond, body, (jnp.uint32(2), s_act, s_match)
        )
        return match_.at[idxs].set(s_match, mode="drop")

    match = lax.cond(
        n_active > tail_cap, full_width, compacted, (active, match)
    )
    return match, match >= 0


# int32 row indices are < 2^31, so INT32_MAX can never be a live row
_DIRECT_EMPTY = _np.int32(0x7FFFFFFF)


def insert_direct(
    keys: jax.Array,
    live: jax.Array,
    capacity: int,
    base: jax.Array,
    table_size: int,
):
    """Dense-domain dimension table: tab[key - base] = row index.

    The TPC-DS dimension pattern (Spark's LongHashedRelation takes the
    same dense-array fast path): surrogate keys are near-contiguous
    ints, so the "hash table" degenerates to ONE 4-byte-per-slot array
    that fits in L2 for typical dims (131k keys = 512KB vs the 8MB
    key|row u64 table), and probing is a single gather with no hash,
    no probe rounds, no key comparison - slot identity IS key equality.

    `base`/`table_size`: base is the (dynamic, device-scalar) minimum
    live key; table_size the static power-of-two >= key span, so one
    compiled kernel serves every relation with the same span bucket.
    Returns (tab i32[table_size], dup): dup=True means two live rows
    share a key (the caller demotes to the sorted core, exactly like
    the hash insert's duplicate detection)."""
    cap = capacity
    idx = jnp.clip(
        keys.astype(jnp.int64) - base.astype(jnp.int64),
        0, table_size - 1,
    ).astype(jnp.int32)
    rows = jnp.arange(cap, dtype=jnp.int32)
    tab = jnp.full(table_size, _DIRECT_EMPTY, dtype=jnp.int32)
    tab = tab.at[idx].min(
        jnp.where(live, rows, _DIRECT_EMPTY), mode="drop"
    )
    rep = jnp.take(tab, idx)
    dup = jnp.any(live & (rep != rows))
    return tab, dup


def lookup_direct(
    tab: jax.Array,
    base: jax.Array,
    span: jax.Array,
    keys: jax.Array,
    probe_live: jax.Array,
):
    """Probe a dense-domain table: one subtract + range check + gather.
    Returns (match_idx i32, matched bool) - the lookup_kr contract."""
    table_size = tab.shape[0]
    idx = keys.astype(jnp.int64) - base.astype(jnp.int64)
    in_range = (idx >= 0) & (idx < span.astype(jnp.int64))
    rep = jnp.take(
        tab,
        jnp.clip(idx, 0, table_size - 1).astype(jnp.int32),
    )
    matched = probe_live & in_range & (rep != _DIRECT_EMPTY)
    return jnp.where(matched, rep, jnp.int32(-1)), matched


def direct_table_size(span: int) -> int:
    """Static power-of-two table size for a key span (>= 1024 so span
    jitter across relations reuses one compiled kernel)."""
    t = 1024
    while t < span:
        t <<= 1
    return t


def group_slots(
    key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    live: jax.Array,
    capacity: int,
    table_size: int,
    max_rounds: Optional[int] = None,
):
    """Slot assignment for GROUPING (null_equal semantics).

    Single-integer-key inputs get a direct-indexing branch: when the
    live value range fits the table (dictionary codes, `x % N` bucket
    ids, narrow ints - the overwhelmingly common TPC-DS group keys),
    slot = value - min(value) with one reserved slot for NULL, skipping
    the probe loop entirely (one scatter instead of ~2 rounds of
    scatter+gather+compare). The branch decision is data-dependent, so
    both variants compile under one `lax.cond`; out-of-range or
    multi-key inputs take the hash-insert path.

    Hashing happens lazily inside the hash branch (cheap_hash): the
    direct branch never pays for it.

    Returns (slot, rep_tab, overflow)."""
    cap = capacity
    single_int = (
        len(key_cols) == 1
        and key_cols[0][0].ndim == 1
        and (
            jnp.issubdtype(key_cols[0][0].dtype, jnp.integer)
            # bool keys (2-3 groups incl. NULL) are the direct path's
            # best case; they cast to int32 below
            or key_cols[0][0].dtype == jnp.bool_
        )
    )

    def hash_insert():
        h = cheap_hash(key_cols, cap)
        slot, tab, _dup, overflow = insert(
            h, key_cols, live, cap, table_size, True, max_rounds
        )
        return slot, tab, overflow

    if not single_int:
        return hash_insert()

    v, m = key_cols[0]
    valid = live if m is None else (live & m)
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int32)
    info = jnp.iinfo(v.dtype)
    # scalar min/max reductions stay in the ORIGINAL dtype; only the
    # two scalars widen - converting 8M rows to int64 for arithmetic
    # that (inside the taken branch) provably fits 2^23 costs ~0.1s/8M
    # on one core
    kmin = jnp.min(jnp.where(valid, v, info.max))
    kmax = jnp.max(jnp.where(valid, v, info.min))
    diff = kmax.astype(jnp.int64) - kmin.astype(jnp.int64)
    # reserve one slot for the NULL group when the key is nullable.
    # int64 wrap on an astronomically wide range makes diff negative,
    # which the >= 0 guard rejects (a true range >= 2^63 can never wrap
    # into [0, table_size))
    need = diff + (2 if m is not None else 1)
    in_range = (diff >= 0) & (need <= table_size) & jnp.any(valid)

    def direct(_):
        # per-row subtraction: int32/int64 keys subtract in their own
        # dtype (VALID rows cannot wrap: range < table_size <= 2^23 in
        # the taken branch; invalid rows may wrap but are overridden by
        # null_slot/clip). int8/int16 widen to int32 first - their own
        # range CAN overflow the narrow dtype (e.g. int8 span 254).
        vw = v if v.dtype.itemsize >= 4 else v.astype(jnp.int32)
        raw = jnp.clip(
            (vw - kmin.astype(vw.dtype)).astype(jnp.int32),
            0, table_size - 1,
        )
        null_slot = jnp.clip(diff + 1, 0, table_size - 1).astype(
            jnp.int32
        )
        slot = jnp.where(valid, raw, null_slot)
        cand = jnp.where(
            live, jnp.arange(cap, dtype=jnp.int32), jnp.int32(cap)
        )
        tab = jnp.full(table_size, cap, dtype=jnp.int32)
        tab = tab.at[slot].min(cand, mode="drop")
        return slot, tab, jnp.asarray(False)

    def hashed(_):
        return hash_insert()

    return lax.cond(in_range, direct, hashed, operand=None)


def lookup(
    rep_tab: jax.Array,
    h_probe: jax.Array,
    probe_key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    build_key_cols: Sequence[Tuple[jax.Array, Optional[jax.Array]]],
    probe_live: jax.Array,
    build_capacity: int,
    null_equal: bool = False,
):
    """Find each probe row's matching build row (first inserted row of
    the equal key), walking the probe chain to the first empty slot.

    Returns (match_idx i32[pcap] - build row index, clip-safe garbage
    when unmatched - and matched bool[pcap])."""
    table_size = rep_tab.shape[0]
    mask = jnp.uint32(table_size - 1)
    pcap = h_probe.shape[0]
    empty = jnp.int32(build_capacity)
    slot0 = jnp.asarray(
        h_probe.astype(jnp.uint32) & mask, dtype=jnp.int32
    )

    def keys_match(rep):
        reps = jnp.clip(rep, 0, build_capacity - 1)
        rep_keys = _keys_at(build_key_cols, reps)
        ok = jnp.ones(pcap, dtype=jnp.bool_)
        for (bv, bm), (pv, pm) in zip(rep_keys, probe_key_cols):
            ok = ok & _pairwise_eq(pv, pm, bv, bm, null_equal)
        return ok

    # lean carry: the probe slot is DERIVED from the round counter
    # (triangular probing: slot_r = home + r(r+1)/2), and the matched
    # flag lives in the match sentinel (-1 = no match) - every array
    # dropped from the carry saves a full-probe-array rewrite per round
    u0 = slot0.astype(jnp.uint32)

    def round_(r, active, match):
        slot = _tri_slot(u0, r, mask)
        rep = jnp.take(rep_tab, slot)
        is_empty = rep == empty
        hit = active & ~is_empty & keys_match(rep)
        match = jnp.where(hit, rep, match)
        active = active & ~is_empty & ~hit
        return active, match

    def cond(state):
        _, active, _ = state
        return jnp.any(active)

    def body(state):
        r, active, match = state
        active, match = round_(r, active, match)
        return r + jnp.uint32(1), active, match

    # unroll the first two rounds: they resolve the vast majority of
    # probes as straight-line code with no loop-carry copies
    active = probe_live
    match = jnp.full(pcap, -1, dtype=jnp.int32)
    active, match = round_(jnp.uint32(0), active, match)
    active, match = round_(jnp.uint32(1), active, match)
    _, _, match = lax.while_loop(
        cond, body, (jnp.uint32(2), active, match)
    )
    return match, match >= 0


def dense_group_ids(
    slot: jax.Array,
    rep_tab: jax.Array,
    live: jax.Array,
    capacity: int,
    out_cap: int,
):
    """Compact occupied slots to dense group ids [0, n_groups).

    Returns (row_gid i32[capacity] - dead rows park in out_cap-1,
    n_groups i32 scalar, bpos i32[out_cap] - representative row index
    per group, zero-padded).

    The production scatter core no longer calls this: hash_aggregate
    reduces on RAW slots and compacts only the (out_cap,)-sized states
    (inlining the occupied/nonzero/bpos math here, minus the full-row
    gid gather). This remains the reference formulation and the
    bench's tpu_core_probe measurement target."""
    occupied = rep_tab != jnp.int32(capacity)
    gid_of_slot = jnp.cumsum(occupied.astype(jnp.int32)) - 1
    row_gid = jnp.where(
        live,
        jnp.take(gid_of_slot, slot),
        jnp.int32(out_cap - 1),
    )
    n_groups = jnp.sum(occupied.astype(jnp.int32))
    occ_slots = jnp.nonzero(
        occupied, size=out_cap, fill_value=0
    )[0]
    bpos = jnp.clip(
        jnp.take(rep_tab, occ_slots), 0, capacity - 1
    )
    return row_gid, n_groups, bpos
