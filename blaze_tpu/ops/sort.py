"""Sort: pipeline-breaking multi-key sort.

Reference counterpart: DataFusion SortExec, partition-preserving
(from_proto.rs:306-348; wrapper NativeSortExec.scala). TPU design: collect
the partition into one padded device buffer, one XLA sort pass per key
(iterated stable lexsort, ops/util.sort_indices), then re-slice into
bucket-sized batches. String keys become comparable by sorting against a
lexicographically-ordered unified dictionary (host) and remapping codes, so
the device compares int32 codes only.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional

import numpy as np

import jax.numpy as jnp

from blaze_tpu.types import Schema
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.util import (
    concat_batches,
    slice_to_batches,
    sort_indices,
    take_batch,
)


@dataclasses.dataclass(frozen=True)
class SortKey:
    expr: ir.Expr
    ascending: bool = True
    nulls_first: bool = True


class SortExec(PhysicalOp):
    def __init__(self, child: PhysicalOp, keys: List[SortKey],
                 fetch: Optional[int] = None):
        self.children = [child]
        self.keys = [
            SortKey(bind_opt(k.expr, child.schema), k.ascending,
                    k.nulls_first)
            for k in keys
        ]
        self.fetch = fetch

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        batches = list(self.children[0].execute(partition, ctx))
        cb = concat_batches(batches, schema=self.schema)
        if cb.num_rows == 0:
            return iter(())
        cb = sort_batch(cb, self.keys)
        if self.fetch is not None and cb.num_rows > self.fetch:
            cb = ColumnBatch(
                cb.schema, cb.columns, self.fetch, cb.selection
            )
        return iter(slice_to_batches(cb, ctx.config.batch_size))


def sort_batch(cb: ColumnBatch, keys: List[SortKey]) -> ColumnBatch:
    """Sort one compacted batch by the given keys."""
    key_cols = []
    for k in keys:
        col = _key_column(cb, k.expr)
        values = col.values
        if col.dtype.is_dictionary_encoded and col.dictionary is not None:
            values = _lexicographic_codes(col)
        key_cols.append((values, col.validity, k.ascending, k.nulls_first))
    idx = sort_indices(key_cols, cb.num_rows, cb.capacity)
    return take_batch(cb, idx, cb.num_rows)


def _key_column(cb: ColumnBatch, e: ir.Expr) -> Column:
    if isinstance(e, ir.BoundCol):
        return cb.columns[e.index]
    if isinstance(e, ir.Col):
        return cb.column(e.name)
    # general expression keys: evaluate through the device evaluator
    from blaze_tpu.exprs.eval import DeviceEvaluator
    from blaze_tpu.exprs.typing import infer_dtype

    ev = DeviceEvaluator(
        cb.schema, [(c.values, c.validity) for c in cb.columns], cb.capacity
    )
    v, m = ev.evaluate(e)
    return Column(infer_dtype(e, cb.schema), v, m, None)


def _lexicographic_codes(col: Column) -> jnp.ndarray:
    """Remap dictionary codes to ranks in lexicographic dictionary order so
    integer comparison == string comparison."""
    import pyarrow.compute as pc

    order = np.asarray(pc.sort_indices(col.dictionary))
    rank = np.empty(len(order), dtype=np.int32)
    rank[order] = np.arange(len(order), dtype=np.int32)
    return jnp.take(
        jnp.asarray(rank),
        jnp.clip(col.values, 0, len(rank) - 1),
        axis=0,
    )
