"""Sort: pipeline-breaking multi-key sort.

Reference counterpart: DataFusion SortExec, partition-preserving
(from_proto.rs:306-348; wrapper NativeSortExec.scala). TPU design: collect
the partition into one padded device buffer, one XLA sort pass per key
(iterated stable lexsort, ops/util.sort_indices), then re-slice into
bucket-sized batches. String keys become comparable by sorting against a
lexicographically-ordered unified dictionary (host) and remapping codes, so
the device compares int32 codes only.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional

import numpy as np

import jax.numpy as jnp

from blaze_tpu.types import Schema
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.util import (
    compact,
    concat_batches,
    slice_to_batches,
    sort_indices,
    take_batch,
)


@dataclasses.dataclass(frozen=True)
class SortKey:
    expr: ir.Expr
    ascending: bool = True
    nulls_first: bool = True


class SortExec(PhysicalOp):
    def __init__(self, child: PhysicalOp, keys: List[SortKey],
                 fetch: Optional[int] = None):
        self.children = [child]
        self.keys = [
            SortKey(bind_opt(k.expr, child.schema), k.ascending,
                    k.nulls_first)
            for k in keys
        ]
        self.fetch = fetch

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return f"{self.keys!r};fetch={self.fetch}"

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.ops.external import collect_until

        it = self.children[0].execute(partition, ctx)
        limit = ctx.config.max_materialize_rows
        batches, exceeded = collect_until(it, limit)
        if exceeded:
            # top-k stays bounded: sort+trim incrementally on device
            if self.fetch is not None and self.fetch <= limit // 2:
                return self._execute_topk(batches, it, ctx)
            if all(
                isinstance(k.expr, ir.BoundCol)
                and not k.expr.dtype.is_string_like
                for k in self.keys
            ):
                return self._execute_run_merge(batches, it, ctx)
            # string keys: dictionary codes are not comparable across
            # spilled runs - host sort handles those
            return self._execute_host_sort(batches, it, ctx)
        cb = concat_batches(batches, schema=self.schema)
        if cb.num_rows == 0:
            return iter(())
        cb = sort_batch(cb, self.keys)
        if self.fetch is not None and cb.num_rows > self.fetch:
            cb = ColumnBatch(
                cb.schema, cb.columns, self.fetch, cb.selection
            )
        return iter(slice_to_batches(cb, ctx.config.batch_size))

    def _execute_topk(self, head, rest, ctx) -> Iterator[ColumnBatch]:
        """Oversized sort WITH a small fetch: keep only the running top-k
        (sort + trim per chunk), memory stays O(fetch + chunk)."""
        from blaze_tpu.ops.external import collect_until

        limit = ctx.config.max_materialize_rows
        acc = None
        chunk = head
        it = rest
        while chunk:
            pool = ([acc] if acc is not None else []) + list(chunk)
            cb = concat_batches(pool, schema=self.schema)
            cb = sort_batch(cb, self.keys)
            n = min(self.fetch, cb.num_rows)
            acc = ColumnBatch(cb.schema, cb.columns, n, None)
            chunk, _ = collect_until(it, limit)
        if acc is None:
            return
        yield from slice_to_batches(acc, ctx.config.batch_size)

    def _execute_run_merge(self, head, rest, ctx) -> Iterator[ColumnBatch]:
        """External sort: device-sort each chunk into a spilled run
        (segmented IPC), then batch-wise k-way merge. Memory stays
        O(runs x batch) - the reference leans on DataFusion's external
        sort for the same job (SURVEY 5.7)."""
        import os
        import tempfile

        from blaze_tpu.io.ipc import (
            encode_ipc_segment,
            read_file_segment,
        )
        from blaze_tpu.ops.external import collect_until

        limit = ctx.config.max_materialize_rows
        fd, spill = tempfile.mkstemp(
            prefix="blz-sortrun-", dir=ctx.config.spill_dir()
        )
        os.close(fd)
        runs: List[tuple] = []  # (offset, length)
        chunk = head
        with open(spill, "wb") as f:
            pos = 0
            while chunk:
                cb = concat_batches(list(chunk), schema=self.schema)
                cb = sort_batch(cb, self.keys)
                start = pos
                for piece in slice_to_batches(
                    cb, ctx.config.batch_size
                ):
                    part = encode_ipc_segment(
                        piece.to_arrow(),
                        ctx.config.ipc_compression_level,
                    )
                    f.write(part)
                    pos += len(part)
                runs.append((start, pos - start))
                chunk, _ = collect_until(rest, limit)
        ctx.metrics.add("sort_spilled_runs", len(runs))

        key_idx = [k.expr.index for k in self.keys]

        def _component(col, k, rows) -> List[tuple]:
            """(null_rank, +-value) per requested row; native Python
            numbers (ints keep full precision - no float64 round trip).
            Wide-decimal (cap, 2) [lo, hi] limb pairs reassemble into
            exact 128-bit Python ints, matching the device sort's
            hi-major/unsigned-lo order."""
            arr = np.asarray(col.values)
            is_float = np.issubdtype(arr.dtype, np.floating)
            wide = arr.ndim == 2
            vals = arr[rows].tolist()
            if col.validity is not None:
                valid = np.asarray(col.validity)[rows].tolist()
            else:
                valid = [True] * len(vals)
            out = []
            for v, ok in zip(vals, valid):
                if not ok:
                    out.append((0 if k.nulls_first else 2, 0))
                    continue
                if wide:
                    lo, hi = v
                    v = (hi << 64) | (lo & 0xFFFFFFFFFFFFFFFF)
                elif is_float and v != v:  # NaN greatest
                    v = float("inf")
                out.append((1, v if k.ascending else -v))
            return out

        def row_ranks(cb: ColumnBatch) -> List[tuple]:
            """Comparable rank tuple per live row, consistent with the
            device sort order (null placement, direction, NaN-greatest)."""
            rows = np.arange(cb.num_rows)
            per_key = [
                _component(cb.columns[i], k, rows)
                for k, i in zip(self.keys, key_idx)
            ]
            return [
                tuple(x for pair in row for x in pair)
                for row in zip(*per_key)
            ]

        def last_rank(cb: ColumnBatch) -> tuple:
            rows = np.array([cb.num_rows - 1])
            per_key = [
                _component(cb.columns[i], k, rows)[0]
                for k, i in zip(self.keys, key_idx)
            ]
            return tuple(x for pair in per_key for x in pair)

        iters = [
            (ColumnBatch.from_arrow(rb) for rb in
             read_file_segment(spill, off, length))
            for off, length in runs
        ]
        heads: List[Optional[ColumnBatch]] = [next(i, None) for i in iters]
        leftover: Optional[ColumnBatch] = None
        emitted = 0
        try:
            while True:
                live = [h for h in heads if h is not None]
                if not live and leftover is None:
                    break
                exhausted = all(h is None for h in heads)
                pool = concat_batches(
                    ([leftover] if leftover is not None else []) + live,
                    schema=self.schema,
                )
                leftover = None
                pool = sort_batch(pool, self.keys)
                if exhausted:
                    for piece in slice_to_batches(
                        pool, ctx.config.batch_size
                    ):
                        emitted += piece.num_rows
                        yield piece
                    break
                bt = min(last_rank(h) for h in live)
                ranks = row_ranks(pool)
                n_safe = 0
                for r in ranks:
                    if tuple(r) <= bt:
                        n_safe += 1
                    else:
                        break
                safe = ColumnBatch(
                    pool.schema, pool.columns, n_safe, None
                )
                for piece in slice_to_batches(
                    safe, ctx.config.batch_size
                ):
                    emitted += piece.num_rows
                    yield piece
                if n_safe < pool.num_rows:
                    leftover = compact(
                        pool,
                        jnp.arange(pool.capacity, dtype=jnp.int32)
                        >= n_safe,
                    )
                # every live head was absorbed into the pool (its unsafe
                # tail lives in `leftover` now) - advance all of them
                for ri, h in enumerate(heads):
                    if h is not None:
                        heads[ri] = next(iters[ri], None)
        finally:
            try:
                os.remove(spill)
            except OSError:
                pass

    def _execute_host_sort(self, head, rest, ctx) -> Iterator[ColumnBatch]:
        """Oversized full sort: spill to host RAM and sort with pyarrow
        (host RAM outsizes the device-materialization cap; sorting beyond
        host RAM would need run-merge spilling - future work, the
        reference leans on DataFusion's external sort the same way)."""
        import pyarrow as pa

        ctx.metrics.add("host_sorts", 1)
        tables = [b.to_arrow() for b in head] + [
            b.to_arrow() for b in rest
        ]
        tbl = pa.Table.from_batches(tables)
        keys = []
        for k in self.keys:
            assert isinstance(k.expr, ir.BoundCol), (
                "host sort fallback needs plain column keys"
            )
            name = self.schema.fields[k.expr.index].name
            keys.append((name, "ascending" if k.ascending else
                         "descending"))
        tbl = tbl.sort_by(keys)
        if self.fetch is not None:
            tbl = tbl.slice(0, self.fetch)
        bs = ctx.config.batch_size
        for rb in tbl.to_batches(max_chunksize=bs):
            if rb.num_rows:
                yield ColumnBatch.from_arrow(rb)


def sort_batch(cb: ColumnBatch, keys: List[SortKey]) -> ColumnBatch:
    """Sort one compacted batch by the given keys."""
    key_cols = []
    for k in keys:
        col = _key_column(cb, k.expr)
        values = col.values
        if col.dtype.is_wide_decimal:
            # (cap, 2) [lo, hi] limb pairs become TWO adjacent sort
            # lanes - high limb signed, low limb remapped to unsigned
            # order (top-bit flip) - and the radix-style lexsort's
            # minor-to-major passes make them one 128-bit key
            lo = values[:, 0]
            hi = values[:, 1]
            lo_sortable = jnp.bitwise_xor(
                lo, jnp.int64(np.int64(-(2 ** 63)))
            )
            key_cols.append(
                (hi, col.validity, k.ascending, k.nulls_first)
            )
            key_cols.append(
                (lo_sortable, col.validity, k.ascending, k.nulls_first)
            )
            continue
        if col.dtype.is_dictionary_encoded and col.dictionary is not None:
            values = _lexicographic_codes(col)
        key_cols.append((values, col.validity, k.ascending, k.nulls_first))
    idx = sort_indices(key_cols, cb.num_rows, cb.capacity)
    return take_batch(cb, idx, cb.num_rows)


def _key_column(cb: ColumnBatch, e: ir.Expr) -> Column:
    if isinstance(e, ir.BoundCol):
        return cb.columns[e.index]
    if isinstance(e, ir.Col):
        return cb.column(e.name)
    # general expression keys: evaluate through the device evaluator
    from blaze_tpu.exprs.eval import DeviceEvaluator
    from blaze_tpu.exprs.typing import infer_dtype

    ev = DeviceEvaluator(
        cb.schema, [(c.values, c.validity) for c in cb.columns], cb.capacity
    )
    v, m = ev.evaluate(e)
    return Column(infer_dtype(e, cb.schema), v, m, None)


def _lexicographic_codes(col: Column) -> jnp.ndarray:
    """Remap dictionary codes to ranks in lexicographic dictionary order so
    integer comparison == string comparison."""
    import pyarrow.compute as pc

    order = np.asarray(pc.sort_indices(col.dictionary))
    rank = np.empty(len(order), dtype=np.int32)
    rank[order] = np.arange(len(order), dtype=np.int32)
    return jnp.take(
        jnp.asarray(rank),
        jnp.clip(col.values, 0, len(rank) - 1),
        axis=0,
    )
