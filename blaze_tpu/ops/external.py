"""External (grace) execution: hash-bucket oversized inputs through the
engine's own shuffle format, then process bucket-by-bucket.

The reference handles oversized state with the DataFusion MemoryConsumer
spill ladder (shuffle_writer_exec.rs:570-623) and streaming operators; our
sort-based aggregate and vectorized join instead materialize a partition,
which caps input size at device-buffer capacity. This module restores
unbounded inputs the TPU-first way (SURVEY 7 "spill & memory ladder"):

    too-big stream -> murmur3 hash-bucket on the op's keys ->
    segmented-IPC bucket file (same writer/format as the shuffle tier) ->
    per-bucket processing (each bucket now fits)

Because bucketing uses the same key hash on both join sides, equal keys
co-locate and every join type remains correct bucket-wise; for aggregation
every group lands wholly in one bucket.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.io.ipc import (
    encode_ipc_segment,
    partition_ranges,
    read_file_segment,
)
from blaze_tpu.ops.base import ExecContext
from blaze_tpu.ops.shuffle_writer import (
    PartitionBuffers,
    spark_partition_ids,
)
from blaze_tpu.ops.util import ensure_compacted, take_batch


class BucketedInput:
    """A stream hash-bucketed into an on-disk .data/.index pair."""

    def __init__(self, data_path: str, index_path: str, schema: Schema,
                 n_buckets: int):
        self.data_path = data_path
        self.index_path = index_path
        self.schema = schema
        self.n_buckets = n_buckets

    def bucket(self, i: int) -> Iterator[ColumnBatch]:
        off, length = partition_ranges(self.index_path)[i]
        if length == 0:
            return
        for rb in read_file_segment(self.data_path, off, length):
            yield ColumnBatch.from_arrow(rb)

    def cleanup(self) -> None:
        for p in (self.data_path, self.index_path):
            try:
                os.remove(p)
            except OSError:
                pass


def subdivide_pid_fn(key_exprs: Sequence[ir.Expr], parent_modulus: int,
                     fanout: int = 4) -> Callable:
    """pid function splitting one parent hash bucket into `fanout`
    children using the NEXT hash bits: rows of a parent bucket share
    h % parent_modulus, so pmod(h, parent_modulus * fanout) //
    parent_modulus spreads them over 0..fanout-1. Grace recursion uses
    this so each level allocates `fanout` buckets, not parent * fanout
    (of which all but `fanout` would stay empty)."""

    def pid(cb: ColumnBatch) -> np.ndarray:
        wide = spark_partition_ids(
            cb, list(key_exprs), parent_modulus * fanout
        )
        return (wide // parent_modulus).astype(np.int32)

    return pid


def bucket_stream(
    batches: Iterator[ColumnBatch],
    key_exprs: Sequence[ir.Expr],
    n_buckets: int,
    ctx: ExecContext,
    schema: Schema,
    head: Sequence[ColumnBatch] = (),
    pid_fn: Optional[Callable] = None,
) -> BucketedInput:
    """Write (head + remaining stream) into n_buckets hash buckets using
    the shuffle writer's scatter + segmented-IPC machinery. `pid_fn`
    overrides the partition-id computation (grace recursion)."""
    d = ctx.config.spill_dir()
    fd, data_path = tempfile.mkstemp(prefix="blz-ext-", suffix=".data",
                                     dir=d)
    os.close(fd)
    index_path = data_path[:-5] + ".index"
    bufs = PartitionBuffers(n_buckets, d)

    def feed(cb: ColumnBatch) -> None:
        cb = ensure_compacted(cb)
        if cb.num_rows == 0:
            return
        pids = (
            pid_fn(cb) if pid_fn is not None
            else spark_partition_ids(cb, list(key_exprs), n_buckets)
        )
        pid_full = jnp.full(cb.capacity, n_buckets, dtype=jnp.int32)
        pid_full = pid_full.at[: len(pids)].set(jnp.asarray(pids))
        order = jnp.argsort(pid_full, stable=True)
        rb_sorted = take_batch(cb, order, cb.num_rows).to_arrow()
        sorted_pids = np.sort(pids, kind="stable")
        counts = np.bincount(sorted_pids, minlength=n_buckets)
        start = 0
        for p in range(n_buckets):
            c = int(counts[p])
            if c:
                bufs.append(
                    p,
                    encode_ipc_segment(
                        rb_sorted.slice(start, c),
                        ctx.config.ipc_compression_level,
                    ),
                )
                start += c

    for cb in head:
        feed(cb)
    for cb in batches:
        feed(cb)
    bufs.finalize(data_path, index_path)
    return BucketedInput(data_path, index_path, schema, n_buckets)


def collect_until(
    it: Iterator[ColumnBatch], row_limit: int
) -> tuple[List[ColumnBatch], bool]:
    """Pull batches until the stream ends or row_limit is crossed.
    Returns (collected, exceeded)."""
    out: List[ColumnBatch] = []
    total = 0
    for cb in it:
        out.append(cb)
        total += cb.num_rows
        if total > row_limit:
            return out, True
    return out, False
