"""Zero-copy positional column rename (reference RenameColumnsExec,
rename_columns_exec.rs:38-75 - used to reconcile Spark attribute names like
`col#123` across plan fragments)."""

from __future__ import annotations

from typing import Iterator, List

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import ExecContext, PhysicalOp


class RenameColumnsExec(PhysicalOp):
    def __init__(self, child: PhysicalOp, names: List[str]):
        self.children = [child]
        self.names = list(names)
        self._schema = child.schema.rename(self.names)

    @property
    def schema(self) -> Schema:
        return self._schema

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return ";".join(self.names)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        for b in self.children[0].execute(partition, ctx):
            yield ColumnBatch(
                self._schema, b.columns, b.num_rows, b.selection
            )
