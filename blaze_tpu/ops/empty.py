"""N empty partitions of a schema (reference EmptyPartitionsExec,
empty_partitions_exec.rs:37-50)."""

from __future__ import annotations

from typing import Iterator

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import ExecContext, PhysicalOp


class EmptyPartitionsExec(PhysicalOp):
    def __init__(self, schema: Schema, num_partitions: int):
        self.children = []
        self._schema = schema
        self._n = num_partitions

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return self._n

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        return iter(())
