"""Parquet scan: host decode -> device columns.

Reference counterpart: DataFusion ParquetExec with pruning predicate,
driven by per-partition FileGroups (from_proto.rs:202-212; Spark side
NativeParquetScanExec.scala:61-107 builds the groups/projection/filters).

TPU-first shape (SURVEY 7 step 4): Parquet decode is host-tier work
(pyarrow's C++ reader), producing record batches of `batch_size` rows that
are dictionary-encoded/padded/transferred once each. Row-group pruning
evaluates the pruning predicate against row-group statistics before any IO,
like the reference's pruning predicate; byte ranges in a FileRange select
row groups the way Spark's splits do."""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from blaze_tpu.types import Schema, from_arrow_schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.testing import chaos


@dataclasses.dataclass(frozen=True)
class FileRange:
    path: str
    start: int = 0
    length: int = 0  # 0 = whole file


class ParquetScanExec(PhysicalOp):
    def __init__(
        self,
        file_groups: Sequence[Sequence[FileRange]],
        schema: Optional[Schema] = None,
        projection: Optional[Sequence[str]] = None,
        pruning_predicate: Optional[ir.Expr] = None,
    ):
        import pyarrow.parquet as pq

        self.children = []
        self.file_groups = [list(g) for g in file_groups]
        self.projection = list(projection) if projection else None
        self.pruning_predicate = pruning_predicate
        if schema is None:
            from blaze_tpu.io.object_store import store_for

            first = self.file_groups[0][0].path
            aschema = pq.read_schema(store_for(first).open_input(first))
            if self.projection:
                aschema = __import__("pyarrow").schema(
                    [aschema.field(n) for n in self.projection]
                )
            schema = from_arrow_schema(aschema)
        elif self.projection and list(schema.names()) != self.projection:
            # index-bound pruning-predicate columns were bound against
            # the FULL file schema; rewrite them to name references
            # before the schema narrows so stats pruning keeps reading
            # the right row-group columns
            if pruning_predicate is not None:
                full = schema
                pruning_predicate = ir.transform(
                    pruning_predicate,
                    lambda e: ir.Col(full.fields[e.index].name)
                    if isinstance(e, ir.BoundCol)
                    else e,
                )
                self.pruning_predicate = pruning_predicate
            # a producer following the reference's NativeParquetScanExec
            # contract sends the FULL file schema plus a projection of
            # field indices (NativeParquetScanExec.scala:105-107); the
            # operator's schema is the PROJECTED one - normalizing here
            # keeps every downstream consumer (output schema, pruned-
            # batch assembly) positionally consistent
            schema = Schema(
                [
                    schema.fields[schema.index_of(n)]
                    for n in self.projection
                ]
            )
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return len(self.file_groups)

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        # content identity = file ranges + projection + pruning
        # predicate. File CONTENT changes under the same path are not
        # captured - the serving tier's result cache covers that with
        # TTL + explicit invalidation (docs/SERVICE.md)
        groups = "|".join(
            ",".join(f"{fr.path}:{fr.start}:{fr.length}" for fr in g)
            for g in self.file_groups
        )
        proj = ",".join(self.projection) if self.projection else "*"
        return f"{groups};proj={proj};prune={self.pruning_predicate!r}"

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        import pyarrow.parquet as pq

        from blaze_tpu.io.object_store import store_for
        from blaze_tpu.runtime.prefetch import prefetch

        cfg = ctx.config
        cols = self.projection or [f.name for f in self._schema]

        # planner/colprune hints: columns no ancestor reads are neither
        # decoded nor transferred (device zero placeholders keep schema
        # positions valid); filter conjuncts pushed from the FilterExec
        # directly above run on the host during decode, like DataFusion's
        # CPU-side row-filter pushdown in ParquetExec (from_proto.rs:
        # 202-212 builds the same pruning predicate)
        required = getattr(self, "_hint_required", None)
        filters = list(getattr(self, "_hint_filters", ()) or ())
        if required is not None:
            req_names = {cols[i] for i in required if i < len(cols)}
            filt_names = {name for name, _, _ in filters}
            read_names = [
                c for c in cols if c in req_names or c in filt_names
            ]
            if not read_names:
                # COUNT(*)-style scans still need row counts: read the
                # cheapest column (strings cost parquet decode +
                # dictionary encoding regardless of code width)
                def decode_cost(c):
                    dt = self._schema.fields[
                        self._schema.index_of(c)
                    ].dtype
                    penalty = 100 if dt.is_dictionary_encoded else 0
                    return penalty + dt.physical_dtype().itemsize

                read_names = [min(cols, key=decode_cost)]
            keep_names = [c for c in cols if c in req_names] or read_names[:1]
            present = [cols.index(c) for c in keep_names]
            if keep_names == cols and read_names == cols:
                present = None
        else:
            read_names = cols
            keep_names = cols
            present = None

        def decode() -> Iterator[ColumnBatch]:
            from blaze_tpu.obs import trace as obs_trace

            for fr in self.file_groups[partition]:
                # obs seam: one span per file-range decode (open,
                # row-group selection, and the batch iteration - the
                # inclusive decode wall time for this range)
                # rec= explicitly: decode() is drained by a prefetch
                # worker thread, which has no thread-current recorder
                span_cm = (
                    obs_trace.span(
                        "parquet_decode", rec=ctx.tracer,
                        partition=partition, path=fr.path,
                    )
                    if obs_trace.ACTIVE else obs_trace.NULL
                )
                with span_cm:
                    if chaos.ACTIVE:
                        # chaos seam: parquet decode / object-store
                        # read failure for this file range (inside
                        # the span, so the injected fault lands as a
                        # chaos.fault event on THIS span)
                        chaos.fire(
                            "parquet.decode", partition=partition,
                            path=fr.path,
                        )
                    # all byte IO flows through the object-store seam
                    # (the reference's registered ObjectStore,
                    # exec.rs:96-103)
                    pf = pq.ParquetFile(
                        store_for(fr.path).open_input(fr.path)
                    )
                    groups = self._select_row_groups(pf, fr, filters)
                    if not groups:
                        continue
                    for rb in pf.iter_batches(
                        batch_size=cfg.batch_size, row_groups=groups,
                        columns=read_names, use_threads=True,
                    ):
                        ctx.metrics.add("input_rows", rb.num_rows)
                        ctx.metrics.add("input_batches", 1)
                        if filters and cfg.host_filter_pushdown:
                            before = rb.num_rows
                            rb = _apply_host_filters(rb, filters)
                            ctx.metrics.add(
                                "pushdown_filtered_rows",
                                before - rb.num_rows,
                            )
                        if rb.num_rows == 0:
                            continue
                        if present is None:
                            yield ColumnBatch.from_arrow(rb)
                        else:
                            import pyarrow as pa

                            sub = pa.record_batch(
                                [rb.column(c) for c in keep_names],
                                names=keep_names,
                            )
                            yield ColumnBatch.from_arrow_pruned(
                                sub, self._schema, present
                            )

        # overlap parquet decode + H2D with downstream device compute
        # (SURVEY 7 streaming model: double-buffered host pipeline)
        yield from prefetch(decode(), depth=2)

    # ------------------------------------------------------------------
    def _select_row_groups(self, pf, fr: FileRange,
                           filters=()) -> List[int]:
        """Row groups whose byte midpoint falls in the split range (Spark's
        split ownership rule) and that survive stats pruning (the explicit
        pruning predicate plus any pushed-down filter conjuncts)."""
        md = pf.metadata
        out = []
        for i in range(md.num_row_groups):
            rg = md.row_group(i)
            if fr.length > 0:
                start = rg.column(0).file_offset
                mid = start + rg.total_byte_size // 2
                if not (fr.start <= mid < fr.start + fr.length):
                    continue
            if self.pruning_predicate is not None and not _may_match(
                self.pruning_predicate, rg, self._schema
            ):
                continue
            if any(
                not _stats_may_match(name, op, value, rg)
                for name, op, value in filters
            ):
                continue
            out.append(i)
        return out


def _apply_host_filters(rb, filters):
    """Evaluate pushed-down `(name, cmp, literal)` conjuncts with pyarrow
    compute (vectorized C++) and compact the batch before any padding or
    device transfer. NULL comparison results drop the row - exactly what
    the device selection mask would do - and the device FilterExec still
    re-applies the full predicate, so a conjunct that fails to evaluate
    here is simply skipped."""
    import pyarrow.compute as pc

    fns = {
        ir.Op.LT: pc.less, ir.Op.LTE: pc.less_equal,
        ir.Op.GT: pc.greater, ir.Op.GTE: pc.greater_equal,
        ir.Op.EQ: pc.equal, ir.Op.NEQ: pc.not_equal,
    }
    mask = None
    for name, op, value in filters:
        try:
            m = fns[op](rb.column(name), value)
        except Exception:
            continue  # device filter re-checks; skipping is only slower
        mask = m if mask is None else pc.and_(mask, m)
    if mask is None:
        return rb
    return rb.filter(mask)


def _rg_stats(name: str, rg):
    for ci in range(rg.num_columns):
        c = rg.column(ci)
        if c.path_in_schema == name:
            return c.statistics
    return None


def _minmax_may_match(stats, op: ir.Op, value) -> bool:
    """min/max-vs-comparison core shared by the pruning-predicate and
    pushed-conjunct row-group checks: False only when the whole group
    provably fails the comparison."""
    if stats is None or not stats.has_min_max:
        return True
    lo, hi = stats.min, stats.max
    try:
        if op is ir.Op.EQ:
            return lo <= value <= hi
        if op is ir.Op.LT:
            return lo < value
        if op is ir.Op.LTE:
            return lo <= value
        if op is ir.Op.GT:
            return hi > value
        if op is ir.Op.GTE:
            return hi >= value
    except TypeError:
        return True
    return True


def _stats_may_match(name: str, op: ir.Op, value, rg) -> bool:
    return _minmax_may_match(_rg_stats(name, rg), op, value)


def _may_match(pred: ir.Expr, rg, schema: Schema) -> bool:
    """Conservative stats-based pruning: False only when the predicate
    provably rejects the whole row group. Handles comparisons between a
    column and a literal plus AND/OR composition (the reference gets the
    equivalent from DataFusion's PruningPredicate)."""
    from blaze_tpu.exprs.ir import BinaryOp, Col, BoundCol, Literal, Op

    if isinstance(pred, BinaryOp) and pred.op in (Op.AND, Op.OR):
        l = _may_match(pred.left, rg, schema)
        r = _may_match(pred.right, rg, schema)
        return (l and r) if pred.op is Op.AND else (l or r)
    if not isinstance(pred, BinaryOp):
        return True
    col, lit, op = None, None, pred.op
    flip = {Op.LT: Op.GT, Op.GT: Op.LT, Op.LTE: Op.GTE, Op.GTE: Op.LTE}
    if isinstance(pred.left, (Col, BoundCol)) and isinstance(
        pred.right, Literal
    ):
        col, lit = pred.left, pred.right
    elif isinstance(pred.right, (Col, BoundCol)) and isinstance(
        pred.left, Literal
    ):
        col, lit = pred.right, pred.left
        op = flip.get(op, op)
    if col is None or lit.value is None:
        return True
    name = col.name if isinstance(col, Col) else schema.fields[col.index].name
    return _minmax_may_match(_rg_stats(name, rg), op, lit.value)
