"""Parquet scan: host decode -> device columns.

Reference counterpart: DataFusion ParquetExec with pruning predicate,
driven by per-partition FileGroups (from_proto.rs:202-212; Spark side
NativeParquetScanExec.scala:61-107 builds the groups/projection/filters).

TPU-first shape (SURVEY 7 step 4): Parquet decode is host-tier work
(pyarrow's C++ reader), producing record batches of `batch_size` rows that
are dictionary-encoded/padded/transferred once each. Row-group pruning
evaluates the pruning predicate against row-group statistics before any IO,
like the reference's pruning predicate; byte ranges in a FileRange select
row groups the way Spark's splits do."""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from blaze_tpu.types import Schema, from_arrow_schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.ops.base import ExecContext, PhysicalOp


@dataclasses.dataclass(frozen=True)
class FileRange:
    path: str
    start: int = 0
    length: int = 0  # 0 = whole file


class ParquetScanExec(PhysicalOp):
    def __init__(
        self,
        file_groups: Sequence[Sequence[FileRange]],
        schema: Optional[Schema] = None,
        projection: Optional[Sequence[str]] = None,
        pruning_predicate: Optional[ir.Expr] = None,
    ):
        import pyarrow.parquet as pq

        self.children = []
        self.file_groups = [list(g) for g in file_groups]
        self.projection = list(projection) if projection else None
        self.pruning_predicate = pruning_predicate
        if schema is None:
            from blaze_tpu.io.object_store import store_for

            first = self.file_groups[0][0].path
            aschema = pq.read_schema(store_for(first).open_input(first))
            if self.projection:
                aschema = __import__("pyarrow").schema(
                    [aschema.field(n) for n in self.projection]
                )
            schema = from_arrow_schema(aschema)
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return len(self.file_groups)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        import pyarrow.parquet as pq

        from blaze_tpu.io.object_store import store_for
        from blaze_tpu.runtime.prefetch import prefetch

        cfg = ctx.config
        cols = self.projection or [f.name for f in self._schema]

        def decode() -> Iterator[ColumnBatch]:
            for fr in self.file_groups[partition]:
                # all byte IO flows through the object-store seam (the
                # reference's registered ObjectStore, exec.rs:96-103)
                pf = pq.ParquetFile(
                    store_for(fr.path).open_input(fr.path)
                )
                groups = self._select_row_groups(pf, fr)
                if not groups:
                    continue
                for rb in pf.iter_batches(
                    batch_size=cfg.batch_size, row_groups=groups,
                    columns=cols, use_threads=True,
                ):
                    ctx.metrics.add("input_rows", rb.num_rows)
                    ctx.metrics.add("input_batches", 1)
                    if rb.num_rows == 0:
                        continue
                    yield ColumnBatch.from_arrow(rb)

        # overlap parquet decode + H2D with downstream device compute
        # (SURVEY 7 streaming model: double-buffered host pipeline)
        yield from prefetch(decode(), depth=2)

    # ------------------------------------------------------------------
    def _select_row_groups(self, pf, fr: FileRange) -> List[int]:
        """Row groups whose byte midpoint falls in the split range (Spark's
        split ownership rule) and that survive stats pruning."""
        md = pf.metadata
        out = []
        for i in range(md.num_row_groups):
            rg = md.row_group(i)
            if fr.length > 0:
                start = rg.column(0).file_offset
                mid = start + rg.total_byte_size // 2
                if not (fr.start <= mid < fr.start + fr.length):
                    continue
            if self.pruning_predicate is not None and not _may_match(
                self.pruning_predicate, rg, self._schema
            ):
                continue
            out.append(i)
        return out


def _may_match(pred: ir.Expr, rg, schema: Schema) -> bool:
    """Conservative stats-based pruning: False only when the predicate
    provably rejects the whole row group. Handles comparisons between a
    column and a literal plus AND/OR composition (the reference gets the
    equivalent from DataFusion's PruningPredicate)."""
    from blaze_tpu.exprs.ir import BinaryOp, Col, BoundCol, Literal, Op

    if isinstance(pred, BinaryOp) and pred.op in (Op.AND, Op.OR):
        l = _may_match(pred.left, rg, schema)
        r = _may_match(pred.right, rg, schema)
        return (l and r) if pred.op is Op.AND else (l or r)
    if not isinstance(pred, BinaryOp):
        return True
    col, lit, op = None, None, pred.op
    flip = {Op.LT: Op.GT, Op.GT: Op.LT, Op.LTE: Op.GTE, Op.GTE: Op.LTE}
    if isinstance(pred.left, (Col, BoundCol)) and isinstance(
        pred.right, Literal
    ):
        col, lit = pred.left, pred.right
    elif isinstance(pred.right, (Col, BoundCol)) and isinstance(
        pred.left, Literal
    ):
        col, lit = pred.right, pred.left
        op = flip.get(op, op)
    if col is None or lit.value is None:
        return True
    name = col.name if isinstance(col, Col) else schema.fields[col.index].name
    stats = None
    for ci in range(rg.num_columns):
        c = rg.column(ci)
        if c.path_in_schema == name:
            stats = c.statistics
            break
    if stats is None or not stats.has_min_max:
        return True
    lo, hi, v = stats.min, stats.max, lit.value
    try:
        if op is Op.EQ:
            return lo <= v <= hi
        if op is Op.LT:
            return lo < v
        if op is Op.LTE:
            return lo <= v
        if op is Op.GT:
            return hi > v
        if op is Op.GTE:
            return hi >= v
    except TypeError:
        return True
    return True
