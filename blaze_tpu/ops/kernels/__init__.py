"""Pallas TPU kernels for hot ops the XLA fusion path doesn't already own.

SURVEY 7 design stance: "hash partition = murmur3 (bit-exact Spark
semantics) as a Pallas kernel". Everything here ships with a jnp fallback
and an interpret-mode test path so the CPU test mesh exercises the same
code."""
