"""Pallas kernel: mask compaction (the filter pipeline-breaker).

Reference counterpart: the selection-vector materialization inside
FilterExec that the reference gets from DataFusion's `filter` compute
kernel (from_proto.rs FilterExec arm); SURVEY 7 names compaction as the
second TPU-first Pallas target. The engine usually DEFERS selection
(batch.ColumnBatch.selection rides through fused kernels), but pipeline
breakers (shuffle writers, external spill, host hand-off) must
physically drop dead rows.

A naive gather-by-sorted-indices serializes on TPU. This kernel keeps
the index computation matrix-shaped:

  per row-block (1024 rows):
    pos[i]  = cumsum(keep)[i] - 1          (block-local target slot)
    out[j]  = sum_i idx[i] * (pos[i] == j & keep[i])   - an MXU
              contraction of block-LOCAL ROW INDICES against the
              permutation one-hot
  per block it also emits the block's keep-count.

The kernel compacts INDICES, not data: local indices are in [0, 1024),
always exact in f32, so the IEEE 0*NaN hazard of contracting raw data
(one non-finite row anywhere in a block would poison every surviving
row of that block) cannot arise. Cross-block stitching happens in jnp
glue (`compact_perm`): block outputs are dense prefixes, so indices
derived from the per-block count prefix sum compose into one global
source-row permutation. Data columns of ANY dtype then move by a
single bit-exact gather - one kernel launch serves every column
compacted by the same mask.

Tested with interpret=True on CPU (tests/test_pallas_kernels.py);
hardware enablement follows the same bench-gated path as the
segmented-reduce kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROWS_BLK = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compact_kernel(v_ref, keep_ref, out_ref, cnt_ref):
    v = v_ref[:].reshape(_ROWS_BLK).astype(jnp.float32)
    keep = keep_ref[:].reshape(_ROWS_BLK)
    kept = keep != 0
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    pos = jnp.where(kept, pos, -1)
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (_ROWS_BLK, _ROWS_BLK), 1
    )
    oh = (pos[:, None] == cols).astype(jnp.float32)
    out = jax.lax.dot_general(
        v[None, :], oh,
        (((1,), (0,)), ((), ())),
        # HIGHEST: default MXU precision truncates operands to bf16,
        # which would corrupt the "moved exactly once" guarantee (and
        # the int32 plane reconstruction) on real hardware
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).reshape(_ROWS_BLK)
    out_ref[:] = out.reshape(out_ref.shape)
    cnt_ref[0, 0] = jnp.sum(keep.astype(jnp.int32))


def _call_compact(v2, keep2, n_blocks: int):
    blk = (_ROWS_BLK // _LANES, _LANES)
    return pl.pallas_call(
        _compact_kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(
                (1,) + blk, lambda b: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1,) + blk, lambda b: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1,) + blk, lambda b: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1), lambda b: (b, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(
                (n_blocks,) + blk, jnp.float32
            ),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(v2, keep2)


def supports(capacity: int) -> bool:
    return capacity % _ROWS_BLK == 0


@jax.jit
def compact_perm(keep: jax.Array):
    """Compute the compaction PERMUTATION: for every global output slot,
    the global source row, plus the live count.

    The kernel contracts block-LOCAL row indices (values in [0, 1024),
    always exactly representable in f32 - so the IEEE 0*NaN hazard of
    contracting raw data can never arise) against the one-hot; data
    columns then move by a plain gather. One kernel launch serves every
    column and dtype compacted by the same mask."""
    cap = keep.shape[0]
    n_blocks = cap // _ROWS_BLK
    shape3 = (n_blocks, _ROWS_BLK // _LANES, _LANES)
    local_idx = jnp.broadcast_to(
        jnp.arange(_ROWS_BLK, dtype=jnp.float32), (n_blocks, _ROWS_BLK)
    )
    blocks, cnts = _call_compact(
        local_idx.reshape(shape3),
        keep.astype(jnp.int32).reshape(shape3),
        n_blocks,
    )
    flat = blocks.reshape(n_blocks, _ROWS_BLK)
    cnts = cnts.reshape(n_blocks)
    # stitch: global position of block b's local slot j is
    # offset[b] + j; invert so each output slot knows its source
    offsets = jnp.cumsum(cnts) - cnts
    n_live = jnp.sum(cnts)
    out_pos = jnp.arange(cap, dtype=jnp.int32)
    # for each output slot, which (block, local) produced it?
    blk_of = jnp.searchsorted(
        jnp.cumsum(cnts), out_pos, side="right"
    ).astype(jnp.int32)
    blk_of = jnp.clip(blk_of, 0, n_blocks - 1)
    local = out_pos - jnp.take(offsets, blk_of)
    slot = blk_of * _ROWS_BLK + jnp.clip(local, 0, _ROWS_BLK - 1)
    src = blk_of * _ROWS_BLK + jnp.take(
        flat.reshape(cap), slot
    ).astype(jnp.int32)
    return src, n_live


@jax.jit
def compact_column_f32(v: jax.Array, keep: jax.Array):
    """Compact one f32 column by a boolean mask.

    Returns (compacted, n_live): `compacted` has the input's length,
    live rows packed at the front, zeros after. Exact for EVERY f32
    bit pattern including NaN/inf - values move by gather through the
    index permutation, never through arithmetic."""
    src, n_live = compact_perm(keep)
    out_pos = jnp.arange(v.shape[0], dtype=jnp.int32)
    gathered = jnp.take(v.astype(jnp.float32), src)
    return (
        jnp.where(out_pos < n_live, gathered, jnp.float32(0.0)),
        n_live,
    )


@jax.jit
def compact_column_i32(v: jax.Array, keep: jax.Array):
    """Exact int32 compaction via the same index permutation."""
    src, n_live = compact_perm(keep)
    out_pos = jnp.arange(v.shape[0], dtype=jnp.int32)
    gathered = jnp.take(v.astype(jnp.int32), src)
    return jnp.where(out_pos < n_live, gathered, jnp.int32(0)), n_live


def compact_columns(cols, keep):
    """Compact many columns by ONE mask: the permutation kernel runs
    once, each column moves by a single gather. `cols` is a sequence of
    1-D arrays (any dtype, same capacity as `keep`); returns
    ([compacted...], n_live) with dead tail slots zeroed."""
    src, n_live = compact_perm(keep)
    cap = keep.shape[0]
    out_pos = jnp.arange(cap, dtype=jnp.int32)
    outs = []
    for v in cols:
        g = jnp.take(v, src)
        outs.append(
            jnp.where(out_pos < n_live, g, jnp.zeros((), g.dtype))
        )
    return outs, n_live
