"""Pallas kernel: mask compaction (the filter pipeline-breaker).

Reference counterpart: the selection-vector materialization inside
FilterExec that the reference gets from DataFusion's `filter` compute
kernel (from_proto.rs FilterExec arm); SURVEY 7 names compaction as the
second TPU-first Pallas target. The engine usually DEFERS selection
(batch.ColumnBatch.selection rides through fused kernels), but pipeline
breakers (shuffle writers, external spill, host hand-off) must
physically drop dead rows.

A naive gather-by-sorted-indices serializes on TPU. This kernel keeps
everything matrix-shaped:

  per row-block (1024 rows):
    pos[i]  = cumsum(keep)[i] - 1          (block-local target slot)
    out[j]  = sum_i v[i] * (pos[i] == j & keep[i])   - an MXU
              contraction against the block-local permutation one-hot
  per block it also emits the block's keep-count.

Cross-block stitching happens in jnp glue (`compact_column`): block
outputs are dense prefixes, so one gather with indices derived from the
per-block count prefix sum concatenates them - the gather touches only
surviving rows. Ints ride the same f32 contraction exactly up to 2^24;
wider ints split into two 16-bit planes contracted separately and
recombined (exact for the full int32 range).

Tested with interpret=True on CPU (tests/test_pallas_kernels.py);
hardware enablement follows the same bench-gated path as the
segmented-reduce kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROWS_BLK = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compact_kernel(v_ref, keep_ref, out_ref, cnt_ref):
    v = v_ref[:].reshape(_ROWS_BLK).astype(jnp.float32)
    keep = keep_ref[:].reshape(_ROWS_BLK)
    kept = keep != 0
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    pos = jnp.where(kept, pos, -1)
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (_ROWS_BLK, _ROWS_BLK), 1
    )
    oh = (pos[:, None] == cols).astype(jnp.float32)
    out = jax.lax.dot_general(
        v[None, :], oh,
        (((1,), (0,)), ((), ())),
        # HIGHEST: default MXU precision truncates operands to bf16,
        # which would corrupt the "moved exactly once" guarantee (and
        # the int32 plane reconstruction) on real hardware
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).reshape(_ROWS_BLK)
    out_ref[:] = out.reshape(out_ref.shape)
    cnt_ref[0, 0] = jnp.sum(keep.astype(jnp.int32))


def _call_compact(v2, keep2, n_blocks: int):
    blk = (_ROWS_BLK // _LANES, _LANES)
    return pl.pallas_call(
        _compact_kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(
                (1,) + blk, lambda b: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1,) + blk, lambda b: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1,) + blk, lambda b: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1), lambda b: (b, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(
                (n_blocks,) + blk, jnp.float32
            ),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(v2, keep2)


def supports(capacity: int) -> bool:
    return capacity % _ROWS_BLK == 0


@jax.jit
def compact_column_f32(v: jax.Array, keep: jax.Array):
    """Compact one f32 column by a boolean mask.

    Returns (compacted, n_live): `compacted` has the input's length,
    live rows packed at the front, zeros after. Exact for f32 (the
    one-hot contraction moves each value once, no arithmetic)."""
    cap = v.shape[0]
    n_blocks = cap // _ROWS_BLK
    shape3 = (n_blocks, _ROWS_BLK // _LANES, _LANES)
    blocks, cnts = _call_compact(
        v.astype(jnp.float32).reshape(shape3),
        keep.astype(jnp.int32).reshape(shape3),
        n_blocks,
    )
    flat = blocks.reshape(n_blocks, _ROWS_BLK)
    cnts = cnts.reshape(n_blocks)
    # stitch: global position of block b's local slot j is
    # offset[b] + j; invert to a single gather of surviving rows
    offsets = jnp.cumsum(cnts) - cnts
    n_live = jnp.sum(cnts)
    out_pos = jnp.arange(cap, dtype=jnp.int32)
    # for each output slot, which (block, local) produced it?
    blk_of = jnp.searchsorted(
        jnp.cumsum(cnts), out_pos, side="right"
    ).astype(jnp.int32)
    blk_of = jnp.clip(blk_of, 0, n_blocks - 1)
    local = out_pos - jnp.take(offsets, blk_of)
    src = blk_of * _ROWS_BLK + jnp.clip(local, 0, _ROWS_BLK - 1)
    gathered = jnp.take(flat.reshape(cap), src)
    return (
        jnp.where(out_pos < n_live, gathered, jnp.float32(0.0)),
        n_live,
    )


@jax.jit
def compact_column_i32(v: jax.Array, keep: jax.Array):
    """Exact int32 compaction: two 16-bit planes ride the f32
    contraction (each plane < 2^16 is exactly representable) and
    recombine."""
    cap = v.shape[0]
    vi = v.astype(jnp.int32)
    lo = (vi & jnp.int32(0xFFFF)).astype(jnp.float32)
    hi = jax.lax.shift_right_logical(
        vi, jnp.int32(16)
    ).astype(jnp.float32)
    clo, n_live = compact_column_f32(lo, keep)
    chi, _ = compact_column_f32(hi, keep)
    out = (
        chi.astype(jnp.int32) << jnp.int32(16)
    ) | clo.astype(jnp.int32)
    out_pos = jnp.arange(cap, dtype=jnp.int32)
    return jnp.where(out_pos < n_live, out, jnp.int32(0)), n_live
