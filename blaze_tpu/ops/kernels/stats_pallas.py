"""Pallas kernel: fused masked column statistics (sum/min/max/count).

SURVEY 7's build plan calls for segmented-reduce-class Pallas kernels
beyond murmur3. Full sorted-segment reductions need scatter stores,
which this Mosaic build does not legalize (see murmur3_pallas.py notes);
what IS expressible in the proven whole-block form is the single-group
core every keyless aggregate and every range-sampling/statistics pass
runs: ONE memory pass over a masked f32/i32 column producing all four
reduction states at once, instead of four separate XLA reductions each
re-reading the column from HBM.

Layout mirrors murmur3_pallas: (rows/128, 128) VMEM blocks, chunked
through an outer lax.map; per-chunk partials (shape (4,) per chunk)
combine outside the kernel - the combine is O(chunks), the pass is
O(rows). Masked-out lanes contribute the operation identity (0 for
sum/count, +inf/-inf for min/max); an all-masked column reports
count 0 and the caller maps min/max to NULL, exactly like the
aggregate's masked reductions.

Status: a STANDALONE fast path with its own API - `supports()` gates
eligibility (f32/i32, bucket-aligned) but nothing dispatches to it yet;
wiring into the keyless-aggregate path waits on hardware legalization
(the tunnel was down all round - ROADMAP). Interpret mode pins
semantics on the CPU test mesh (tests/test_pallas_kernels.py).

Accuracy: per-chunk partials accumulate in f32 (512K-row chunks keep
counts exact; value sums carry f32 rounding - rtol ~1e-5); the
cross-chunk combine runs in f64 outside the kernel. Callers needing
exact integer sums must keep the XLA int64 path.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BLOCK_ROWS = 1024
_CHUNK_ROWS = 1 << 19  # 512K rows: 2MB values + 2MB mask in VMEM

_POS_INF = np.float32(np.inf)
_NEG_INF = np.float32(-np.inf)


def supports(capacity: int, dtype) -> bool:
    return (
        capacity % _BLOCK_ROWS == 0
        and jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                 jnp.dtype(jnp.int32))
    )


def _kernel(v_ref, m_ref, out_ref):
    v = v_ref[:].astype(jnp.float32)
    m = m_ref[:]
    live = m != 0
    s = jnp.sum(jnp.where(live, v, np.float32(0.0)))
    lo = jnp.min(jnp.where(live, v, _POS_INF))
    hi = jnp.max(jnp.where(live, v, _NEG_INF))
    n = jnp.sum(m.astype(jnp.float32))
    # (1, 4) output tile: scalar reductions packed on the lane axis
    out_ref[0, 0] = s
    out_ref[0, 1] = lo
    out_ref[0, 2] = hi
    out_ref[0, 3] = n


def _call(v2, m2, interpret):
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, 4), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=interpret,
    )(v2, m2)


def _chunked(cap: int):
    chunk = min(cap, _CHUNK_ROWS)
    while cap % chunk:
        chunk //= 2
    return cap // chunk, chunk


@partial(jax.jit, static_argnames=("interpret",))
def masked_stats(values: jax.Array, mask: jax.Array,
                 interpret: bool = False) -> jax.Array:
    """(sum, min, max, count) over rows where mask!=0, as one f32[4].
    `values` length must be a multiple of 1024 (shape buckets are);
    empty selection -> (0, +inf, -inf, 0)."""
    cap = values.shape[0]
    n_chunks, chunk = _chunked(cap)
    shape3 = (n_chunks, chunk // _LANES, _LANES)
    v3 = values.astype(jnp.float32).reshape(shape3)
    m3 = mask.astype(jnp.int32).reshape(shape3)
    parts = jax.lax.map(
        lambda b: _call(b[0], b[1], interpret), (v3, m3)
    )  # (n_chunks, 1, 4)
    # combine across chunks in f64: counts stay exact past 2^24 rows
    # and the sum-of-partials adds no further f32 rounding
    parts = parts.reshape(n_chunks, 4).astype(jnp.float64)
    return jnp.stack([
        jnp.sum(parts[:, 0]),
        jnp.min(parts[:, 1]),
        jnp.max(parts[:, 2]),
        jnp.sum(parts[:, 3]),
    ])
