"""Pallas kernel: segmented reduction (group-by core) for bounded key
domains.

Reference counterpart: the per-row accumulator update loops inside
DataFusion's grouped aggregation that the reference reuses
(from_proto.rs:452-545); SURVEY 7 names segmented-reduce as a TPU-first
Pallas target. A row-at-a-time hash-table update is the wrong shape for
a systolic array, and XLA lowers `segment_sum` to a serialized scatter
on TPU. This kernel instead reformulates the reduction as matmul:

    out[k] = sum_i v[i] * onehot(gid[i])[k]

i.e. a (rows x K) one-hot contraction - which runs on the MXU at full
tile utilization. The grid tiles rows (ROWS_BLK) x segments (K_BLK);
each (row-block, k-tile) step contracts the block's one-hot slice and
accumulates into the K-tile's output block (constant index_map over the
row dimension - the canonical Pallas accumulation pattern). FLOP cost is
rows*K, so this is the right core exactly where the scatter core's
direct-domain branch lives: group counts bounded by a few thousand
(TPC-DS rollup keys: brand/year/month/quarter/store). MIN/MAX ride the
same contraction with +/-inf masking and a max-reduction instead of a
dot - still VPU/MXU shaped, no scatter anywhere.

Tested with interpret=True on CPU (tests/test_pallas_kernels.py);
auto-enabled on TPU hardware via ops/hash_aggregate's segops once the
end-of-round bench validates it against the XLA scatter path
(bench.py tpu_core_probe).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROWS_BLK = 1024      # rows per grid step (8 sublanes x 128 lanes)
_K_BLK = 512          # segment slots per grid step
_MAX_K = 8192         # beyond this, rows*K FLOPs lose to the sort core


def _sum_kernel(gid_ref, v_ref, out_ref):
    """One (row-block, k-tile) step: out[k] += v . onehot(gid)[:, k]."""
    rb = pl.program_id(1)
    k0 = pl.program_id(0) * _K_BLK
    gid = gid_ref[:].reshape(_ROWS_BLK)
    v = v_ref[:].reshape(_ROWS_BLK).astype(jnp.float32)
    # one-hot slice for this k-tile: (ROWS_BLK, K_BLK)
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (_ROWS_BLK, _K_BLK), 1
    ) + k0
    hit = gid[:, None] == cols
    # IEEE hazard: 0 * NaN/inf = NaN, so one non-finite row anywhere in
    # the block would poison EVERY segment the contraction touches. The
    # MXU dot runs over sanitized values only; non-finite rows re-enter
    # through a where-masked VPU reduction (a select, not a multiply,
    # so unselected NaN/inf rows truly contribute nowhere) - gated by
    # pl.when so the all-finite common case pays nothing extra.
    finite = jnp.isfinite(v)
    part = jax.lax.dot_general(
        jnp.where(finite, v, jnp.float32(0.0))[None, :],
        hit.astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        # HIGHEST: default precision truncates f32 operands to bf16 on
        # the MXU, which would silently diverge from the XLA scatter
        # path this kernel must match
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).reshape(_K_BLK)

    @pl.when(rb == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] = out_ref[:] + part.reshape(out_ref.shape)

    @pl.when(jnp.any(~finite))
    def _nonfinite_fixup():
        corr = jnp.sum(
            jnp.where(
                hit & ~finite[:, None], v[:, None], jnp.float32(0.0)
            ),
            axis=0,
        )
        out_ref[:] = out_ref[:] + corr.reshape(out_ref.shape)


def _minmax_kernel(gid_ref, v_ref, out_ref, *, is_min: bool):
    rb = pl.program_id(1)
    k0 = pl.program_id(0) * _K_BLK
    gid = gid_ref[:].reshape(_ROWS_BLK)
    v = v_ref[:].reshape(_ROWS_BLK).astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (_ROWS_BLK, _K_BLK), 1
    ) + k0
    neutral = jnp.float32(np.inf if is_min else -np.inf)
    masked = jnp.where(
        gid[:, None] == cols, v[:, None], neutral
    )
    part = (
        jnp.min(masked, axis=0) if is_min else jnp.max(masked, axis=0)
    )

    @pl.when(rb == 0)
    def _init():
        out_ref[:] = jnp.full_like(out_ref, neutral)

    cur = out_ref[:].reshape(_K_BLK)
    out_ref[:] = (
        jnp.minimum(cur, part) if is_min else jnp.maximum(cur, part)
    ).reshape(out_ref.shape)


def _call(kernel, gid, v, k: int):
    cap = gid.shape[0]
    n_rb = cap // _ROWS_BLK
    n_kb = k // _K_BLK
    grid = (n_kb, n_rb)
    gid2 = gid.reshape(n_rb, _ROWS_BLK // _LANES, _LANES)
    v2 = v.reshape(n_rb, _ROWS_BLK // _LANES, _LANES)
    blk = (_ROWS_BLK // _LANES, _LANES)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1,) + blk, lambda kb, rb: (rb, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1,) + blk, lambda kb, rb: (rb, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (_K_BLK // _LANES, _LANES), lambda kb, rb: (kb, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (k // _LANES, _LANES), jnp.float32
        ),
        interpret=_interpret(),
    )(gid2, v2).reshape(k)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def supports(capacity: int, k: int) -> bool:
    """Static applicability: row/segment tiles must divide evenly and
    the rows*K FLOP budget must stay MXU-cheap."""
    return (
        capacity % _ROWS_BLK == 0
        and k % _K_BLK == 0
        and k <= _MAX_K
    )


@partial(jax.jit, static_argnames=("k",))
def segment_sum(gid: jax.Array, v: jax.Array, k: int) -> jax.Array:
    """sum of v per segment, f32, for gid in [0, k). Rows with gid
    outside [0, k) contribute nowhere (the one-hot row is all zero) -
    callers park dead rows at an out-of-range id or pre-zero them."""
    return _call(_sum_kernel, gid.astype(jnp.int32), v, k)


@partial(jax.jit, static_argnames=("k", "is_min"))
def segment_minmax(gid: jax.Array, v: jax.Array, k: int,
                   is_min: bool) -> jax.Array:
    """min/max of v per segment, f32; empty segments hold +/-inf."""
    return _call(
        partial(_minmax_kernel, is_min=is_min),
        gid.astype(jnp.int32), v, k,
    )
