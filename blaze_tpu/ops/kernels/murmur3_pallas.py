"""Pallas kernel: Spark murmur3 + pmod partition ids.

The shuffle writer's per-row hot op (reference computes it row-batched in
Rust, spark_hash.rs create_hashes + pmod; SURVEY 7 calls for it as a Pallas
kernel). Pure VPU uint32 integer ops over (8, 128)-tiled row blocks; the
partition count is compile-time static so the modulo strengthens to
multiply-shift.

64-bit inputs enter pre-split as two uint32 word planes (the TPU backend
neither loads s64 tiles natively nor bitcasts them - the split is two
cheap emulated i64 ops outside the kernel, amortized over the whole
column).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# All arithmetic runs in int32: two's-complement wrap-around makes add /
# multiply / xor / shifts bit-identical to the uint32 formulation, and
# Mosaic's int32 lowering is the well-trodden path. Right shifts must be
# LOGICAL (lax.shift_right_logical), never arithmetic.
_i32 = lambda x: np.int32(np.uint32(x))  # noqa: E731
_C1 = _i32(0xCC9E2D51)
_C2 = _i32(0x1B873593)
_M5 = _i32(0xE6546B64)
_FX1 = _i32(0x85EBCA6B)
_FX2 = _i32(0xC2B2AE35)
_SEED = np.int32(42)

_LANES = 128
_SUBLANES = 8
_BLOCK_ROWS = _LANES * _SUBLANES  # minimum row granularity
# One pallas invocation processes a VMEM-sized chunk; larger columns run
# through an outer lax.map. (The axon toolchain's Mosaic build fails to
# legalize gridded pallas_calls - "func.return" - so the kernel uses the
# whole-block form, which compiles and runs fine.)
_CHUNK_ROWS = 1 << 19  # 512K rows = 2 MB int32 in / 2 MB out of ~16MB VMEM


def _shr(x, r: int):
    return jax.lax.shift_right_logical(x, np.int32(r))


def _rotl(x, r: int):
    return (x << np.int32(r)) | _shr(x, 32 - r)


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return h1 * np.int32(5) + _M5


def _fmix(h1, length):
    h1 = h1 ^ np.int32(length)
    h1 = h1 ^ _shr(h1, 16)
    h1 = h1 * _FX1
    h1 = h1 ^ _shr(h1, 13)
    h1 = h1 * _FX2
    return h1 ^ _shr(h1, 16)


def _pmod_i32(h, n: int):
    r = h % np.int32(n)
    return jnp.where(r < 0, r + np.int32(n), r)


def _kernel_int32(v_ref, out_ref, *, n_parts: int):
    v = v_ref[:]
    h = _fmix(_mix_h1(_SEED, _mix_k1(v)), 4)
    out_ref[:] = _pmod_i32(h, n_parts)


def _kernel_int64(lo_ref, hi_ref, out_ref, *, n_parts: int):
    h1 = _mix_h1(_SEED, _mix_k1(lo_ref[:]))
    h1 = _mix_h1(h1, _mix_k1(hi_ref[:]))
    h = _fmix(h1, 8)
    out_ref[:] = _pmod_i32(h, n_parts)


def _chunked(cap: int):
    assert cap % _BLOCK_ROWS == 0, "shape buckets are multiples of 1024"
    chunk = min(cap, _CHUNK_ROWS)
    while cap % chunk:
        chunk //= 2
    return cap // chunk, chunk


def _call_1in(kernel, v2, interpret):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(v2.shape, jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v2)


def _call_2in(kernel, lo, hi, interpret):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(lo.shape, jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(lo, hi)


@partial(jax.jit, static_argnames=("n_parts", "interpret"))
def partition_ids_int32(values: jax.Array, n_parts: int,
                        interpret: bool = False) -> jax.Array:
    """Spark partition id per row for one int32/date32 key column.
    `values` length must be a multiple of 1024 (shape buckets are)."""
    cap = values.shape[0]
    n_chunks, chunk = _chunked(cap)
    kernel = partial(_kernel_int32, n_parts=n_parts)
    v3 = values.astype(jnp.int32).reshape(
        n_chunks, chunk // _LANES, _LANES
    )
    out = jax.lax.map(
        lambda v2: _call_1in(kernel, v2, interpret), v3
    )
    return out.reshape(cap)


@partial(jax.jit, static_argnames=("n_parts", "interpret"))
def partition_ids_int64(values: jax.Array, n_parts: int,
                        interpret: bool = False) -> jax.Array:
    """Spark partition id per row for one int64/timestamp key column."""
    cap = values.shape[0]
    n_chunks, chunk = _chunked(cap)
    v = values.astype(jnp.int64)
    lo = jnp.bitwise_and(v, 0xFFFFFFFF).astype(jnp.int32)
    hi = jnp.bitwise_and(jnp.right_shift(v, 32), 0xFFFFFFFF).astype(
        jnp.int32
    )
    shape3 = (n_chunks, chunk // _LANES, _LANES)
    kernel = partial(_kernel_int64, n_parts=n_parts)
    out = jax.lax.map(
        lambda b: _call_2in(kernel, b[0], b[1], interpret),
        (lo.reshape(shape3), hi.reshape(shape3)),
    )
    return out.reshape(cap)


def supports(dtype_id: str, capacity: int) -> bool:
    return capacity % _BLOCK_ROWS == 0 and dtype_id in (
        "int32", "date32", "int64", "timestamp_us"
    )
