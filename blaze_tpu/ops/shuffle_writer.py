"""Shuffle write: hash-repartition batches into a .data/.index file pair.

Reference counterpart: the native ShuffleWriterExec (shuffle_writer_exec.rs,
780 LoC): spark-murmur3 pmod bucketing, per-partition buffers with
spill-to-disk under memory pressure, final merge into one data file + LE
i64 offsets index, committed by Spark (ArrowShuffleExchangeExec301.scala:
531-602). Single-partition (no-key) and round-robin variants cover the
JVM fallback paths' semantics.

TPU-first layout (SURVEY 7 step 5): partition ids are computed on-device
(bit-exact Spark murmur3 over the key columns) and the row scatter is ONE
stable device argsort by partition id - the counting-sort scatter of the
reference (rs:349-371) becomes an XLA sort - followed by a single D2H
transfer of the already-partition-contiguous batch. String/f64 keys hash
through the C++ host runtime instead (TPU has no string compute; its f64
is not bit-exact - exprs/hashing.device_hash_supported).
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.config import get_config
from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.hashing import (
    device_hash_supported,
    hash_columns_device,
    pmod,
)
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.io.ipc import encode_ipc_segment
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.host_lower import lower_strings_host
from blaze_tpu.ops.util import ensure_compacted, take_batch
from blaze_tpu.runtime import native
from blaze_tpu.runtime.memory import get_pool


class PartitionBuffers:
    """Per-partition compressed segment buffers with the reference's
    buffer->spill->merge ladder (PartitionBuffer/spill_into,
    shuffle_writer_exec.rs:66-194, :522-556)."""

    def __init__(self, num_partitions: int, spill_dir: str):
        self.num_partitions = num_partitions
        self.buffers: List[bytearray] = [
            bytearray() for _ in range(num_partitions)
        ]
        self.spills: List[Tuple[str, List[int]]] = []
        self.spill_dir = spill_dir
        self.mem_used = 0
        self._pool = get_pool()
        self._pool.register(id(self), self.spill)

    def append(self, partition: int, part: bytes) -> None:
        self.buffers[partition] += part
        self.mem_used += len(part)
        self._pool.grow(id(self), len(part))

    def spill(self) -> int:
        """Write current buffers to a spill file; returns bytes released."""
        if self.mem_used == 0:
            return 0
        path = os.path.join(
            self.spill_dir,
            f"blz-spill-{id(self):x}-{len(self.spills)}.tmp",
        )
        offsets = [0] * (self.num_partitions + 1)
        pos = 0
        with open(path, "wb") as f:
            for p in range(self.num_partitions):
                offsets[p] = pos
                f.write(self.buffers[p])
                pos += len(self.buffers[p])
                self.buffers[p] = bytearray()
        offsets[self.num_partitions] = pos
        self.spills.append((path, offsets))
        released = self.mem_used
        self.mem_used = 0
        return released

    def finalize(self, data_path: str, index_path: str) -> List[int]:
        """Assemble .data/.index (native C++ fast path); returns partition
        lengths. Cleans up spill files."""
        native.shuffle_assemble(
            data_path, index_path,
            [bytes(b) for b in self.buffers],
            self.num_partitions, self.spills,
        )
        self._pool.shrink(id(self), self.mem_used)
        self._pool.unregister(id(self))
        self.mem_used = 0
        for path, _ in self.spills:
            try:
                os.remove(path)
            except OSError:
                pass
        from blaze_tpu.io.ipc import partition_ranges

        return [length for _, length in partition_ranges(index_path)]


def spark_partition_ids(cb: ColumnBatch, key_exprs: Sequence[ir.Expr],
                        num_partitions: int) -> np.ndarray:
    """Spark-murmur3 pmod partition id per live row (batch must be
    compacted). Device fast path when all key dtypes hash bit-exactly
    there; C++/numpy host path otherwise."""
    schema = cb.schema
    dtypes = [infer_dtype(e, schema) for e in key_exprs]
    # pallas fast path: single non-nullable int key on real TPU hardware
    # (SURVEY 7: murmur3 partition hash as a Pallas kernel)
    if (
        len(key_exprs) == 1
        and isinstance(key_exprs[0], ir.BoundCol)
        and cb.columns[key_exprs[0].index].validity is None
        and jax.default_backend() == "tpu"
    ):
        from blaze_tpu.ops.kernels import murmur3_pallas as mp

        col = cb.columns[key_exprs[0].index]
        tid = dtypes[0].id.value
        if mp.supports(tid, cb.capacity):
            fn = (
                mp.partition_ids_int32
                if tid in ("int32", "date32")
                else mp.partition_ids_int64
            )
            pids = fn(col.values, num_partitions)
            return np.asarray(pids)[: cb.num_rows]
    if all(device_hash_supported(dt) for dt in dtypes):
        cols = []
        ev = DeviceEvaluator(
            schema, [(c.values, c.validity) for c in cb.columns],
            cb.capacity,
        )
        for e, dt in zip(key_exprs, dtypes):
            v, m = ev.evaluate(e)
            cols.append((v, m, dt))
        h = hash_columns_device(cols, cb.capacity)
        pids = pmod(h, num_partitions)
        return np.asarray(pids)[: cb.num_rows]
    # host path: exact Spark chain incl. utf8 bytes via the C++ runtime
    n = cb.num_rows
    h = np.full(n, 42, dtype=np.uint32)
    ev = DeviceEvaluator(
        schema, [(c.values, c.validity) for c in cb.columns], cb.capacity
    )
    for e, dt in zip(key_exprs, dtypes):
        if dt.is_dictionary_encoded:
            # string keys are plain columns after host lowering
            assert isinstance(e, ir.BoundCol), "string key must be a column"
            col = cb.columns[e.index]
            validity = (
                np.asarray(col.validity)[:n]
                if col.validity is not None
                else None
            )
            h = native.murmur3_dict_strings_chain(
                col.dictionary,
                np.ascontiguousarray(np.asarray(col.values)[:n],
                                     dtype=np.int32),
                validity, h,
            )
        else:
            v, m = ev.evaluate(e)
            validity = np.asarray(m)[:n] if m is not None else None
            h = _chain_fixed(np.asarray(v)[:n], validity, dt, h)
    return native.pmod_np(h, num_partitions)


def _chain_fixed(values, validity, dt, h):
    """Chain one fixed-width column into running hashes (numpy)."""
    from blaze_tpu.exprs import hashing as H
    from blaze_tpu.types import TypeId

    tid = dt.id
    if tid in (TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.DATE32,
               TypeId.BOOL):
        link = H._np_hash_int(values.astype(np.int32).view(np.uint32)
                              if tid is not TypeId.BOOL
                              else values.astype(np.uint32), h)
    elif tid in (TypeId.INT64, TypeId.TIMESTAMP_US) or (
        tid is TypeId.DECIMAL and dt.precision <= 18
    ):
        u = values.astype(np.int64).view(np.uint64)
        low = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        high = (u >> np.uint64(32)).astype(np.uint32)
        h1 = H._np_mix_h1(h, H._np_mix_k1(low))
        h1 = H._np_mix_h1(h1, H._np_mix_k1(high))
        link = H._np_fmix(h1, 8)
    elif tid is TypeId.FLOAT32:
        v = values.astype(np.float32)
        v = np.where(v == 0.0, np.float32(0.0), v)
        link = H._np_hash_int(v.view(np.uint32), h)
    elif tid is TypeId.FLOAT64:
        v = values.astype(np.float64)
        v = np.where(v == 0.0, 0.0, v)
        u = v.view(np.uint64)
        low = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        high = (u >> np.uint64(32)).astype(np.uint32)
        h1 = H._np_mix_h1(h, H._np_mix_k1(low))
        h1 = H._np_mix_h1(h1, H._np_mix_k1(high))
        link = H._np_fmix(h1, 8)
    else:
        raise NotImplementedError(f"hash of {dt}")
    if validity is not None:
        link = np.where(validity, link, h)
    return link


def _key_array_for_range(rb, cb: ColumnBatch, e: ir.Expr) -> np.ndarray:
    """Materialized host key values for range partitioning, as object
    arrays with None for NULL. Non-string keys use the engine's PHYSICAL
    representation (date32 day ints, timestamp micros, decimal unscaled
    i64) - physical order == logical order, and the values round-trip
    through the plan proto as plain int/float literals. Strings use real
    values (dictionary codes don't order). Float NaN maps to +inf so it
    ranks greatest like Spark's total order (inf ties break to the same
    or adjacent partition; the in-partition sort finishes the job)."""
    if isinstance(e, ir.BoundCol):
        idx = e.index
    elif isinstance(e, ir.Col):
        idx = cb.schema.index_of(e.name)
    else:
        raise NotImplementedError(
            "range partitioning keys must be plain columns"
        )
    field = cb.schema.fields[idx]
    n = cb.num_rows
    if field.dtype.is_string_like:
        out = np.asarray(rb.column(idx).to_pandas(), dtype=object)
        return out[:n]
    col = cb.columns[idx]
    vals = np.asarray(col.values)[:n]
    if np.issubdtype(vals.dtype, np.floating):
        vals = np.where(np.isnan(vals), np.inf, vals)
    out = vals.astype(object)
    if col.validity is not None:
        valid = np.asarray(col.validity)[:n]
        out[~valid] = None
    return out


def range_partition_ids(key_arrays: Sequence[np.ndarray],
                        bounds: Sequence[Tuple],
                        ascending: Sequence[bool]) -> np.ndarray:
    """Partition id per row for RANGE partitioning: the count of
    boundary tuples the row's key tuple exceeds lexicographically (rows
    equal to a bound land in the lower partition, like Spark's
    RangePartitioner binary search). NULL ranks first in the sort
    order regardless of direction."""
    import pandas as pd

    n = len(key_arrays[0]) if key_arrays else 0
    pid = np.zeros(n, dtype=np.int32)
    for bound in bounds:
        gt = np.zeros(n, dtype=bool)
        eq = np.ones(n, dtype=bool)
        for arr, bv, asc in zip(key_arrays, bound, ascending):
            isn = pd.isna(arr)
            if bv is None or (isinstance(bv, float) and np.isnan(bv)):
                col_gt = ~isn  # any value outranks a NULL bound
                col_eq = isn
            else:
                # NULL slots can't be compared (object arrays raise);
                # substitute the bound itself, then mask them out
                safe = np.where(isn, bv, arr)
                with np.errstate(invalid="ignore"):
                    raw_gt = np.asarray(safe > bv, dtype=bool)
                    raw_lt = np.asarray(safe < bv, dtype=bool)
                if not asc:
                    raw_gt, raw_lt = raw_lt, raw_gt
                col_gt = raw_gt & ~isn
                col_eq = np.asarray(safe == bv, dtype=bool) & ~isn
            gt = gt | (eq & col_gt)
            eq = eq & col_eq
        pid += gt.astype(np.int32)
    return pid


def compute_range_bounds(sample_df, num_partitions: int,
                         ascending: Sequence[bool]) -> List[Tuple]:
    """num_partitions-1 boundary tuples from a sample of key rows
    (driver-side sampling, reference RangePartitioner role in
    ArrowShuffleExchangeExec301.scala:317-357)."""
    if len(sample_df) == 0 or num_partitions <= 1:
        return []
    s = sample_df.sort_values(
        list(sample_df.columns),
        ascending=list(ascending),
        na_position="first",
        kind="stable",
    ).reset_index(drop=True)
    n = len(s)
    bounds = []
    for k in range(1, num_partitions):
        idx = min(n - 1, (k * n) // num_partitions)
        row = tuple(
            None if (v is None or (isinstance(v, float) and np.isnan(v)))
            else v
            for v in s.iloc[idx]
        )
        bounds.append(row)
    return bounds


class ShuffleWriterExec(PhysicalOp):
    """Writes one map task's shuffle output; the output stream is empty
    (lengths land in the index file), matching the reference
    (external_shuffle, shuffle_writer_exec.rs:753-780)."""

    def __init__(self, child: PhysicalOp, key_exprs: Sequence[ir.Expr],
                 num_partitions: int, data_file: str, index_file: str,
                 mode: str = "hash",
                 range_bounds: Optional[Sequence[Tuple]] = None,
                 sort_ascending: Optional[Sequence[bool]] = None):
        self.children = [child]
        self.key_exprs = [bind_opt(e, child.schema) for e in key_exprs]
        self.num_partitions = num_partitions
        self.data_file = data_file
        self.index_file = index_file
        assert mode in ("hash", "single", "round_robin", "range")
        self.mode = mode
        if mode == "hash" and not key_exprs:
            raise ValueError("hash partitioning requires keys")
        if mode == "range":
            if not key_exprs:
                raise ValueError("range partitioning requires sort keys")
            # bounds are plan constants (driver-sampled) so every map
            # task splits identically
            self.range_bounds = list(range_bounds or [])
            self.sort_ascending = list(
                sort_ascending
                if sort_ascending is not None
                else [True] * len(key_exprs)
            )
        else:
            self.range_bounds = []
            self.sort_ascending = []

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        cfg = ctx.config
        bufs = PartitionBuffers(self.num_partitions, cfg.spill_dir())
        rr_next = partition  # round-robin start varies by map partition
        for cb in self.children[0].execute(partition, ctx):
            cb = ensure_compacted(cb)
            if cb.num_rows == 0:
                continue
            exprs, _, aug = lower_strings_host(self.key_exprs, cb) \
                if self.mode == "hash" else (self.key_exprs, 0, cb)
            if self.mode == "single" or self.num_partitions == 1:
                rb = cb.to_arrow()
                bufs.append(
                    0, encode_ipc_segment(rb, cfg.ipc_compression_level)
                )
                continue
            if self.mode == "round_robin":
                pids = (
                    (np.arange(cb.num_rows) + rr_next)
                    % self.num_partitions
                ).astype(np.int32)
                rr_next = int(
                    (rr_next + cb.num_rows) % self.num_partitions
                )
                order = np.argsort(pids, kind="stable")
                rb_sorted = take_batch(
                    cb, jnp.asarray(np.concatenate(
                        [order,
                         np.arange(cb.num_rows, cb.capacity)])),
                    cb.num_rows,
                ).to_arrow()
                sorted_pids = pids[order]
            elif self.mode == "range":
                # host path: key ordering incl. strings/NULLs needs real
                # values (ordering on dictionary codes would be wrong);
                # the D2H below is the same transfer the IPC encode
                # needs anyway
                rb = cb.to_arrow()
                key_arrays = [
                    _key_array_for_range(rb, cb, e)
                    for e in self.key_exprs
                ]
                pids = range_partition_ids(
                    key_arrays, self.range_bounds, self.sort_ascending
                )
                order = np.argsort(pids, kind="stable")
                rb_sorted = rb.take(order)
                sorted_pids = pids[order]
            else:
                pids = spark_partition_ids(
                    aug, exprs, self.num_partitions
                )
                # scatter = one stable device argsort by partition id
                pid_full = jnp.full(
                    cb.capacity, self.num_partitions, dtype=jnp.int32
                )
                pid_full = pid_full.at[: len(pids)].set(
                    jnp.asarray(pids)
                )
                order_dev = jnp.argsort(pid_full, stable=True)
                rb_sorted = take_batch(
                    cb, order_dev, cb.num_rows
                ).to_arrow()
                sorted_pids = np.sort(pids, kind="stable")
            counts = np.bincount(
                sorted_pids, minlength=self.num_partitions
            )
            start = 0
            for p in range(self.num_partitions):
                c = int(counts[p])
                if c == 0:
                    continue
                part_rb = rb_sorted.slice(start, c)
                bufs.append(
                    p,
                    encode_ipc_segment(
                        part_rb, cfg.ipc_compression_level
                    ),
                )
                start += c
            ctx.metrics.add("shuffle_rows_written", cb.num_rows)
        lengths = bufs.finalize(self.data_file, self.index_file)
        ctx.metrics.add("shuffle_bytes_written", sum(lengths))
        return iter(())
