"""Shuffle write: hash-repartition batches into a .data/.index file pair.

Reference counterpart: the native ShuffleWriterExec (shuffle_writer_exec.rs,
780 LoC): spark-murmur3 pmod bucketing, per-partition buffers with
spill-to-disk under memory pressure, final merge into one data file + LE
i64 offsets index, committed by Spark (ArrowShuffleExchangeExec301.scala:
531-602). Single-partition (no-key) and round-robin variants cover the
JVM fallback paths' semantics.

TPU-first layout (SURVEY 7 step 5): partition ids are computed on-device
(bit-exact Spark murmur3 over the key columns) and the row scatter is ONE
stable device argsort by partition id - the counting-sort scatter of the
reference (rs:349-371) becomes an XLA sort - followed by a single D2H
transfer of the already-partition-contiguous batch. String/f64 keys hash
through the C++ host runtime instead (TPU has no string compute; its f64
is not bit-exact - exprs/hashing.device_hash_supported).
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.config import get_config
from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.hashing import (
    device_hash_supported,
    hash_columns_device,
    pmod,
)
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.io.ipc import encode_ipc_segment
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.host_lower import lower_strings_host
from blaze_tpu.ops.util import ensure_compacted, take_batch
from blaze_tpu.runtime import native
from blaze_tpu.runtime.memory import get_pool


class PartitionBuffers:
    """Per-partition compressed segment buffers with the reference's
    buffer->spill->merge ladder (PartitionBuffer/spill_into,
    shuffle_writer_exec.rs:66-194, :522-556)."""

    def __init__(self, num_partitions: int, spill_dir: str):
        self.num_partitions = num_partitions
        self.buffers: List[bytearray] = [
            bytearray() for _ in range(num_partitions)
        ]
        self.spills: List[Tuple[str, List[int]]] = []
        self.spill_dir = spill_dir
        self.mem_used = 0
        self._pool = get_pool()
        self._pool.register(id(self), self.spill)

    def append(self, partition: int, part: bytes) -> None:
        self.buffers[partition] += part
        self.mem_used += len(part)
        self._pool.grow(id(self), len(part))

    def spill(self) -> int:
        """Write current buffers to a spill file; returns bytes released."""
        if self.mem_used == 0:
            return 0
        path = os.path.join(
            self.spill_dir,
            f"blz-spill-{id(self):x}-{len(self.spills)}.tmp",
        )
        offsets = [0] * (self.num_partitions + 1)
        pos = 0
        with open(path, "wb") as f:
            for p in range(self.num_partitions):
                offsets[p] = pos
                f.write(self.buffers[p])
                pos += len(self.buffers[p])
                self.buffers[p] = bytearray()
        offsets[self.num_partitions] = pos
        self.spills.append((path, offsets))
        released = self.mem_used
        self.mem_used = 0
        return released

    def finalize(self, data_path: str, index_path: str) -> List[int]:
        """Assemble .data/.index (native C++ fast path); returns partition
        lengths. Cleans up spill files."""
        native.shuffle_assemble(
            data_path, index_path,
            [bytes(b) for b in self.buffers],
            self.num_partitions, self.spills,
        )
        self._pool.shrink(id(self), self.mem_used)
        self._pool.unregister(id(self))
        self.mem_used = 0
        for path, _ in self.spills:
            try:
                os.remove(path)
            except OSError:
                pass
        from blaze_tpu.io.ipc import partition_ranges

        return [length for _, length in partition_ranges(index_path)]


def spark_partition_ids(cb: ColumnBatch, key_exprs: Sequence[ir.Expr],
                        num_partitions: int) -> np.ndarray:
    """Spark-murmur3 pmod partition id per live row (batch must be
    compacted). Device fast path when all key dtypes hash bit-exactly
    there; C++/numpy host path otherwise."""
    schema = cb.schema
    dtypes = [infer_dtype(e, schema) for e in key_exprs]
    # pallas fast path: single non-nullable int key on real TPU hardware
    # (SURVEY 7: murmur3 partition hash as a Pallas kernel)
    if (
        len(key_exprs) == 1
        and isinstance(key_exprs[0], ir.BoundCol)
        and cb.columns[key_exprs[0].index].validity is None
        and jax.default_backend() == "tpu"
    ):
        from blaze_tpu.ops.kernels import murmur3_pallas as mp

        col = cb.columns[key_exprs[0].index]
        tid = dtypes[0].id.value
        if mp.supports(tid, cb.capacity):
            fn = (
                mp.partition_ids_int32
                if tid in ("int32", "date32")
                else mp.partition_ids_int64
            )
            pids = fn(col.values, num_partitions)
            return np.asarray(pids)[: cb.num_rows]
    if all(device_hash_supported(dt) for dt in dtypes):
        cols = []
        ev = DeviceEvaluator(
            schema, [(c.values, c.validity) for c in cb.columns],
            cb.capacity,
        )
        for e, dt in zip(key_exprs, dtypes):
            v, m = ev.evaluate(e)
            cols.append((v, m, dt))
        h = hash_columns_device(cols, cb.capacity)
        pids = pmod(h, num_partitions)
        return np.asarray(pids)[: cb.num_rows]
    # host path: exact Spark chain incl. utf8 bytes via the C++ runtime
    n = cb.num_rows
    h = np.full(n, 42, dtype=np.uint32)
    ev = DeviceEvaluator(
        schema, [(c.values, c.validity) for c in cb.columns], cb.capacity
    )
    for e, dt in zip(key_exprs, dtypes):
        if dt.is_dictionary_encoded:
            # string keys are plain columns after host lowering
            assert isinstance(e, ir.BoundCol), "string key must be a column"
            col = cb.columns[e.index]
            validity = (
                np.asarray(col.validity)[:n]
                if col.validity is not None
                else None
            )
            h = native.murmur3_dict_strings_chain(
                col.dictionary,
                np.ascontiguousarray(np.asarray(col.values)[:n],
                                     dtype=np.int32),
                validity, h,
            )
        else:
            v, m = ev.evaluate(e)
            validity = np.asarray(m)[:n] if m is not None else None
            h = _chain_fixed(np.asarray(v)[:n], validity, dt, h)
    return native.pmod_np(h, num_partitions)


def _chain_fixed(values, validity, dt, h):
    """Chain one fixed-width column into running hashes (numpy)."""
    from blaze_tpu.exprs import hashing as H
    from blaze_tpu.types import TypeId

    tid = dt.id
    if tid in (TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.DATE32,
               TypeId.BOOL):
        link = H._np_hash_int(values.astype(np.int32).view(np.uint32)
                              if tid is not TypeId.BOOL
                              else values.astype(np.uint32), h)
    elif tid in (TypeId.INT64, TypeId.TIMESTAMP_US) or (
        tid is TypeId.DECIMAL and dt.precision <= 18
    ):
        u = values.astype(np.int64).view(np.uint64)
        low = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        high = (u >> np.uint64(32)).astype(np.uint32)
        h1 = H._np_mix_h1(h, H._np_mix_k1(low))
        h1 = H._np_mix_h1(h1, H._np_mix_k1(high))
        link = H._np_fmix(h1, 8)
    elif tid is TypeId.FLOAT32:
        v = values.astype(np.float32)
        v = np.where(v == 0.0, np.float32(0.0), v)
        link = H._np_hash_int(v.view(np.uint32), h)
    elif tid is TypeId.FLOAT64:
        v = values.astype(np.float64)
        v = np.where(v == 0.0, 0.0, v)
        u = v.view(np.uint64)
        low = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        high = (u >> np.uint64(32)).astype(np.uint32)
        h1 = H._np_mix_h1(h, H._np_mix_k1(low))
        h1 = H._np_mix_h1(h1, H._np_mix_k1(high))
        link = H._np_fmix(h1, 8)
    else:
        raise NotImplementedError(f"hash of {dt}")
    if validity is not None:
        link = np.where(validity, link, h)
    return link


class ShuffleWriterExec(PhysicalOp):
    """Writes one map task's shuffle output; the output stream is empty
    (lengths land in the index file), matching the reference
    (external_shuffle, shuffle_writer_exec.rs:753-780)."""

    def __init__(self, child: PhysicalOp, key_exprs: Sequence[ir.Expr],
                 num_partitions: int, data_file: str, index_file: str,
                 mode: str = "hash"):
        self.children = [child]
        self.key_exprs = [bind_opt(e, child.schema) for e in key_exprs]
        self.num_partitions = num_partitions
        self.data_file = data_file
        self.index_file = index_file
        assert mode in ("hash", "single", "round_robin")
        self.mode = mode
        if mode == "hash" and not key_exprs:
            raise ValueError("hash partitioning requires keys")

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        cfg = ctx.config
        bufs = PartitionBuffers(self.num_partitions, cfg.spill_dir())
        rr_next = partition  # round-robin start varies by map partition
        for cb in self.children[0].execute(partition, ctx):
            cb = ensure_compacted(cb)
            if cb.num_rows == 0:
                continue
            exprs, _, aug = lower_strings_host(self.key_exprs, cb) \
                if self.mode == "hash" else (self.key_exprs, 0, cb)
            if self.mode == "single" or self.num_partitions == 1:
                rb = cb.to_arrow()
                bufs.append(
                    0, encode_ipc_segment(rb, cfg.ipc_compression_level)
                )
                continue
            if self.mode == "round_robin":
                pids = (
                    (np.arange(cb.num_rows) + rr_next)
                    % self.num_partitions
                ).astype(np.int32)
                rr_next = int(
                    (rr_next + cb.num_rows) % self.num_partitions
                )
                order = np.argsort(pids, kind="stable")
                rb_sorted = take_batch(
                    cb, jnp.asarray(np.concatenate(
                        [order,
                         np.arange(cb.num_rows, cb.capacity)])),
                    cb.num_rows,
                ).to_arrow()
                sorted_pids = pids[order]
            else:
                pids = spark_partition_ids(
                    aug, exprs, self.num_partitions
                )
                # scatter = one stable device argsort by partition id
                pid_full = jnp.full(
                    cb.capacity, self.num_partitions, dtype=jnp.int32
                )
                pid_full = pid_full.at[: len(pids)].set(
                    jnp.asarray(pids)
                )
                order_dev = jnp.argsort(pid_full, stable=True)
                rb_sorted = take_batch(
                    cb, order_dev, cb.num_rows
                ).to_arrow()
                sorted_pids = np.sort(pids, kind="stable")
            counts = np.bincount(
                sorted_pids, minlength=self.num_partitions
            )
            start = 0
            for p in range(self.num_partitions):
                c = int(counts[p])
                if c == 0:
                    continue
                part_rb = rb_sorted.slice(start, c)
                bufs.append(
                    p,
                    encode_ipc_segment(
                        part_rb, cfg.ipc_compression_level
                    ),
                )
                start += c
            ctx.metrics.add("shuffle_rows_written", cb.num_rows)
        lengths = bufs.finalize(self.data_file, self.index_file)
        ctx.metrics.add("shuffle_bytes_written", sum(lengths))
        return iter(())
