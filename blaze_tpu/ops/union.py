"""Union: append children's partitions (reference: DataFusion UnionExec,
from_proto.rs:429-436; wrapper NativeUnionExec.scala remaps child
partitions the same way)."""

from __future__ import annotations

from typing import Iterator, List

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import ExecContext, PhysicalOp


class UnionExec(PhysicalOp):
    def __init__(self, children: List[PhysicalOp]):
        assert children
        self.children = list(children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return sum(c.partition_count for c in self.children)

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return ""

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        for child in self.children:
            n = child.partition_count
            if partition < n:
                for b in child.execute(partition, ctx):
                    # positional union: rename to the union schema
                    yield ColumnBatch(
                        self.schema, b.columns, b.num_rows, b.selection
                    )
                return
            partition -= n
        raise IndexError("partition out of range")


class CoalescePartitionsExec(PhysicalOp):
    """Merge every child partition into one (Spark CoalescePartitionsExec;
    the planner plants it below single-partition operators - e.g. a
    COMPLETE aggregate or global sort - when no exchange re-partitions
    the stream first)."""

    def __init__(self, child: PhysicalOp):
        self.children = [child]

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return 1

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return ""

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if partition != 0:
            raise IndexError("partition out of range")
        child = self.children[0]
        for p in range(child.partition_count):
            yield from child.execute(p, ctx)
