"""Native (device) window functions.

The reference never offloads Window - it plants a row barrier and leaves it
to the JVM (BlazeConverters.scala:93-107). Here the sort-based machinery
that powers the aggregate makes the common window functions cheap on
device, so this operator EXCEEDS reference capability while staying
TPU-first: one stable sort by (partition keys, order keys), segment ids by
boundary detection, then each function is a few vectorized passes
(cumulative counts, run boundaries, segment reductions, guarded shifts,
partition-reset prefix scans for frames).

Supported: row_number, rank, dense_rank, ntile(n), percent_rank,
cume_dist, lag/lead(offset k), and sum/min/max/count/avg over
- the whole partition (frame=None),
- ROWS BETWEEN a PRECEDING AND b FOLLOWING (("rows", lo, hi); None =
  UNBOUNDED; bounded min/max ride a sparse-table RMQ over the sorted
  runs, so any lo/hi combination is supported),
- RANGE UNBOUNDED PRECEDING .. CURRENT ROW (("range", None, 0) - the
  SQL default frame with ORDER BY; ties share the frame result),
- RANGE BETWEEN x PRECEDING AND y FOLLOWING value offsets over a
  single numeric order key (("range", lo, hi): frame bounds located
  by searchsorted over the packed order keys).
Rows are emitted in (partition, order) sorted order - the order Spark's
WindowExec produces.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.types import DataType, Field, Schema
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.sort import SortKey, sort_batch
from blaze_tpu.ops.util import concat_batches

_RANKING = ("row_number", "rank", "dense_rank", "ntile",
            "percent_rank", "cume_dist")
_FRAME_AGGS = ("sum", "min", "max", "count", "avg")


@dataclasses.dataclass(frozen=True)
class WindowFn:
    kind: str  # ranking | lag | lead | frame aggs
    source: Optional[ir.Expr]  # for lag/lead/aggs
    output: str
    # lag/lead distance, or ntile bucket count
    offset: int = 1
    # None = whole partition; ("rows", lo, hi) with None = UNBOUNDED;
    # ("range", None, 0) = RANGE UNBOUNDED..CURRENT (ties share)
    frame: Optional[tuple] = None


def _whole_partition_agg(kind, v, contrib, gid, cap):
    """sum/min/max/count/avg over the entire partition (frame=None)."""
    if kind == "count":
        red = jax.ops.segment_sum(
            contrib.astype(jnp.int64), gid, num_segments=cap
        )
        return jnp.take(red, gid), None
    if kind in ("sum", "avg"):
        acc = jnp.where(contrib, v, jnp.zeros_like(v))
        if jnp.issubdtype(v.dtype, jnp.integer):
            acc = acc.astype(jnp.int64)
        s = jax.ops.segment_sum(acc, gid, num_segments=cap)
        c = jax.ops.segment_sum(
            contrib.astype(jnp.int64), gid, num_segments=cap
        )
        anyv = jnp.take(c, gid) > 0
        if kind == "sum":
            return jnp.take(s, gid), anyv
        return (
            jnp.take(s, gid).astype(jnp.float64)
            / jnp.maximum(jnp.take(c, gid), 1).astype(jnp.float64),
            anyv,
        )
    if jnp.issubdtype(v.dtype, jnp.floating):
        neutral = jnp.inf if kind == "min" else -jnp.inf
    else:
        info = jnp.iinfo(v.dtype)
        neutral = info.max if kind == "min" else info.min
    acc = jnp.where(contrib, v, jnp.asarray(neutral, v.dtype))
    red = (
        jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    )(acc, gid, num_segments=cap)
    c = jax.ops.segment_sum(
        contrib.astype(jnp.int32), gid, num_segments=cap
    )
    return jnp.take(red, gid), jnp.take(c, gid) > 0


class WindowExec(PhysicalOp):
    def __init__(self, child: PhysicalOp,
                 partition_by: Sequence[ir.Expr],
                 order_by: Sequence[SortKey],
                 functions: Sequence[WindowFn]):
        self.children = [child]
        schema = child.schema
        # window-input schema: fixed here even when the fusion pass later
        # rebases children[0] to the chain leaf (planner/fuse folds a
        # Project/Rename chain into this operator's kernel)
        self._in_schema = schema
        self._fused_pipeline = None
        # (partition, order)-spec sort permutations cached across
        # executions keyed on input buffer identity - the window analog
        # of the join build-index cache (joins._ensure_index): repeated
        # queries over the same staged table skip the argsort entirely
        self._sort_cache = {}
        self.partition_by = [bind_opt(e, schema) for e in partition_by]
        self.order_by = [
            SortKey(bind_opt(k.expr, schema), k.ascending, k.nulls_first)
            for k in order_by
        ]
        self.functions = [
            WindowFn(
                f.kind,
                bind_opt(f.source, schema)
                if f.source is not None else None,
                f.output,
                f.offset,
                f.frame,
            )
            for f in functions
        ]
        for f in self.functions:
            if f.kind in ("lag", "lead") and f.offset < 0:
                raise NotImplementedError(
                    f"negative {f.kind} offset (use the mirror fn)"
                )
            if f.kind == "ntile" and f.offset < 1:
                # SQL: NTILE(n) requires n >= 1
                raise NotImplementedError("ntile bucket count must be >= 1")
            fr = f.frame
            if fr is None:
                continue
            ftype, lo, hi = fr
            if ftype == "range":
                if lo is None and hi == 0:
                    pass  # SQL default frame: UNBOUNDED..CURRENT (ties)
                else:
                    # RANGE with VALUE offsets: exactly one numeric
                    # order key narrow enough for the u32 order
                    # encoding the bound search packs (round 4;
                    # int64/f64 order keys stay host-tier work)
                    if len(self.order_by) != 1:
                        raise NotImplementedError(
                            "RANGE value offsets need exactly one "
                            "ORDER BY key"
                        )
                    odt = infer_dtype(self.order_by[0].expr, schema)
                    narrow = (
                        odt.id.value in ("int8", "int16", "int32",
                                         "date32", "float32")
                        or (odt.is_integer
                            and odt.physical_dtype().itemsize <= 4)
                    )
                    if not narrow:
                        raise NotImplementedError(
                            "RANGE value offsets over wide order "
                            "keys are host-tier work"
                        )
                    for off in (lo, hi):
                        if off is not None and off < 0:
                            raise NotImplementedError(
                                "negative RANGE offset"
                            )
            elif ftype == "rows":
                if lo is not None and lo < 0:
                    raise NotImplementedError("negative frame lo")
                if hi is not None and hi < 0:
                    raise NotImplementedError("negative frame hi")
            else:
                raise NotImplementedError(f"frame type {ftype}")
        for e in self.partition_by + [k.expr for k in self.order_by] + [
            f.source for f in self.functions if f.source is not None
        ]:
            if infer_dtype(e, schema).is_wide_decimal:
                raise NotImplementedError(
                    "window over decimal(>18) is host-tier work"
                )
        out_fields = list(schema.fields)
        for f in self.functions:
            out_fields.append(
                Field(f.output, self._fn_dtype(f, schema), True)
            )
        self._schema = Schema(out_fields)

    @staticmethod
    def _fn_dtype(f: WindowFn, schema: Schema) -> DataType:
        if f.kind in ("percent_rank", "cume_dist"):
            return DataType.float64()
        if f.kind in _RANKING or f.kind == "count":
            return DataType.int64()
        if f.kind in ("lag", "lead"):
            return infer_dtype(f.source, schema)
        if f.kind == "avg":
            return DataType.float64()
        st = infer_dtype(f.source, schema)
        if f.kind == "sum" and st.is_integer:
            return DataType.int64()
        return st

    @property
    def schema(self) -> Schema:
        return self._schema

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return (f"p={self.partition_by!r};o={self.order_by!r};"
                f"f={self.functions!r}")

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        keys = [
            SortKey(e, True, True) for e in self.partition_by
        ] + list(self.order_by)
        pipe = self._fused_pipeline
        if self._sort_fusable(keys):
            # whole-task fusion: ONE kernel evaluates any folded stage
            # chain, computes the shared argsort, gathers every column,
            # and runs all frame passes - no materialized sorted
            # intermediate, no per-column eager gather dispatches (the
            # sort_batch/take_batch path), and every function shares the
            # one (partition, order) argsort
            src = self.children[0]  # the chain leaf when pipe is folded
            cb = concat_batches(
                list(src.execute(partition, ctx)), schema=src.schema,
            )
            if cb.num_rows == 0:
                return
            yield self._apply_fused(cb, keys, pipe)
            return
        if pipe is not None:
            # host-tier sort keys: run the folded chain as a plain
            # operator (children[0] may be an instrumented wrapper)
            pipe.children = list(self.children)
            src = pipe
        else:
            src = self.children[0]
        cb = concat_batches(
            list(src.execute(partition, ctx)), schema=self._in_schema,
        )
        if cb.num_rows == 0:
            return
        cb = sort_batch(cb, keys)
        yield self._apply(cb)

    def _sort_fusable(self, keys) -> bool:
        """True when the sort needs no host tier: dictionary-encoded
        (string) keys must remap codes to lexicographic ranks on the
        host, so they keep the classic sort_batch path."""
        for k in keys:
            if infer_dtype(k.expr, self._in_schema).is_dictionary_encoded:
                return False
        return True

    # ------------------------------------------------------------------
    def _apply(self, cb: ColumnBatch) -> ColumnBatch:
        from blaze_tpu.runtime.dispatch import cached_kernel

        key = ("window", tuple(self.partition_by),
               tuple((k.expr, k.ascending, k.nulls_first)
                     for k in self.order_by),
               tuple((f.kind, f.source, f.offset, f.frame)
                     for f in self.functions),
               cb.layout())
        fn = cached_kernel(key, lambda: self._build_kernel(cb.layout()))
        outs = fn(cb.device_buffers(), cb.num_rows)
        cols = list(cb.columns)
        for f, (v, m) in zip(self.functions, outs):
            dt = self._fn_dtype(f, self._in_schema)
            cols.append(Column(dt, v, m, None))
        return ColumnBatch(self._schema, cols, cb.num_rows)

    def _cached_sort_idx(self, bufs, num_rows):
        """Device sort permutation cached on input-buffer identity (jax
        arrays are immutable, so identical buffers imply an identical
        permutation for this operator's fixed (partition, order) spec).
        Returns the cached idx array or None."""
        import weakref

        key = (tuple(id(b) for b in bufs), num_rows)
        hit = self._sort_cache.get(key)
        if hit is None:
            return None
        refs, idx = hit
        if all(r() is b for r, b in zip(refs, bufs)):
            return idx
        self._sort_cache.pop(key, None)
        return None

    def _store_sort_idx(self, bufs, num_rows, idx) -> None:
        import weakref

        try:
            refs = tuple(weakref.ref(b) for b in bufs)
        except TypeError:
            return
        key = (tuple(id(b) for b in bufs), num_rows)
        self._sort_cache[key] = (refs, idx)
        while len(self._sort_cache) > 2:  # tiny LRU: HBM is precious
            self._sort_cache.pop(next(iter(self._sort_cache)))

    def _apply_fused(self, cb: ColumnBatch, keys, pipe) -> ColumnBatch:
        from blaze_tpu.config import get_config, resolve_core_choice
        from blaze_tpu.runtime.dispatch import cached_kernel

        # the in-kernel argsort reads the sort-core knob at trace time
        core = resolve_core_choice(
            "BLAZE_SORT_CORE", get_config().sort_core
        )
        layout = cb.layout()
        bufs = cb.device_buffers()
        base = ("window_fused",
                pipe.structure_key() if pipe is not None else None,
                tuple(self.partition_by),
                tuple((k.expr, k.ascending, k.nulls_first)
                      for k in self.order_by),
                tuple((f.kind, f.source, f.offset, f.frame)
                      for f in self.functions),
                layout, core)
        idx = self._cached_sort_idx(bufs, cb.num_rows)
        if idx is None:
            fn = cached_kernel(
                base + ("sort",),
                lambda: self._build_fused_kernel(
                    layout, keys, pipe, with_idx=False
                ),
            )
            idx, sorted_bufs, outs = fn(bufs, cb.num_rows)
            self._store_sort_idx(bufs, cb.num_rows, idx)
        else:
            fn = cached_kernel(
                base + ("reuse",),
                lambda: self._build_fused_kernel(
                    layout, keys, pipe, with_idx=True
                ),
            )
            sorted_bufs, outs = fn(bufs, cb.num_rows, idx)
        cols: List[Column] = []
        it = iter(sorted_bufs)
        if pipe is not None:
            dicts = pipe._out_dictionaries(cb)
            for field, d in zip(self._in_schema, dicts):
                cols.append(Column(field.dtype, next(it), next(it), d))
        else:
            for c in cb.columns:
                v = next(it)
                m = next(it) if c.validity is not None else None
                cols.append(Column(c.dtype, v, m, c.dictionary))
        for f, (v, m) in zip(self.functions, outs):
            dt = self._fn_dtype(f, self._in_schema)
            cols.append(Column(dt, v, m, None))
        return ColumnBatch(self._schema, cols, cb.num_rows)

    def _fused_body(self, layout, keys, pipe):
        """Traceable core shared by the fused-window kernel and the
        window+aggregate whole-task fusion (ops/fused.
        FusedWindowAggExec): [folded stage chain +] shared argsort +
        gather + every frame pass. Returns `body(bufs, num_rows, idx)`
        -> `(idx, mid_layout, sorted_bufs, outs)`; pass `idx=None` to
        compute the sort in-kernel, or a cached permutation to skip
        it."""
        from blaze_tpu.ops.project import _unflatten_cvs
        from blaze_tpu.ops.util import sort_indices

        schema = self._in_schema
        if pipe is not None:
            pipe_kernel = pipe._build_kernel(layout)
            mid_layout = (
                layout[0],
                tuple(
                    (f.dtype.id.value, f.dtype.precision,
                     f.dtype.scale, True)
                    for f in schema
                ),
            )
        else:
            pipe_kernel = None
            mid_layout = layout
        inner = self._build_kernel(mid_layout)

        def body(bufs, num_rows, idx):
            if pipe_kernel is not None:
                bufs, _sel = pipe_kernel(bufs, None)
            cols = _unflatten_cvs(mid_layout, bufs)
            cap = mid_layout[0]
            ev = DeviceEvaluator(schema, cols, cap)
            key_cols = []
            for k in keys:
                v, m = ev.evaluate(k.expr)
                key_cols.append((v, m, k.ascending, k.nulls_first))
            if idx is None:
                idx = sort_indices(key_cols, num_rows, cap)
            sorted_bufs = [jnp.take(b, idx, axis=0) for b in bufs]
            return idx, sorted_bufs, inner(sorted_bufs, num_rows)

        return body, mid_layout

    def _build_fused_kernel(self, layout, keys, pipe, with_idx: bool):
        """[folded stage chain +] argsort + gather + every window
        function in one program. `with_idx` builds the permutation-reuse
        variant (takes the cached idx instead of sorting)."""
        body, _mid = self._fused_body(layout, keys, pipe)

        if with_idx:
            def kernel(bufs, num_rows, idx):
                _, sorted_bufs, outs = body(bufs, num_rows, idx)
                return sorted_bufs, outs

            return kernel

        def kernel(bufs, num_rows):
            return body(bufs, num_rows, None)

        return kernel

    def _build_kernel(self, layout):
        from blaze_tpu.ops.project import _unflatten_cvs

        schema = self._in_schema
        part_exprs = self.partition_by
        order_exprs = [k.expr for k in self.order_by]
        order_keys = self.order_by
        fns = self.functions

        def kernel(bufs, num_rows):
            cols = _unflatten_cvs(layout, bufs)
            cap = layout[0]
            ev = DeviceEvaluator(schema, cols, cap)
            live = jnp.arange(cap, dtype=jnp.int32) < num_rows
            pos = jnp.arange(cap, dtype=jnp.int32)

            def boundaries(exprs):
                b = jnp.zeros(cap, dtype=jnp.bool_)
                for e in exprs:
                    v, m = ev.evaluate(e)
                    if jnp.issubdtype(v.dtype, jnp.floating):
                        # NaN partitions/runs group together (Spark
                        # normalizes NaN), distinct from real +inf
                        nan = jnp.isnan(v)
                        nanp = jnp.concatenate([nan[:1], nan[:-1]])
                        v = jnp.where(nan, jnp.inf, v)
                        extra = nan != nanp
                    else:
                        extra = jnp.zeros(cap, dtype=jnp.bool_)
                    prev = jnp.concatenate([v[:1], v[:-1]])
                    neq = (v != prev) | extra
                    if m is not None:
                        pm = jnp.concatenate([m[:1], m[:-1]])
                        neq = jnp.where(m & pm, neq, m != pm)
                    b = b | neq
                return b

            first_live = live & ~jnp.concatenate(
                [jnp.zeros(1, dtype=jnp.bool_), live[:-1]]
            )
            pb = (boundaries(part_exprs) | first_live) & live
            gid = jnp.cumsum(pb.astype(jnp.int32)) - 1
            gid = jnp.where(live, gid, cap - 1)
            # start position of each row's partition
            seg_start = jnp.take(
                jnp.nonzero(pb, size=cap, fill_value=0)[0], gid
            )
            # partition sizes + end position (exclusive)
            seg_count = jax.ops.segment_sum(
                live.astype(jnp.int64), gid, num_segments=cap
            )
            size = jnp.take(seg_count, gid)
            seg_end = seg_start + size.astype(jnp.int32)
            rn = (pos - seg_start + 1).astype(jnp.int64)
            # value-run boundaries within partitions (rank/dense_rank/
            # cume_dist/range frames)
            vb = (boundaries(order_exprs) | pb) & live
            run_id = jnp.cumsum(vb.astype(jnp.int32)) - 1
            run_start = jnp.take(
                jnp.nonzero(vb, size=cap, fill_value=0)[0], run_id
            )
            run_count = jax.ops.segment_sum(
                live.astype(jnp.int32), run_id, num_segments=cap
            )
            run_end = run_start + jnp.take(run_count, run_id)  # excl

            def part_prefix(x):
                """Inclusive prefix sums reset at partition starts."""
                g = jnp.cumsum(x, axis=0)
                gshift = jnp.concatenate(
                    [jnp.zeros_like(g[:1]), g[:-1]]
                )
                return g - jnp.take(gshift, seg_start)

            def rows_frame_idx(lo, hi):
                """ROWS-offset frame -> explicit clamped index spans
                (None = unbounded to the partition edge)."""
                lo_idx = (
                    seg_start if lo is None
                    else jnp.maximum(pos - lo, seg_start)
                )
                hi_idx = (
                    seg_end - 1 if hi is None
                    else jnp.minimum(pos + hi, seg_end - 1)
                )
                return lo_idx, hi_idx

            def frame_agg_sumlike(vals64, contrib, lo, hi):
                """SUM over ROWS frame [i-lo, i+hi] clamped to the
                partition (None = unbounded); also used for counts.
                Thin wrapper over agg_over so the span-sum logic lives
                once."""
                if lo is None and hi == 0:
                    # running frame: the partition-reset prefix sums ARE
                    # the per-row results - skip agg_over's span gathers
                    # (take(S, pos) is an 8M-row gather XLA won't
                    # simplify away)
                    x = jnp.where(
                        contrib, vals64, jnp.zeros_like(vals64)
                    )
                    return part_prefix(x)
                lo_idx, hi_idx = rows_frame_idx(lo, hi)
                return agg_over(vals64, contrib, lo_idx, hi_idx)

            def running_minmax(v, contrib, is_min):
                """Partition-reset running min/max via associative scan."""
                if jnp.issubdtype(v.dtype, jnp.floating):
                    neutral = jnp.inf if is_min else -jnp.inf
                else:
                    info = jnp.iinfo(v.dtype)
                    neutral = info.max if is_min else info.min
                x = jnp.where(contrib, v, jnp.asarray(neutral, v.dtype))

                def op(a, b):
                    fa, va = a
                    fb, vb_ = b
                    red = (
                        jnp.minimum(va, vb_) if is_min
                        else jnp.maximum(va, vb_)
                    )
                    return fa | fb, jnp.where(fb, vb_, red)

                _, out = jax.lax.associative_scan(op, (pb, x))
                return out

            def agg_over(vals64, contrib, lo_idx, hi_idx):
                """SUM of vals64 over explicit row spans [lo_idx,
                hi_idx] (partition-clamped by the caller); empty spans
                (hi < lo) contribute zero."""
                x = jnp.where(contrib, vals64, jnp.zeros_like(vals64))
                S = part_prefix(x)
                hi_c = jnp.clip(hi_idx, 0, cap - 1)
                s_hi = jnp.take(S, hi_c)
                s_lo_prev = jnp.where(
                    lo_idx > seg_start,
                    jnp.take(S, jnp.clip(lo_idx - 1, 0, cap - 1)),
                    jnp.zeros_like(s_hi),
                )
                return jnp.where(
                    hi_idx >= lo_idx, s_hi - s_lo_prev,
                    jnp.zeros_like(s_hi),
                )

            def rmq(v, contrib, lo_idx, hi_idx, is_min,
                    max_len=None):
                """min/max over explicit spans via a sparse table:
                doubling passes up to log2(max frame length), then per
                level a masked combine of the two power-of-two covers
                (classic RMQ). No (K, cap) stack materializes - each
                level is consumed as it's built - and bounded ROWS
                frames pass max_len so only log2(w) levels exist at
                all. Empty spans return the neutral (caller masks by
                count)."""
                if jnp.issubdtype(v.dtype, jnp.floating):
                    neutral = jnp.asarray(
                        jnp.inf if is_min else -jnp.inf, v.dtype
                    )
                else:
                    info = jnp.iinfo(v.dtype)
                    neutral = jnp.asarray(
                        info.max if is_min else info.min, v.dtype
                    )
                x = jnp.where(contrib, v, neutral)
                red = jnp.minimum if is_min else jnp.maximum
                length = jnp.maximum(hi_idx - lo_idx + 1, 1)
                k = (
                    jnp.int32(31)
                    - jax.lax.clz(length.astype(jnp.int32))
                )
                pow2 = jnp.int32(1) << k
                left = jnp.clip(lo_idx, 0, cap - 1)
                right = jnp.clip(hi_idx - pow2 + 1, 0, cap - 1)
                bound = min(max_len or cap, cap)
                out = jnp.full(cap, neutral, v.dtype)
                level = x
                span = 1
                j = 0
                while True:
                    sel = k == j
                    out = jnp.where(
                        sel,
                        red(jnp.take(level, left),
                            jnp.take(level, right)),
                        out,
                    )
                    if span >= bound:
                        break
                    shifted = jnp.concatenate(
                        [level[span:],
                         jnp.full((span,), neutral, v.dtype)]
                    )
                    level = red(level, shifted)
                    span <<= 1
                    j += 1
                return jnp.where(hi_idx >= lo_idx, out, neutral)

            def range_value_bounds(lo_off, hi_off):
                """Frame spans for RANGE with VALUE offsets: rows are
                sorted by (partition, null-rank, order value), so each
                bound is one searchsorted over
                (gid:31 | null-rank:1 | order-key:32) packed u64 keys.
                Without the null-rank bit, a NULL row's arbitrary
                payload would break key monotonicity and corrupt the
                binary search for every row in its partition.
                NULL-ordered rows themselves use their tie run (SQL:
                a null frame is its null peers)."""
                from blaze_tpu.ops.util import _order_key_u32

                sk = order_keys[0]
                ov, om = ev.evaluate(sk.expr)
                asc = sk.ascending
                if om is None:
                    null_rank = jnp.zeros(cap, dtype=jnp.uint64)
                else:
                    # physical order: nulls_first sorts nulls before
                    # values, nulls_last after
                    valid_rank = (
                        jnp.uint64(1) if sk.nulls_first
                        else jnp.uint64(0)
                    )
                    null_rank = jnp.where(
                        om, valid_rank, valid_rank ^ jnp.uint64(1)
                    )

                def packed(values):
                    enc = _order_key_u32(values, asc)
                    return (
                        (gid.astype(jnp.uint64) << jnp.uint64(33))
                        | (null_rank << jnp.uint64(32))
                        | enc.astype(jnp.uint64)
                    )

                def bound_val(off, toward_hi):
                    # bound arithmetic in a WIDER domain so it cannot
                    # wrap: int keys compute in int64 then saturate to
                    # the key dtype's range (saturation preserves the
                    # span: every stored value is in-range); float
                    # keys saturate naturally to +/-inf
                    plus = toward_hi == asc
                    if jnp.issubdtype(ov.dtype, jnp.floating):
                        d = jnp.asarray(off, ov.dtype)
                        return ov + d if plus else ov - d
                    w = ov.astype(jnp.int64)
                    d = jnp.asarray(int(off), jnp.int64)
                    b = w + d if plus else w - d
                    info = jnp.iinfo(ov.dtype)
                    return jnp.clip(b, info.min, info.max).astype(
                        ov.dtype
                    )

                keys_sorted = packed(ov)
                if lo_off is None:
                    lo_idx = seg_start
                else:
                    lo_idx = jnp.searchsorted(
                        keys_sorted,
                        packed(bound_val(lo_off, toward_hi=False)),
                        side="left",
                    ).astype(jnp.int32)
                if hi_off is None:
                    hi_idx = seg_end - 1
                else:
                    hi_idx = (
                        jnp.searchsorted(
                            keys_sorted,
                            packed(bound_val(hi_off, toward_hi=True)),
                            side="right",
                        ).astype(jnp.int32)
                        - 1
                    )
                if om is not None:
                    # null order values: an OFFSET bound collapses to
                    # the null peer run's edge (offsets are undefined
                    # on null); an UNBOUNDED side still reaches the
                    # partition edge
                    if lo_off is not None:
                        lo_idx = jnp.where(
                            om, lo_idx, run_start.astype(jnp.int32)
                        )
                    if hi_off is not None:
                        hi_idx = jnp.where(
                            om, hi_idx, (run_end - 1).astype(jnp.int32)
                        )
                return lo_idx, hi_idx

            frame_bounds_cache = {}

            def cached_range_bounds(lo, hi):
                """One key-pack + two searchsorted per DISTINCT frame,
                however many functions share it."""
                key = (lo, hi)
                if key not in frame_bounds_cache:
                    frame_bounds_cache[key] = range_value_bounds(
                        lo, hi
                    )
                return frame_bounds_cache[key]

            outs = []
            for f in fns:
                if f.kind == "row_number":
                    outs.append((rn, None))
                elif f.kind == "rank":
                    outs.append(
                        ((run_start - seg_start + 1).astype(jnp.int64),
                         None)
                    )
                elif f.kind == "dense_rank":
                    dr = jnp.cumsum(vb.astype(jnp.int64))
                    seg_dr = jnp.take(dr, seg_start)
                    outs.append((dr - seg_dr + 1, None))
                elif f.kind == "ntile":
                    nt = int(f.offset)  # >= 1, validated at init
                    base = size // nt
                    rem = size % nt
                    cutoff = rem * (base + 1)
                    tile = jnp.where(
                        rn <= cutoff,
                        (rn - 1) // jnp.maximum(base + 1, 1),
                        rem + (rn - 1 - cutoff)
                        // jnp.maximum(base, 1),
                    )
                    outs.append(((tile + 1).astype(jnp.int64), None))
                elif f.kind == "percent_rank":
                    rk = (run_start - seg_start + 1).astype(jnp.float64)
                    pr = jnp.where(
                        size > 1,
                        (rk - 1.0)
                        / jnp.maximum(size - 1, 1).astype(jnp.float64),
                        0.0,
                    )
                    outs.append((pr, None))
                elif f.kind == "cume_dist":
                    cd = (run_end - seg_start).astype(jnp.float64) \
                        / jnp.maximum(size, 1).astype(jnp.float64)
                    outs.append((cd, None))
                elif f.kind in ("lag", "lead"):
                    v, m = ev.evaluate(f.source)
                    k = int(f.offset)
                    if k == 0:  # Spark lag/lead(v, 0) = current row
                        valid = live if m is None else (live & m)
                        outs.append((v, valid))
                        continue
                    if f.kind == "lag":
                        sv = jnp.concatenate([v[:k], v[:-k]], axis=0)
                        sm = (
                            jnp.concatenate([m[:k], m[:-k]])
                            if m is not None else None
                        )
                        ok = rn > k
                    else:
                        sv = jnp.concatenate([v[k:], v[-k:]], axis=0)
                        sm = (
                            jnp.concatenate([m[k:], m[-k:]])
                            if m is not None else None
                        )
                        ok = rn <= size - k
                    valid = ok if sm is None else (ok & sm)
                    outs.append((sv, valid & live))
                else:  # frame aggregates
                    v, m = ev.evaluate(f.source)
                    contrib = live if m is None else (live & m)
                    frame = f.frame
                    if frame is None:
                        outs.append(
                            _whole_partition_agg(
                                f.kind, v, contrib, gid, cap
                            )
                        )
                        continue
                    ftype, lo, hi = frame
                    range_value = ftype == "range" and not (
                        lo is None and hi == 0
                    )
                    if f.kind in ("min", "max"):
                        is_min = f.kind == "min"
                        if ftype == "rows" and lo is None and hi == 0:
                            # running frame: the associative scan is
                            # one pass, cheaper than the sparse table
                            running = running_minmax(v, contrib, is_min)
                            cnt = frame_agg_sumlike(
                                contrib.astype(jnp.int64), live, lo, 0
                            )
                            outs.append((running, cnt > 0))
                            continue
                        if ftype == "range" and not range_value:
                            # RANGE UNBOUNDED..CURRENT: running value
                            # at the tie-run end
                            running = running_minmax(v, contrib, is_min)
                            cnt = frame_agg_sumlike(
                                contrib.astype(jnp.int64), live,
                                None, 0,
                            )
                            at = jnp.clip(run_end - 1, 0, cap - 1)
                            outs.append((
                                jnp.take(running, at),
                                jnp.take(cnt, at) > 0,
                            ))
                            continue
                        # bounded sliding (ROWS a PRECEDING..b
                        # FOLLOWING) or RANGE value offsets: explicit
                        # spans through the sparse-table RMQ
                        if range_value:
                            lo_idx, hi_idx = cached_range_bounds(
                                lo, hi
                            )
                            max_len = None
                        else:
                            lo_idx, hi_idx = rows_frame_idx(lo, hi)
                            max_len = (
                                int(lo) + int(hi) + 1
                                if lo is not None and hi is not None
                                else None
                            )
                        red = rmq(
                            v, contrib, lo_idx, hi_idx, is_min,
                            max_len=max_len,
                        )
                        cnt = agg_over(
                            contrib.astype(jnp.int64), live,
                            lo_idx, hi_idx,
                        )
                        outs.append((red, cnt > 0))
                        continue
                    vals = v
                    if jnp.issubdtype(v.dtype, jnp.integer):
                        vals = v.astype(jnp.int64)
                    if range_value:
                        lo_idx, hi_idx = cached_range_bounds(lo, hi)
                        s = agg_over(vals, contrib, lo_idx, hi_idx)
                        c = agg_over(
                            contrib.astype(jnp.int64), live,
                            lo_idx, hi_idx,
                        )
                    else:
                        s = frame_agg_sumlike(vals, contrib, lo, hi)
                        c = frame_agg_sumlike(
                            contrib.astype(jnp.int64), live, lo, hi
                        )
                        if ftype == "range":
                            # ties share the frame ending at the run end
                            at = jnp.clip(run_end - 1, 0, cap - 1)
                            s = jnp.take(s, at)
                            c = jnp.take(c, at)
                    anyv = c > 0
                    if f.kind == "count":
                        outs.append((c, None))
                    elif f.kind == "sum":
                        outs.append((s, anyv))
                    else:  # avg
                        outs.append(
                            (
                                s.astype(jnp.float64)
                                / jnp.maximum(c, 1).astype(jnp.float64),
                                anyv,
                            )
                        )
            return outs

        return kernel
