"""Native (device) window functions.

The reference never offloads Window - it plants a row barrier and leaves it
to the JVM (BlazeConverters.scala:93-107). Here the sort-based machinery
that powers the aggregate makes the common window functions cheap on
device, so this operator EXCEEDS reference capability while staying
TPU-first: one stable sort by (partition keys, order keys), segment ids by
boundary detection, then each function is a few vectorized passes
(cumulative counts, run boundaries, segment reductions, guarded shifts).

Supported: row_number, rank, dense_rank, lag, lead (offset 1),
sum/min/max/count/avg over the whole partition frame. Rows are emitted in
(partition, order) sorted order - the order Spark's WindowExec produces.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.types import DataType, Field, Schema
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.sort import SortKey, sort_batch
from blaze_tpu.ops.util import concat_batches

_RANKING = ("row_number", "rank", "dense_rank")
_FRAME_AGGS = ("sum", "min", "max", "count", "avg")


@dataclasses.dataclass(frozen=True)
class WindowFn:
    kind: str  # row_number | rank | dense_rank | lag | lead | frame aggs
    source: Optional[ir.Expr]  # for lag/lead/aggs
    output: str


class WindowExec(PhysicalOp):
    def __init__(self, child: PhysicalOp,
                 partition_by: Sequence[ir.Expr],
                 order_by: Sequence[SortKey],
                 functions: Sequence[WindowFn]):
        self.children = [child]
        schema = child.schema
        self.partition_by = [bind_opt(e, schema) for e in partition_by]
        self.order_by = [
            SortKey(bind_opt(k.expr, schema), k.ascending, k.nulls_first)
            for k in order_by
        ]
        self.functions = [
            WindowFn(
                f.kind,
                bind_opt(f.source, schema)
                if f.source is not None else None,
                f.output,
            )
            for f in functions
        ]
        for e in self.partition_by + [k.expr for k in self.order_by] + [
            f.source for f in self.functions if f.source is not None
        ]:
            if infer_dtype(e, schema).is_wide_decimal:
                raise NotImplementedError(
                    "window over decimal(>18) is host-tier work"
                )
        out_fields = list(schema.fields)
        for f in self.functions:
            out_fields.append(
                Field(f.output, self._fn_dtype(f, schema), True)
            )
        self._schema = Schema(out_fields)

    @staticmethod
    def _fn_dtype(f: WindowFn, schema: Schema) -> DataType:
        if f.kind in _RANKING or f.kind == "count":
            return DataType.int64()
        if f.kind in ("lag", "lead"):
            return infer_dtype(f.source, schema)
        if f.kind == "avg":
            return DataType.float64()
        st = infer_dtype(f.source, schema)
        if f.kind == "sum" and st.is_integer:
            return DataType.int64()
        return st

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        cb = concat_batches(
            list(self.children[0].execute(partition, ctx)),
            schema=self.children[0].schema,
        )
        if cb.num_rows == 0:
            return
        keys = [
            SortKey(e, True, True) for e in self.partition_by
        ] + list(self.order_by)
        cb = sort_batch(cb, keys)
        yield self._apply(cb)

    # ------------------------------------------------------------------
    def _apply(self, cb: ColumnBatch) -> ColumnBatch:
        from blaze_tpu.runtime.dispatch import cached_kernel

        key = ("window", tuple(self.partition_by),
               tuple((k.expr, k.ascending, k.nulls_first)
                     for k in self.order_by),
               tuple((f.kind, f.source) for f in self.functions),
               cb.layout())
        fn = cached_kernel(key, lambda: self._build_kernel(cb.layout()))
        outs = fn(cb.device_buffers(), cb.num_rows)
        cols = list(cb.columns)
        for f, (v, m) in zip(self.functions, outs):
            dt = self._fn_dtype(f, self.children[0].schema)
            cols.append(Column(dt, v, m, None))
        return ColumnBatch(self._schema, cols, cb.num_rows)

    def _build_kernel(self, layout):
        from blaze_tpu.ops.project import _unflatten_cvs

        schema = self.children[0].schema
        part_exprs = self.partition_by
        order_exprs = [k.expr for k in self.order_by]
        fns = self.functions

        def kernel(bufs, num_rows):
            cols = _unflatten_cvs(layout, bufs)
            cap = layout[0]
            ev = DeviceEvaluator(schema, cols, cap)
            live = jnp.arange(cap, dtype=jnp.int32) < num_rows
            pos = jnp.arange(cap, dtype=jnp.int32)

            def boundaries(exprs):
                b = jnp.zeros(cap, dtype=jnp.bool_)
                for e in exprs:
                    v, m = ev.evaluate(e)
                    if jnp.issubdtype(v.dtype, jnp.floating):
                        # NaN partitions/runs group together (Spark
                        # normalizes NaN), distinct from real +inf
                        nan = jnp.isnan(v)
                        nanp = jnp.concatenate([nan[:1], nan[:-1]])
                        v = jnp.where(nan, jnp.inf, v)
                        extra = nan != nanp
                    else:
                        extra = jnp.zeros(cap, dtype=jnp.bool_)
                    prev = jnp.concatenate([v[:1], v[:-1]])
                    neq = (v != prev) | extra
                    if m is not None:
                        pm = jnp.concatenate([m[:1], m[:-1]])
                        neq = jnp.where(m & pm, neq, m != pm)
                    b = b | neq
                return b

            first_live = live & ~jnp.concatenate(
                [jnp.zeros(1, dtype=jnp.bool_), live[:-1]]
            )
            pb = (boundaries(part_exprs) | first_live) & live
            gid = jnp.cumsum(pb.astype(jnp.int32)) - 1
            gid = jnp.where(live, gid, cap - 1)
            # start position of each row's partition
            seg_start = jnp.take(
                jnp.nonzero(pb, size=cap, fill_value=0)[0], gid
            )
            # value-run boundaries within partitions (for rank/dense_rank)
            vb = (boundaries(order_exprs) | pb) & live
            run_start = jnp.take(
                jnp.nonzero(vb, size=cap, fill_value=0)[0],
                jnp.cumsum(vb.astype(jnp.int32)) - 1,
            )
            outs = []
            for f in fns:
                if f.kind == "row_number":
                    outs.append(
                        ((pos - seg_start + 1).astype(jnp.int64), None)
                    )
                elif f.kind == "rank":
                    outs.append(
                        ((run_start - seg_start + 1).astype(jnp.int64),
                         None)
                    )
                elif f.kind == "dense_rank":
                    dr = jnp.cumsum(vb.astype(jnp.int64))
                    seg_dr = jnp.take(dr, seg_start)
                    outs.append((dr - seg_dr + 1, None))
                elif f.kind in ("lag", "lead"):
                    v, m = ev.evaluate(f.source)
                    if f.kind == "lag":
                        sv = jnp.concatenate([v[:1], v[:-1]])
                        sm = (
                            jnp.concatenate([m[:1], m[:-1]])
                            if m is not None else None
                        )
                        ok = pos > seg_start
                    else:
                        sv = jnp.concatenate([v[1:], v[-1:]])
                        sm = (
                            jnp.concatenate([m[1:], m[-1:]])
                            if m is not None else None
                        )
                        nxt_pb = jnp.concatenate(
                            [pb[1:], jnp.ones(1, dtype=jnp.bool_)]
                        )
                        nxt_live = jnp.concatenate(
                            [live[1:], jnp.zeros(1, dtype=jnp.bool_)]
                        )
                        ok = ~nxt_pb & nxt_live
                    valid = ok if sm is None else (ok & sm)
                    outs.append((sv, valid & live))
                else:  # frame aggregates over the whole partition
                    v, m = ev.evaluate(f.source)
                    contrib = live if m is None else (live & m)
                    if f.kind == "count":
                        red = jax.ops.segment_sum(
                            contrib.astype(jnp.int64), gid,
                            num_segments=cap,
                        )
                        outs.append((jnp.take(red, gid), None))
                        continue
                    if f.kind in ("sum", "avg"):
                        acc = jnp.where(contrib, v, jnp.zeros_like(v))
                        if jnp.issubdtype(v.dtype, jnp.integer):
                            acc = acc.astype(jnp.int64)
                        s = jax.ops.segment_sum(
                            acc, gid, num_segments=cap
                        )
                        c = jax.ops.segment_sum(
                            contrib.astype(jnp.int64), gid,
                            num_segments=cap,
                        )
                        anyv = jnp.take(c, gid) > 0
                        if f.kind == "sum":
                            outs.append((jnp.take(s, gid), anyv))
                        else:
                            outs.append(
                                (
                                    jnp.take(s, gid).astype(jnp.float64)
                                    / jnp.maximum(
                                        jnp.take(c, gid), 1
                                    ).astype(jnp.float64),
                                    anyv,
                                )
                            )
                        continue
                    if jnp.issubdtype(v.dtype, jnp.floating):
                        neutral = (
                            jnp.inf if f.kind == "min" else -jnp.inf
                        )
                    else:
                        info = jnp.iinfo(v.dtype)
                        neutral = (
                            info.max if f.kind == "min" else info.min
                        )
                    acc = jnp.where(contrib, v,
                                    jnp.asarray(neutral, v.dtype))
                    red = (
                        jax.ops.segment_min
                        if f.kind == "min"
                        else jax.ops.segment_max
                    )(acc, gid, num_segments=cap)
                    c = jax.ops.segment_sum(
                        contrib.astype(jnp.int32), gid,
                        num_segments=cap,
                    )
                    outs.append(
                        (jnp.take(red, gid), jnp.take(c, gid) > 0)
                    )
            return outs

        return kernel
