"""Native (device) window functions.

The reference never offloads Window - it plants a row barrier and leaves it
to the JVM (BlazeConverters.scala:93-107). Here the sort-based machinery
that powers the aggregate makes the common window functions cheap on
device, so this operator EXCEEDS reference capability while staying
TPU-first: one stable sort by (partition keys, order keys), segment ids by
boundary detection, then each function is a few vectorized passes
(cumulative counts, run boundaries, segment reductions, guarded shifts,
partition-reset prefix scans for frames).

Supported: row_number, rank, dense_rank, ntile(n), percent_rank,
cume_dist, lag/lead(offset k), and sum/min/max/count/avg over
- the whole partition (frame=None),
- ROWS BETWEEN a PRECEDING AND b FOLLOWING (("rows", lo, hi); None =
  UNBOUNDED; min/max need lo=None i.e. a running frame),
- RANGE UNBOUNDED PRECEDING .. CURRENT ROW (("range", None, 0) - the
  SQL default frame with ORDER BY; ties share the frame result).
Rows are emitted in (partition, order) sorted order - the order Spark's
WindowExec produces.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.types import DataType, Field, Schema
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.sort import SortKey, sort_batch
from blaze_tpu.ops.util import concat_batches

_RANKING = ("row_number", "rank", "dense_rank", "ntile",
            "percent_rank", "cume_dist")
_FRAME_AGGS = ("sum", "min", "max", "count", "avg")


@dataclasses.dataclass(frozen=True)
class WindowFn:
    kind: str  # ranking | lag | lead | frame aggs
    source: Optional[ir.Expr]  # for lag/lead/aggs
    output: str
    # lag/lead distance, or ntile bucket count
    offset: int = 1
    # None = whole partition; ("rows", lo, hi) with None = UNBOUNDED;
    # ("range", None, 0) = RANGE UNBOUNDED..CURRENT (ties share)
    frame: Optional[tuple] = None


def _whole_partition_agg(kind, v, contrib, gid, cap):
    """sum/min/max/count/avg over the entire partition (frame=None)."""
    if kind == "count":
        red = jax.ops.segment_sum(
            contrib.astype(jnp.int64), gid, num_segments=cap
        )
        return jnp.take(red, gid), None
    if kind in ("sum", "avg"):
        acc = jnp.where(contrib, v, jnp.zeros_like(v))
        if jnp.issubdtype(v.dtype, jnp.integer):
            acc = acc.astype(jnp.int64)
        s = jax.ops.segment_sum(acc, gid, num_segments=cap)
        c = jax.ops.segment_sum(
            contrib.astype(jnp.int64), gid, num_segments=cap
        )
        anyv = jnp.take(c, gid) > 0
        if kind == "sum":
            return jnp.take(s, gid), anyv
        return (
            jnp.take(s, gid).astype(jnp.float64)
            / jnp.maximum(jnp.take(c, gid), 1).astype(jnp.float64),
            anyv,
        )
    if jnp.issubdtype(v.dtype, jnp.floating):
        neutral = jnp.inf if kind == "min" else -jnp.inf
    else:
        info = jnp.iinfo(v.dtype)
        neutral = info.max if kind == "min" else info.min
    acc = jnp.where(contrib, v, jnp.asarray(neutral, v.dtype))
    red = (
        jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    )(acc, gid, num_segments=cap)
    c = jax.ops.segment_sum(
        contrib.astype(jnp.int32), gid, num_segments=cap
    )
    return jnp.take(red, gid), jnp.take(c, gid) > 0


class WindowExec(PhysicalOp):
    def __init__(self, child: PhysicalOp,
                 partition_by: Sequence[ir.Expr],
                 order_by: Sequence[SortKey],
                 functions: Sequence[WindowFn]):
        self.children = [child]
        schema = child.schema
        self.partition_by = [bind_opt(e, schema) for e in partition_by]
        self.order_by = [
            SortKey(bind_opt(k.expr, schema), k.ascending, k.nulls_first)
            for k in order_by
        ]
        self.functions = [
            WindowFn(
                f.kind,
                bind_opt(f.source, schema)
                if f.source is not None else None,
                f.output,
                f.offset,
                f.frame,
            )
            for f in functions
        ]
        for f in self.functions:
            if f.kind in ("lag", "lead") and f.offset < 0:
                raise NotImplementedError(
                    f"negative {f.kind} offset (use the mirror fn)"
                )
            if f.kind == "ntile" and f.offset < 1:
                # SQL: NTILE(n) requires n >= 1
                raise NotImplementedError("ntile bucket count must be >= 1")
            fr = f.frame
            if fr is None:
                continue
            ftype, lo, hi = fr
            if ftype == "range":
                # only the SQL default frame (RANGE UNBOUNDED..CURRENT)
                if not (lo is None and hi == 0):
                    raise NotImplementedError(
                        "RANGE frames other than UNBOUNDED..CURRENT"
                    )
            elif ftype == "rows":
                if f.kind in ("min", "max"):
                    # bounded/following min/max needs a sparse-table
                    # pass; only the running frame is supported
                    if not (lo is None and hi == 0):
                        raise NotImplementedError(
                            "min/max ROWS frames other than "
                            "UNBOUNDED..CURRENT"
                        )
                else:
                    if lo is not None and lo < 0:
                        raise NotImplementedError("negative frame lo")
                    if hi is not None and hi < 0:
                        raise NotImplementedError("negative frame hi")
            else:
                raise NotImplementedError(f"frame type {ftype}")
        for e in self.partition_by + [k.expr for k in self.order_by] + [
            f.source for f in self.functions if f.source is not None
        ]:
            if infer_dtype(e, schema).is_wide_decimal:
                raise NotImplementedError(
                    "window over decimal(>18) is host-tier work"
                )
        out_fields = list(schema.fields)
        for f in self.functions:
            out_fields.append(
                Field(f.output, self._fn_dtype(f, schema), True)
            )
        self._schema = Schema(out_fields)

    @staticmethod
    def _fn_dtype(f: WindowFn, schema: Schema) -> DataType:
        if f.kind in ("percent_rank", "cume_dist"):
            return DataType.float64()
        if f.kind in _RANKING or f.kind == "count":
            return DataType.int64()
        if f.kind in ("lag", "lead"):
            return infer_dtype(f.source, schema)
        if f.kind == "avg":
            return DataType.float64()
        st = infer_dtype(f.source, schema)
        if f.kind == "sum" and st.is_integer:
            return DataType.int64()
        return st

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        cb = concat_batches(
            list(self.children[0].execute(partition, ctx)),
            schema=self.children[0].schema,
        )
        if cb.num_rows == 0:
            return
        keys = [
            SortKey(e, True, True) for e in self.partition_by
        ] + list(self.order_by)
        cb = sort_batch(cb, keys)
        yield self._apply(cb)

    # ------------------------------------------------------------------
    def _apply(self, cb: ColumnBatch) -> ColumnBatch:
        from blaze_tpu.runtime.dispatch import cached_kernel

        key = ("window", tuple(self.partition_by),
               tuple((k.expr, k.ascending, k.nulls_first)
                     for k in self.order_by),
               tuple((f.kind, f.source, f.offset, f.frame)
                     for f in self.functions),
               cb.layout())
        fn = cached_kernel(key, lambda: self._build_kernel(cb.layout()))
        outs = fn(cb.device_buffers(), cb.num_rows)
        cols = list(cb.columns)
        for f, (v, m) in zip(self.functions, outs):
            dt = self._fn_dtype(f, self.children[0].schema)
            cols.append(Column(dt, v, m, None))
        return ColumnBatch(self._schema, cols, cb.num_rows)

    def _build_kernel(self, layout):
        from blaze_tpu.ops.project import _unflatten_cvs

        schema = self.children[0].schema
        part_exprs = self.partition_by
        order_exprs = [k.expr for k in self.order_by]
        fns = self.functions

        def kernel(bufs, num_rows):
            cols = _unflatten_cvs(layout, bufs)
            cap = layout[0]
            ev = DeviceEvaluator(schema, cols, cap)
            live = jnp.arange(cap, dtype=jnp.int32) < num_rows
            pos = jnp.arange(cap, dtype=jnp.int32)

            def boundaries(exprs):
                b = jnp.zeros(cap, dtype=jnp.bool_)
                for e in exprs:
                    v, m = ev.evaluate(e)
                    if jnp.issubdtype(v.dtype, jnp.floating):
                        # NaN partitions/runs group together (Spark
                        # normalizes NaN), distinct from real +inf
                        nan = jnp.isnan(v)
                        nanp = jnp.concatenate([nan[:1], nan[:-1]])
                        v = jnp.where(nan, jnp.inf, v)
                        extra = nan != nanp
                    else:
                        extra = jnp.zeros(cap, dtype=jnp.bool_)
                    prev = jnp.concatenate([v[:1], v[:-1]])
                    neq = (v != prev) | extra
                    if m is not None:
                        pm = jnp.concatenate([m[:1], m[:-1]])
                        neq = jnp.where(m & pm, neq, m != pm)
                    b = b | neq
                return b

            first_live = live & ~jnp.concatenate(
                [jnp.zeros(1, dtype=jnp.bool_), live[:-1]]
            )
            pb = (boundaries(part_exprs) | first_live) & live
            gid = jnp.cumsum(pb.astype(jnp.int32)) - 1
            gid = jnp.where(live, gid, cap - 1)
            # start position of each row's partition
            seg_start = jnp.take(
                jnp.nonzero(pb, size=cap, fill_value=0)[0], gid
            )
            # partition sizes + end position (exclusive)
            seg_count = jax.ops.segment_sum(
                live.astype(jnp.int64), gid, num_segments=cap
            )
            size = jnp.take(seg_count, gid)
            seg_end = seg_start + size.astype(jnp.int32)
            rn = (pos - seg_start + 1).astype(jnp.int64)
            # value-run boundaries within partitions (rank/dense_rank/
            # cume_dist/range frames)
            vb = (boundaries(order_exprs) | pb) & live
            run_id = jnp.cumsum(vb.astype(jnp.int32)) - 1
            run_start = jnp.take(
                jnp.nonzero(vb, size=cap, fill_value=0)[0], run_id
            )
            run_count = jax.ops.segment_sum(
                live.astype(jnp.int32), run_id, num_segments=cap
            )
            run_end = run_start + jnp.take(run_count, run_id)  # excl

            def part_prefix(x):
                """Inclusive prefix sums reset at partition starts."""
                g = jnp.cumsum(x, axis=0)
                gshift = jnp.concatenate(
                    [jnp.zeros_like(g[:1]), g[:-1]]
                )
                return g - jnp.take(gshift, seg_start)

            def frame_agg_sumlike(vals64, contrib, lo, hi):
                """SUM over ROWS frame [i-lo, i+hi] clamped to the
                partition (None = unbounded); also used for counts."""
                x = jnp.where(contrib, vals64, jnp.zeros_like(vals64))
                S = part_prefix(x)  # S[i] = sum seg_start..i
                hi_idx = (
                    seg_end - 1 if hi is None
                    else jnp.minimum(pos + hi, seg_end - 1)
                )
                hi_idx = jnp.clip(hi_idx, 0, cap - 1)
                s_hi = jnp.take(S, hi_idx)
                if lo is None:
                    return s_hi
                lo_idx = jnp.maximum(pos - lo, seg_start)
                s_lo_prev = jnp.where(
                    lo_idx > seg_start,
                    jnp.take(S, jnp.clip(lo_idx - 1, 0, cap - 1)),
                    jnp.zeros_like(s_hi),
                )
                return s_hi - s_lo_prev

            def running_minmax(v, contrib, is_min):
                """Partition-reset running min/max via associative scan."""
                if jnp.issubdtype(v.dtype, jnp.floating):
                    neutral = jnp.inf if is_min else -jnp.inf
                else:
                    info = jnp.iinfo(v.dtype)
                    neutral = info.max if is_min else info.min
                x = jnp.where(contrib, v, jnp.asarray(neutral, v.dtype))

                def op(a, b):
                    fa, va = a
                    fb, vb_ = b
                    red = (
                        jnp.minimum(va, vb_) if is_min
                        else jnp.maximum(va, vb_)
                    )
                    return fa | fb, jnp.where(fb, vb_, red)

                _, out = jax.lax.associative_scan(op, (pb, x))
                return out

            outs = []
            for f in fns:
                if f.kind == "row_number":
                    outs.append((rn, None))
                elif f.kind == "rank":
                    outs.append(
                        ((run_start - seg_start + 1).astype(jnp.int64),
                         None)
                    )
                elif f.kind == "dense_rank":
                    dr = jnp.cumsum(vb.astype(jnp.int64))
                    seg_dr = jnp.take(dr, seg_start)
                    outs.append((dr - seg_dr + 1, None))
                elif f.kind == "ntile":
                    nt = int(f.offset)  # >= 1, validated at init
                    base = size // nt
                    rem = size % nt
                    cutoff = rem * (base + 1)
                    tile = jnp.where(
                        rn <= cutoff,
                        (rn - 1) // jnp.maximum(base + 1, 1),
                        rem + (rn - 1 - cutoff)
                        // jnp.maximum(base, 1),
                    )
                    outs.append(((tile + 1).astype(jnp.int64), None))
                elif f.kind == "percent_rank":
                    rk = (run_start - seg_start + 1).astype(jnp.float64)
                    pr = jnp.where(
                        size > 1,
                        (rk - 1.0)
                        / jnp.maximum(size - 1, 1).astype(jnp.float64),
                        0.0,
                    )
                    outs.append((pr, None))
                elif f.kind == "cume_dist":
                    cd = (run_end - seg_start).astype(jnp.float64) \
                        / jnp.maximum(size, 1).astype(jnp.float64)
                    outs.append((cd, None))
                elif f.kind in ("lag", "lead"):
                    v, m = ev.evaluate(f.source)
                    k = int(f.offset)
                    if k == 0:  # Spark lag/lead(v, 0) = current row
                        valid = live if m is None else (live & m)
                        outs.append((v, valid))
                        continue
                    if f.kind == "lag":
                        sv = jnp.concatenate([v[:k], v[:-k]], axis=0)
                        sm = (
                            jnp.concatenate([m[:k], m[:-k]])
                            if m is not None else None
                        )
                        ok = rn > k
                    else:
                        sv = jnp.concatenate([v[k:], v[-k:]], axis=0)
                        sm = (
                            jnp.concatenate([m[k:], m[-k:]])
                            if m is not None else None
                        )
                        ok = rn <= size - k
                    valid = ok if sm is None else (ok & sm)
                    outs.append((sv, valid & live))
                else:  # frame aggregates
                    v, m = ev.evaluate(f.source)
                    contrib = live if m is None else (live & m)
                    frame = f.frame
                    if frame is None:
                        outs.append(
                            _whole_partition_agg(
                                f.kind, v, contrib, gid, cap
                            )
                        )
                        continue
                    ftype, lo, hi = frame
                    if f.kind in ("min", "max"):
                        # running (UNBOUNDED lo) min/max; range frames
                        # read the value at the tie-run end
                        running = running_minmax(
                            v, contrib, f.kind == "min"
                        )
                        cnt = frame_agg_sumlike(
                            contrib.astype(jnp.int64), live, lo, 0
                        )
                        if ftype == "range":
                            at = jnp.clip(run_end - 1, 0, cap - 1)
                            running = jnp.take(running, at)
                            cnt = jnp.take(cnt, at)
                        outs.append((running, cnt > 0))
                        continue
                    vals = v
                    if jnp.issubdtype(v.dtype, jnp.integer):
                        vals = v.astype(jnp.int64)
                    s = frame_agg_sumlike(vals, contrib, lo, hi)
                    c = frame_agg_sumlike(
                        contrib.astype(jnp.int64), live, lo, hi
                    )
                    if ftype == "range":
                        # ties share the frame ending at the run end
                        at = jnp.clip(run_end - 1, 0, cap - 1)
                        s = jnp.take(s, at)
                        c = jnp.take(c, at)
                    anyv = c > 0
                    if f.kind == "count":
                        outs.append((c, None))
                    elif f.kind == "sum":
                        outs.append((s, anyv))
                    else:  # avg
                        outs.append(
                            (
                                s.astype(jnp.float64)
                                / jnp.maximum(c, 1).astype(jnp.float64),
                                anyv,
                            )
                        )
            return outs

        return kernel
