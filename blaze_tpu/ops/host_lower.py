"""Split expression trees at the string-type boundary.

`lower_strings_host(exprs, batch)` rewrites each bound expression so that
any node with a direct string-typed input is evaluated host-side (pyarrow)
over the batch and replaced by a reference to a new precomputed column
appended to an augmented batch. Device pipelines then never see string
semantics - only int32 codes passing through untouched, plus host-computed
bool/int/string-result columns.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

import pyarrow as pa
import pyarrow.compute as pc

from blaze_tpu.config import get_config
from blaze_tpu.types import (
    DataType,
    Field,
    Schema,
    TypeId,
    from_arrow_type,
)
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.host_eval import HostEvaluator
from blaze_tpu.exprs.typing import infer_dtype


def _positional_arrays(cb: ColumnBatch) -> List[pa.Array]:
    """Full-capacity-aligned pyarrow arrays of the first num_rows rows,
    ignoring selection (alignment with device buffers matters)."""
    full = ColumnBatch(cb.schema, cb.columns, cb.num_rows, None)
    rb = full.to_arrow()
    return [rb.column(i) for i in range(rb.num_columns)]


class _Lowerer:
    def __init__(self, cb: ColumnBatch):
        self.cb = cb
        self.schema = cb.schema
        self.new_fields: List[Field] = []
        self.new_columns: List[Column] = []
        self._arrays: Optional[List[pa.Array]] = None
        self._cache = {}

    def arrays(self) -> List[pa.Array]:
        if self._arrays is None:
            self._arrays = _positional_arrays(self.cb)
        return self._arrays

    def aug_schema(self) -> Schema:
        return Schema(list(self.schema.fields) + self.new_fields)

    def lower(self, e: ir.Expr, root: bool = False) -> ir.Expr:
        if isinstance(e, ir.Literal):
            if root and infer_dtype(
                e, self.aug_schema()
            ).is_string_like:
                # a PROJECTED string constant becomes a one-entry
                # dictionary column (codes all zero) - no device
                # strings. Literals nested inside expressions (InList
                # values, comparisons) stay in place: their parent is
                # host-evaluated and consumes them natively.
                return self._hoist_literal(e)
            return e
        e = self._lower_children(e)
        if isinstance(e, ir.BoundCol):
            return e
        if any(
            infer_dtype(c, self.aug_schema()).is_string_like
            for c in ir.children(e)
        ):
            return self._hoist(e)
        return e

    def _lower_children(self, e: ir.Expr) -> ir.Expr:
        return _rebuild_with_children(
            e, [self.lower(c) for c in ir.children(e)]
        )

    def _hoist_literal(self, e: ir.Literal) -> ir.Expr:
        if e in self._cache:
            return self._cache[e]
        cap = self.cb.capacity
        dt = e.dtype
        val_type = (
            pa.binary() if dt.id is TypeId.BINARY else pa.utf8()
        )
        if e.value is None:
            codes = jnp.zeros(cap, dtype=jnp.int32)
            col = Column(
                dt, codes, jnp.zeros(cap, dtype=jnp.bool_),
                pa.array([], type=val_type),
            )
        else:
            codes = jnp.zeros(cap, dtype=jnp.int32)
            col = Column(
                dt, codes, None,
                pa.array([e.value], type=val_type),
            )
        idx = len(self.schema) + len(self.new_fields)
        self.new_fields.append(Field(f"__host_{idx}", dt, True))
        self.new_columns.append(col)
        # keep the host-array view aligned with the augmented schema
        n = self.cb.num_rows
        self._arrays = self.arrays() + [
            pa.array([e.value] * n, type=val_type)
        ]
        ref = ir.BoundCol(idx, dt)
        self._cache[e] = ref
        return ref

    def _hoist(self, e: ir.Expr) -> ir.Expr:
        if e in self._cache:
            return self._cache[e]
        ev = HostEvaluator(self.aug_schema(), self.arrays())
        arr = ev.evaluate(e)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        dt = from_arrow_type(arr.type)
        cap = self.cb.capacity
        tmp = ColumnBatch.from_arrow(
            pa.RecordBatch.from_arrays([arr], names=["x"]), capacity=cap
        )
        col = tmp.columns[0]
        idx = len(self.schema) + len(self.new_fields)
        self.new_fields.append(Field(f"__host_{idx}", dt, True))
        self.new_columns.append(col)
        self._arrays = self.arrays() + [arr]
        ref = ir.BoundCol(idx, dt)
        self._cache[e] = ref
        return ref


def _rebuild_with_children(e: ir.Expr, kids: List[ir.Expr]) -> ir.Expr:
    return ir.with_children(e, kids)


def lower_strings_host(
    exprs: Sequence[ir.Expr], cb: ColumnBatch
) -> Tuple[List[ir.Expr], int, ColumnBatch]:
    """Returns (rewritten exprs, n new columns, augmented batch)."""
    lw = _Lowerer(cb)
    out = [lw.lower(e, root=True) for e in exprs]
    if not lw.new_columns:
        return list(out), 0, cb
    aug = ColumnBatch(
        lw.aug_schema(),
        list(cb.columns) + lw.new_columns,
        cb.num_rows,
        cb.selection,
    )
    return list(out), len(lw.new_columns), aug
