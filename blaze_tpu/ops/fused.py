"""Pipeline fusion: collapse Filter/Project/Rename chains into ONE jitted
XLA program per batch.

SURVEY 7 design stance: "operators are pure functions composed and jit'd
per (plan-fingerprint, batch-shape-bucket)". Unfused, each operator in a
scan->filter->project chain dispatches its own device program per batch;
through this harness's network-tunneled chip a dispatch costs ~70ms, and
even on directly-attached hardware it forfeits XLA's cross-op fusion. The
`fuse_pipelines` pass rewrites maximal stateless chains into a
FusedPipelineExec whose whole chain traces into a single program; the
deferred selection vector (batch.ColumnBatch.selection) carries filter
results through without any host sync.

Stages whose expressions need the host string tier are left unfused (the
per-op path handles their per-batch host lowering).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from blaze_tpu.types import Schema
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.project import ProjectExec, _unflatten_cvs
from blaze_tpu.ops.rename import RenameColumnsExec


def _expr_needs_host(e: ir.Expr, schema: Schema) -> bool:
    """True when any non-passthrough node has a direct string input (the
    host_lower hoisting condition)."""
    if isinstance(e, (ir.BoundCol, ir.Col, ir.Literal)):
        return False
    for c in ir.children(e):
        if _expr_needs_host(c, schema):
            return True
        try:
            if infer_dtype(c, schema).is_string_like:
                return True
        except Exception:
            return True
    return False


def _stage_fusable(op: PhysicalOp) -> bool:
    if isinstance(op, RenameColumnsExec):
        return True
    if isinstance(op, FilterExec):
        return not _expr_needs_host(op.predicate, op.children[0].schema)
    if isinstance(op, ProjectExec):
        child_schema = op.children[0].schema
        return not any(
            _expr_needs_host(e, child_schema) for e, _ in op.exprs
        )
    return False


class FusedPipelineExec(PhysicalOp):
    """A chain of stateless stages compiled as one device program."""

    def __init__(self, leaf: PhysicalOp, stages: Sequence[PhysicalOp]):
        self.children = [leaf]
        self.stages = list(stages)  # bottom-up; stage i's child is i-1
        self._schema = self.stages[-1].schema
        self._jit_cache = {}

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        inner = " -> ".join(type(s).__name__ for s in self.stages)
        return f"FusedPipelineExec[{inner}]"

    def execute(self, partition: int, ctx: ExecContext):
        for cb in self.children[0].execute(partition, ctx):
            yield self._run(cb)

    def _run(self, cb: ColumnBatch) -> ColumnBatch:
        key = cb.layout()
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = jax.jit(self._build_kernel(cb.layout()))
            self._jit_cache[key] = fn
        out_bufs, sel = fn(cb.device_buffers(), cb.selection)
        # dictionaries for passthrough string columns
        dicts = self._out_dictionaries(cb)
        cols: List[Column] = []
        it = iter(out_bufs)
        for field, d in zip(self._schema, dicts):
            v = next(it)
            m = next(it)
            cols.append(Column(field.dtype, v, m, d))
        return ColumnBatch(self._schema, cols, cb.num_rows, sel)

    def _build_kernel(self, layout):
        stages = self.stages
        leaf_schema = self.children[0].schema

        def kernel(bufs, selection):
            cols = _unflatten_cvs(layout, bufs)
            schema = leaf_schema
            cap = layout[0]
            sel = selection
            for st in stages:
                ev = DeviceEvaluator(schema, cols, cap)
                if isinstance(st, FilterExec):
                    keep = ev.evaluate_predicate(st.predicate)
                    sel = keep if sel is None else (sel & keep)
                elif isinstance(st, ProjectExec):
                    cols = [ev.evaluate(e) for e, _ in st.exprs]
                    schema = st.schema
                else:  # Rename
                    schema = st.schema
            out = []
            for v, m in cols:
                out.append(v)
                out.append(
                    m if m is not None
                    else jnp.ones(cap, dtype=jnp.bool_)
                )
            return out, sel

        return kernel

    def _out_dictionaries(self, cb: ColumnBatch):
        """Track dictionaries of string columns through the stage chain
        (only passthrough BoundCol survives fusion for strings)."""
        dicts = [c.dictionary for c in cb.columns]
        for st in self.stages:
            if isinstance(st, ProjectExec):
                new = []
                for e, _ in st.exprs:
                    if isinstance(e, ir.BoundCol) and \
                            e.dtype.is_dictionary_encoded:
                        new.append(dicts[e.index])
                    else:
                        new.append(None)
                dicts = new
        return dicts


class FusedAggregateExec(PhysicalOp):
    """A stateless chain + a streaming PARTIAL aggregate in ONE program.

    Each input batch flows scan -> filter/project stages -> sort-based
    partial aggregation without leaving the device or re-dispatching:
    stage evaluation and the aggregate kernel trace into a single jit
    (ROADMAP: dispatch-count reduction beyond chain fusion)."""

    def __init__(self, pipeline: FusedPipelineExec, agg):
        self.children = [pipeline.children[0]]
        self.pipeline = pipeline
        self.agg = agg
        self._schema = agg.schema
        self._jit_cache = {}

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return f"FusedAggregateExec[{self.pipeline.describe()} -> partial]"

    def execute(self, partition: int, ctx: ExecContext):
        from blaze_tpu.batch import Column, ColumnBatch

        for cb in self.children[0].execute(partition, ctx):
            key = cb.layout()
            fn = self._jit_cache.get(key)
            if fn is None:
                fn = jax.jit(self._build_kernel(cb.layout()))
                self._jit_cache[key] = fn
            outs, n_groups = fn(
                cb.device_buffers(), cb.selection, cb.num_rows
            )
            n = int(n_groups)
            if n == 0:
                continue
            cols = [
                Column(f.dtype, v, m, None)
                for f, (v, m) in zip(self._schema.fields, outs)
            ]
            yield ColumnBatch(self._schema, cols, n)

    def _build_kernel(self, layout):
        pipe_kernel = self.pipeline._build_kernel(layout)
        mid_schema = self.pipeline.schema
        cap = layout[0]
        mid_layout = (
            cap,
            tuple(
                (f.dtype.id.value, f.dtype.precision, f.dtype.scale, True)
                for f in mid_schema
            ),
        )
        agg = self.agg
        key_exprs = [e for e, _ in agg.keys]
        child_map = {
            i: a.child
            for i, (a, _) in enumerate(agg.aggs)
            if a.child is not None
        }
        agg_kernel = agg._build_kernel(
            mid_schema, cap, key_exprs, child_map, False, mid_layout
        )

        def kernel(bufs, selection, num_rows):
            mid_bufs, sel = pipe_kernel(bufs, selection)
            return agg_kernel(mid_bufs, sel, num_rows)

        return kernel


def _agg_fusable(agg) -> bool:
    from blaze_tpu.ops.hash_aggregate import AggMode

    if agg.mode is not AggMode.PARTIAL:
        return False
    child_schema = agg.children[0].schema
    exprs = [e for e, _ in agg.keys] + [
        a.child for a, _ in agg.aggs if a.child is not None
    ]
    for e in exprs:
        if _expr_needs_host(e, child_schema):
            return False
        try:
            if infer_dtype(e, child_schema).is_string_like:
                return False
        except Exception:
            return False
    return True


def fuse_pipelines(op: PhysicalOp) -> PhysicalOp:
    """Top-down rewrite collapsing maximal fusable chains (>= 2 stages),
    plus folding a streaming PARTIAL aggregate into the chain below it."""
    from blaze_tpu.ops.hash_aggregate import HashAggregateExec

    if (
        isinstance(op, HashAggregateExec)
        and len(op.children) == 1
        and _agg_fusable(op)
    ):
        child = op.children[0]
        chain: List[PhysicalOp] = []
        t = child
        while (
            isinstance(t, (FilterExec, ProjectExec, RenameColumnsExec))
            and len(t.children) == 1
            and _stage_fusable(t)
        ):
            chain.append(t)
            t = t.children[0]
        if chain:
            pipeline = FusedPipelineExec(
                fuse_pipelines(t), list(reversed(chain))
            )
            return FusedAggregateExec(pipeline, op)
    chain = []
    t = op
    while (
        isinstance(t, (FilterExec, ProjectExec, RenameColumnsExec))
        and len(t.children) == 1
        and _stage_fusable(t)
    ):
        chain.append(t)
        t = t.children[0]
    if len(chain) >= 2:
        return FusedPipelineExec(fuse_pipelines(t), list(reversed(chain)))
    op.children = [fuse_pipelines(c) for c in op.children]
    return op
