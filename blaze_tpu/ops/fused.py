"""Pipeline fusion: collapse operator chains into ONE device program.

SURVEY 7 design stance: "operators are pure functions composed and jit'd
per (plan-fingerprint, batch-shape-bucket)". Unfused, each operator in a
scan->filter->project chain dispatches its own device program per batch;
through this harness's network-tunneled chip a dispatch costs ~70ms, and
even on directly-attached hardware it forfeits XLA's cross-op fusion. The
`fuse_pipelines` pass rewrites maximal stateless chains into a
FusedPipelineExec whose whole chain traces into a single program; the
deferred selection vector (batch.ColumnBatch.selection) carries filter
results through without any host sync.

Aggregate folding goes further (the reference's one-native-call-per-task
model, exec.rs:196-255): a PARTIAL aggregate fuses into the producing
chain (FusedAggregateExec - one dispatch per input batch), and a COMPLETE
aggregate is rewritten as device-PARTIAL + host-FINAL: the per-batch heavy
reduction happens on device inside the fused program, its tiny
grouped-state output comes back in ONE batched D2H, and finalization
(AVG division, variance, multi-batch merge) runs in numpy on the host -
zero additional device round trips. Per single-batch aggregation query the
device cost is exactly 1 dispatch + 1 fetch.

Stages whose expressions need the host string tier are left unfused (the
per-op path handles their per-batch host lowering).
"""

from __future__ import annotations

import itertools
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.types import DataType, Schema, TypeId
from blaze_tpu.batch import Column, ColumnBatch, packed_view
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr, AggFn
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.project import ProjectExec, _unflatten_cvs
from blaze_tpu.runtime.dispatch import cached_kernel


def _expr_needs_host(e: ir.Expr, schema: Schema) -> bool:
    """True when any non-passthrough node has a direct string input (the
    host_lower hoisting condition)."""
    if isinstance(e, (ir.BoundCol, ir.Col, ir.Literal)):
        return False
    for c in ir.children(e):
        if _expr_needs_host(c, schema):
            return True
        try:
            if infer_dtype(c, schema).is_string_like:
                return True
        except Exception:
            return True
    return False


def _stage_key(st: PhysicalOp) -> Tuple:
    """Structural descriptor of one fused stage (global kernel-cache key
    component; two plans with equal descriptors trace identically)."""
    if isinstance(st, FilterExec):
        return ("F", st.predicate)
    if isinstance(st, ProjectExec):
        return ("P", tuple(e for e, _ in st.exprs))
    return ("R",)


class FusedPipelineExec(PhysicalOp):
    """A chain of stateless stages compiled as one device program.

    An empty stage list is allowed (identity pipeline) - used when an
    aggregate fuses directly over a non-chain child such as a join."""

    def __init__(self, leaf: PhysicalOp, stages: Sequence[PhysicalOp]):
        self.children = [leaf]
        self.stages = list(stages)  # bottom-up; stage i's child is i-1
        self._schema = (
            self.stages[-1].schema if self.stages else leaf.schema
        )

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        inner = " -> ".join(type(s).__name__ for s in self.stages)
        return f"FusedPipelineExec[{inner}]"

    def structure_key(self) -> Tuple:
        return tuple(_stage_key(s) for s in self.stages)

    def execute(self, partition: int, ctx: ExecContext):
        for cb in self.children[0].execute(partition, ctx):
            yield self._run(cb)

    def _run(self, cb: ColumnBatch) -> ColumnBatch:
        pv = packed_view(cb)
        if pv is not None:
            # still-packed scan batch: the H2D wire-buffer split traces
            # INTO this kernel - transfer-unpack + the whole stage chain
            # is one dispatch (and never materializes pruned columns)
            fn = cached_kernel(
                ("fusedpipe_packed", self.structure_key(), pv.key),
                lambda: self._build_kernel_packed(pv),
            )
            out_bufs, sel = fn(pv.buf, cb.selection)
        else:
            layout = cb.layout()
            fn = cached_kernel(
                ("fusedpipe", self.structure_key(), layout),
                lambda: self._build_kernel(layout),
            )
            out_bufs, sel = fn(cb.device_buffers(), cb.selection)
        # dictionaries for passthrough string columns
        dicts = self._out_dictionaries(cb)
        cols: List[Column] = []
        it = iter(out_bufs)
        for field, d in zip(self._schema, dicts):
            v = next(it)
            m = next(it)
            cols.append(Column(field.dtype, v, m, d))
        return ColumnBatch(self._schema, cols, cb.num_rows, sel)

    def _build_kernel(self, layout):
        stages = self.stages
        leaf_schema = self.children[0].schema

        def kernel(bufs, selection):
            cols = _unflatten_cvs(layout, bufs)
            schema = leaf_schema
            cap = layout[0]
            sel = selection
            for st in stages:
                ev = DeviceEvaluator(schema, cols, cap)
                if isinstance(st, FilterExec):
                    keep = ev.evaluate_predicate(st.predicate)
                    sel = keep if sel is None else (sel & keep)
                elif isinstance(st, ProjectExec):
                    cols = [ev.evaluate(e) for e, _ in st.exprs]
                    schema = st.schema
                else:  # Rename
                    schema = st.schema
            out = []
            for v, m in cols:
                out.append(v)
                out.append(
                    m if m is not None
                    else jnp.ones(cap, dtype=jnp.bool_)
                )
            return out, sel

        return kernel

    def _build_kernel_packed(self, pv):
        unflatten = pv.build_unflatten()
        inner = self._build_kernel(pv.layout)

        def kernel(buf, selection):
            return inner(unflatten(buf), selection)

        return kernel

    def _out_dictionaries(self, cb: ColumnBatch):
        """Track dictionaries of string columns through the stage chain
        (only passthrough BoundCol survives fusion for strings)."""
        dicts = cb.dictionaries()
        for st in self.stages:
            if isinstance(st, ProjectExec):
                new = []
                for e, _ in st.exprs:
                    if isinstance(e, ir.BoundCol) and \
                            e.dtype.is_dictionary_encoded:
                        new.append(dicts[e.index])
                    else:
                        new.append(None)
                dicts = new
        return dicts


class FusedAggregateExec(PhysicalOp):
    """A stateless chain + a streaming PARTIAL aggregate in ONE program.

    Each input batch flows scan -> filter/project stages -> sort-based
    partial aggregation without leaving the device or re-dispatching:
    stage evaluation and the aggregate kernel trace into a single jit.
    With fetch_host=True (the COMPLETE/host-finalize rewrite) the
    grouped state of the first non-empty batch returns in ONE batched
    D2H together with the group count; otherwise (standalone PARTIAL
    feeding a device consumer) states stay device-resident and only the
    group-count scalar syncs."""

    def __init__(self, pipeline: FusedPipelineExec, agg,
                 fetch_host: bool = False):
        self.children = [pipeline.children[0]]
        self.pipeline = pipeline
        self.agg = agg
        # fetch_host: the consumer finalizes on the host (COMPLETE
        # rewrite) - fold the state fetch into one batched D2H. A
        # standalone PARTIAL (feeding a device shuffle writer) keeps
        # states device-resident and pays only the scalar sync.
        self.fetch_host = fetch_host
        self._schema = agg.schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return f"FusedAggregateExec[{self.pipeline.describe()} -> partial]"

    def execute(self, partition: int, ctx: ExecContext):
        from blaze_tpu.ops.joins import HashJoinExec, JoinType

        leaf = self.children[0]
        if (
            isinstance(leaf, HashJoinExec)
            and leaf.join_type is JoinType.INNER
        ):
            # INNER join below the fused aggregate: probe per batch and
            # gather the build side INSIDE the fused kernel, so the
            # joined batch never materializes and XLA dead-codes build
            # columns no stage/aggregate references
            yield from self._execute_join_fused(leaf, partition, ctx)
            return
        if self.fetch_host and not self.agg.keys:
            plan = _keyless_merge_plan(
                self.agg.aggs, self.agg.schema.fields
            )
            if plan is not None:
                yield from self._execute_keyless_carry(
                    leaf, partition, ctx, plan
                )
                return
        plan = self._grouped_carry_plan()
        if plan is not None:
            yield from self._execute_grouped_carry(
                (self._batch_spec(cb)
                 for cb in leaf.execute(partition, ctx)),
                plan,
            )
            return
        first = True
        for cb in leaf.execute(partition, ctx):
            out, first = self._run_agg(*self._batch_spec(cb), first)
            if out is not None:
                yield out

    def _batch_spec(self, cb: ColumnBatch):
        """(key_suffix, build_fn, args, capacity) for one input batch:
        the packed wire-buffer kernel variant when the batch still
        carries its H2D buffer, else the plain-layout variant."""
        pv = packed_view(cb)
        if pv is not None:
            return (
                ("fusedagg_packed", pv.key),
                lambda fl, gc, pv=pv: self._build_kernel_packed(
                    pv, force_lexsort=fl, group_cap=gc
                ),
                (pv.buf, cb.selection,
                 None if cb.num_rows == cb.capacity else cb.num_rows),
                cb.capacity,
            )
        layout = cb.layout()
        return (
            ("fusedagg", layout),
            lambda fl, gc, layout=layout: self._build_kernel(
                layout, force_lexsort=fl, group_cap=gc
            ),
            (cb.device_buffers(), cb.selection,
             None if cb.num_rows == cb.capacity else cb.num_rows),
            cb.capacity,
        )

    def _execute_keyless_carry(self, leaf, partition: int,
                               ctx: ExecContext, plan):
        """Keyless COMPLETE rewrite, streamed: ONE dispatch per input
        batch and ZERO extra dispatches at end of stream.

        The per-batch kernel computes the batch's partial state, merges
        it with the device-resident carry from the previous batch
        (SUM/COUNT add, MIN/MAX combine - masked-out states hold the
        reduction's neutral element so the merge needs no validity
        branching), AND packs the merged state into a tiny uint8 buffer.
        Only the LAST batch's packed buffer ever crosses the wire: one
        plain host fetch, no d2h pack dispatch, no per-batch sync -
        exactly the reference's one-native-call-per-task dispatch shape
        (exec.rs:196-255) with the final merge folded into the stream."""
        agg_sig = tuple((a.fn, a.child) for a, _ in self.agg.aggs)
        carry = None
        packed = None
        for cb in leaf.execute(partition, ctx):
            pv = packed_view(cb)
            if pv is not None:
                shape_key = ("packed", pv.key)
                build_inner = (
                    lambda pv=pv: self._build_kernel_packed(
                        pv, group_cap=1
                    )
                )
                bufs = pv.buf
            else:
                layout = cb.layout()
                shape_key = ("plain", layout)
                build_inner = (
                    lambda layout=layout: self._build_kernel(
                        layout, group_cap=1
                    )
                )
                bufs = cb.device_buffers()
            with_carry = carry is not None
            fn = cached_kernel(
                ("fusedagg_carry", shape_key,
                 self.pipeline.structure_key(), agg_sig, tuple(plan),
                 with_carry),
                lambda: _build_carry_kernel(
                    build_inner(), plan, with_carry
                ),
            )
            num_rows = (
                None if cb.num_rows == cb.capacity else cb.num_rows
            )
            if with_carry:
                carry, packed = fn(bufs, cb.selection, num_rows, carry)
            else:
                carry, packed = fn(bufs, cb.selection, num_rows)
        if carry is None:
            return  # empty stream: HostFinalAggExec emits the global row
        yield _fetch_packed_states(carry, packed, self._schema)

    # ------------------------------------------------------------------
    # keyed streaming device carry (the grouped twin of the keyless form)
    def _grouped_carry_plan(self):
        """Merge plan for the KEYED streaming device carry, or None when
        the shape must keep the batch-at-a-time path.

        Eligible: host-finalized (COMPLETE rewrite) keyed aggregates
        whose partial states merge by pure add/min/max (FIRST/LAST are
        order-sensitive) running on the SCATTER grouping core - the
        scatter core's exact-equality probing has no hash-collision
        sentinel, so the only per-batch retry condition left is group
        overflow, which the carry driver demotes on instead of
        re-laddering inside the composed kernel."""
        if not (self.fetch_host and self.agg.keys):
            return None
        if not self.agg._scatter_core_hint(
            self.agg.children[0].schema,
            [e for e, _ in self.agg.keys],
        ):
            return None
        n_keys = len(self.agg.keys)
        plan = _keyless_merge_plan(
            self.agg.aggs, self._schema.fields[n_keys:]
        )
        if plan is None:
            return None
        # the merge kernel's MIN/MAX lanes have no bool encoding (the
        # batch kernel widens bool to int8, which would break the
        # carry's dtype fixed point)
        for op, f in zip(plan, self._schema.fields[n_keys:]):
            if op in ("min", "max") and f.dtype.id is TypeId.BOOL:
                return None
        return tuple(plan)

    def _execute_grouped_carry(self, specs, plan,
                               span: str = "group_dispatch"):
        """Stream a KEYED aggregate through a persistent device carry:
        ONE dispatch per input batch, the grouped state re-merged
        in-kernel into a fixed set of carry slots instead of being
        re-fetched (or re-merged by a separate device FINAL pass) per
        batch, and ONE plain end-of-stream fetch of the in-kernel-packed
        (count, states) buffer.

        Single-batch partitions (the hot path) skip even the scalar
        sync: the group count rides inside the packed buffer. Multi-
        batch streams pay one scalar sync per batch - the group-overflow
        guard: when the merged group count outgrows the carry slots the
        driver DEMOTES, yielding the accumulated carry as one device-
        resident partial batch and running the rest of the stream
        through the standard per-batch ladder (HostFinalAggExec's device
        FINAL merges, external/grace behavior unchanged)."""
        from blaze_tpu.config import get_config
        from blaze_tpu.runtime.dispatch import host_int

        agg_cap = get_config().agg_group_capacity
        base = (
            "fusedagg_gcarry", self.pipeline.structure_key(),
            tuple((e, n) for e, n in self.agg.keys),
            tuple((a.fn, a.child) for a, _ in self.agg.aggs),
            plan,
        )
        it = iter(specs)
        spec = next(it, None)
        carry = None        # (n_groups device scalar, [(v, m)...])
        carry_n = 0         # host copy of the carry's group count
        slots = None        # carry slot capacity (first batch's out_cap)
        packed = None
        demote = None
        while spec is not None:
            key_suffix, build_fn, args, cap = spec
            s_b = min(cap, agg_cap)
            nxt = next(it, None)
            if carry is None:
                slots = s_b
                fn = cached_kernel(
                    base + (key_suffix, s_b, False),
                    lambda b=build_fn, s=s_b, c=cap:
                        self._build_grouped_carry_kernel(
                            b, plan, s, c, None, None
                        ),
                    scatter_class=True, span=span,
                )
                (n_dev, outs), packed = fn(args)
                if nxt is None:
                    # single-batch hot path: one dispatch + one fetch,
                    # group count inside the packed buffer (no sync)
                    n, out = self._fetch_carry(outs, packed, n_dev)
                    if n > s_b:
                        # overflow: rare re-dispatch under the ladder
                        out, _ = self._run_agg(
                            key_suffix, build_fn, args, cap, True,
                            span=span,
                        )
                    if out is not None:
                        yield out
                    return
            else:
                struct = tuple(
                    (str(np.dtype(v.dtype)), m is not None)
                    for v, m in carry[1]
                )
                fn = cached_kernel(
                    base + (key_suffix, slots, s_b, struct, True),
                    lambda b=build_fn, s=s_b, c=cap, st=struct:
                        self._build_grouped_carry_kernel(
                            b, plan, s, c, slots, st
                        ),
                    scatter_class=True, span=span,
                )
                (n_dev, outs), packed = fn(args, carry)
            # batch-level overflow already rides in n (the kernel
            # substitutes slots+1), so one slot check covers both
            n = host_int(n_dev)
            if n < 0 or n > slots:
                demote = spec
                if nxt is not None:
                    # the lookahead batch is already off the iterator -
                    # put it back for the demotion loop
                    it = itertools.chain([nxt], it)
                break
            carry = (n_dev, outs)
            carry_n = n
            spec = nxt
        if demote is None:
            if carry is not None and carry_n > 0:
                _n, out = self._fetch_carry(carry[1], packed, carry[0])
                if out is not None:
                    yield out
            return
        # ---- demotion: carry -> one device partial batch; the
        # offending batch and the rest of the stream take the standard
        # per-batch ladder (device FINAL merges downstream) ----
        first = True
        if carry is not None and carry_n > 0:
            cols = [
                Column(f.dtype, v, m, None)
                for f, (v, m) in zip(self._schema.fields, carry[1])
            ]
            yield ColumnBatch(self._schema, cols, carry_n)
            first = False
        out, first = self._run_agg(*demote, first, span=span)
        if out is not None:
            yield out
        for spec in it:
            out, first = self._run_agg(*spec, first, span=span)
            if out is not None:
                yield out

    def _fetch_carry(self, outs, packed, n_dev):
        """ONE plain fetch of an in-kernel-packed (group count, states)
        buffer -> (n, ColumnBatch | None). No pack dispatch, no scalar
        sync: the count travels inside the buffer. Returns (n, None)
        for an empty result or a count that overflowed the state slots
        (the caller re-runs the ladder)."""
        from blaze_tpu.runtime.dispatch import record
        from blaze_tpu.runtime.pack import unpack_host

        specs = [(str(np.dtype(n_dev.dtype)), tuple(n_dev.shape))]
        for v, m in outs:
            specs.append((str(np.dtype(v.dtype)), tuple(v.shape)))
            if m is not None:
                specs.append((str(np.dtype(m.dtype)), tuple(m.shape)))
        record("d2h_fetches")
        host = iter(unpack_host(np.asarray(packed), specs))
        n = int(next(host))
        if n <= 0 or n > len(outs[0][0]):
            return n, None
        cols = []
        for (v, m), f in zip(outs, self._schema.fields):
            hv = next(host)
            hm = next(host) if m is not None else None
            cols.append(Column(f.dtype, hv, hm, None))
        return n, ColumnBatch(self._schema, cols, n)

    def _build_grouped_carry_kernel(self, build_inner, plan, s_b, cap_b,
                                    s_carry, carry_struct):
        """Compose one fused-aggregate batch kernel with the keyed
        device carry: batch partial -> (with a carry) concatenate the
        carry rows with the batch's grouped state and regroup them back
        into the carry slots via a state-preserving PARTIAL merge
        aggregate -> pack (count, states) in-kernel. Returns
        ((n, states), packed_u8); n carries the overflow sentinel
        (slots + 1) when either the batch or the merged result outgrew
        its static slot count."""
        from blaze_tpu.runtime.pack import pack_in_kernel

        inner = build_inner(False, s_b if s_b < cap_b else None)
        merge_inner = None
        if s_carry is not None:
            merge_inner = self._build_carry_merge_kernel(
                plan, s_carry, s_b, carry_struct
            )

        def kernel(args, carry=None):
            outs, n_b = inner(*args)
            over_b = n_b > jnp.int32(s_b)
            if carry is None:
                m_outs = outs
                n_out = jnp.where(
                    over_b, jnp.int32(s_b + 1), n_b
                ).astype(jnp.int32)
            else:
                n_c, c_cols = carry
                live = jnp.concatenate([
                    jnp.arange(s_carry, dtype=jnp.int32) < n_c,
                    jnp.arange(s_b, dtype=jnp.int32)
                    < jnp.minimum(n_b, jnp.int32(s_b)),
                ])
                merged = []
                for (cv, cm), (bv, bm) in zip(c_cols, outs):
                    merged.append(jnp.concatenate([cv, bv]))
                    if cm is not None:
                        merged.append(jnp.concatenate([cm, bm]))
                mo, n_m = merge_inner(tuple(merged), live, None)
                # restore the canonical state-mask structure: the merge
                # lanes always emit a validity, the inner states may not
                m_outs = [
                    (v, m if om is not None else None)
                    for (v, m), (_ov, om) in zip(mo, outs)
                ]
                n_out = jnp.where(
                    over_b, jnp.int32(s_carry + 1), n_m
                ).astype(jnp.int32)
            flat = [n_out.reshape(())]
            for v, m in m_outs:
                flat.append(v)
                if m is not None:
                    flat.append(m)
            return (n_out, m_outs), pack_in_kernel(flat)

        return kernel

    def _build_carry_merge_kernel(self, plan, s_carry, s_b, struct):
        """State-preserving grouped merge: a PARTIAL aggregate over the
        (carry + batch) state rows whose lanes are SUM for additive
        state columns and MIN/MAX for extrema - unlike a FINAL kernel it
        emits mergeable partial state again, keeping the carry a fixed
        point. Groups resolve through the same scatter core as the
        batch kernel; output capacity is the carry slot count."""
        from blaze_tpu.ops.hash_aggregate import (
            AggMode,
            HashAggregateExec,
            _SchemaStub,
        )

        pschema = self._schema
        n_keys = len(self.agg.keys)
        fn_map = {
            "add": AggFn.SUM, "min": AggFn.MIN, "max": AggFn.MAX
        }
        merge_agg = HashAggregateExec(
            _SchemaStub(pschema),
            keys=[
                (ir.BoundCol(i, pschema.fields[i].dtype),
                 pschema.fields[i].name)
                for i in range(n_keys)
            ],
            aggs=[
                (AggExpr(
                    fn_map[op],
                    ir.BoundCol(
                        n_keys + j, pschema.fields[n_keys + j].dtype
                    ),
                ), f"m{j}")
                for j, op in enumerate(plan)
            ],
            mode=AggMode.PARTIAL,
        )
        cap = s_carry + s_b
        layout = (cap, tuple(
            (f.dtype.id.value, f.dtype.precision, f.dtype.scale, has_m)
            for f, (_dt, has_m) in zip(pschema.fields, struct)
        ))
        return merge_agg._build_kernel(
            pschema, cap,
            [e for e, _ in merge_agg.keys],
            {j: a.child for j, (a, _) in enumerate(merge_agg.aggs)},
            False, layout, group_cap=s_carry,
        )

    def _execute_join_fused(self, join, partition: int,
                            ctx: ExecContext):
        from blaze_tpu.ops.joins import (
            _JoinCore,
            _eq_layout,
            _flatten_cols,
        )

        build = join._collect_build(ctx)
        # the build INDEX is as probe-invariant as the build relation
        # itself: share one core across partitions/executions (the
        # reference equivalently caches broadcast build relations) so
        # repeated probes don't re-pay the insert + blocking dup sync
        with join._build_lock:
            core = getattr(join, "_fused_core", None)
            if core is None or core.build is not build:
                core = _JoinCore(build, join.left_keys)
                join._fused_core = core
        first = True
        fused_probe = getattr(join, "_fused_probe", None)
        folded = None
        if fused_probe is not None:
            # planner-recorded probe chain (_fuse_join_under_agg): try
            # the fully folded form - raw probe leaf batch -> stages ->
            # key extraction -> table walk -> build gather -> aggregate
            # as ONE kernel. Ineligible shapes (dictionary keys, the
            # sorted core) fall through to the materialized loop below,
            # where children[1] - the same pipeline object - still runs
            # the whole probe chain as one dispatch per batch.
            folded = core.table_state_static(
                join.right_keys, fused_probe[1].schema
            )
        if folded is not None:
            mode, tab = folded
            pleaf, ppipe = fused_probe
            b_layout = build.layout()
            build_key_cols = [build.columns[i] for i in join.left_keys]
            b_eq_layout = _eq_layout(build_key_cols)
            b_eq_bufs = _flatten_cols(build_key_cols)
            pkey_idx = tuple(join.right_keys)

            def probe_spec(raw):
                pv = packed_view(raw)
                if pv is not None:
                    # still-packed wire batch: the H2D buffer split
                    # traces into the folded kernel too (scan unpack ->
                    # stages -> probe -> aggregate, one program; packed
                    # columns nothing references never materialize)
                    key = ("fusedagg_join_probe_packed", mode, pv.key,
                           ppipe.structure_key(), b_layout,
                           b_eq_layout, pkey_idx)
                    build_fn = (
                        lambda fl, gc, pv=pv:
                            self._build_join_probe_kernel_packed(
                                pv, mode, b_layout, b_eq_layout,
                                pkey_idx, ppipe, force_lexsort=fl,
                                group_cap=gc,
                            )
                    )
                    p_bufs = pv.buf
                    pcap = pv.layout[0]
                else:
                    p_layout = raw.layout()
                    key = ("fusedagg_join_probe", mode, p_layout,
                           ppipe.structure_key(), b_layout,
                           b_eq_layout, pkey_idx)
                    build_fn = (
                        lambda fl, gc, p_layout=p_layout:
                            self._build_join_probe_kernel(
                                mode, p_layout, b_layout, b_eq_layout,
                                pkey_idx, ppipe, force_lexsort=fl,
                                group_cap=gc,
                            )
                    )
                    p_bufs = raw.device_buffers()
                    pcap = p_layout[0]
                return (
                    key, build_fn,
                    (build.device_buffers(), p_bufs, b_eq_bufs, tab,
                     raw.selection,
                     None if raw.num_rows == pcap else raw.num_rows),
                    pcap,
                )

            specs = (
                probe_spec(raw)
                for raw in pleaf.execute(partition, ctx)
            )
            plan = self._grouped_carry_plan()
            if plan is not None:
                yield from self._execute_grouped_carry(
                    specs, plan, span="join_dispatch"
                )
                return
            for spec in specs:
                out, first = self._run_agg(
                    *spec, first, span="join_dispatch"
                )
                if out is not None:
                    yield out
            return
        for pb in join.children[1].execute(partition, ctx):
            out, first = self._join_batch(core, join, build, pb, first)
            if out is not None:
                yield out

    def _join_batch(self, core, join, build, pb, first):
        """Fused-join step over one MATERIALIZED probe batch: table-core
        state + the lookup-inclusive fused kernel, or the sorted-core
        pair-emission fallback. Returns (ColumnBatch | None, first)."""
        from blaze_tpu.ops.joins import _eq_layout, _flatten_cols

        tstate, pb = core.table_state(pb, join.right_keys)
        if tstate is None:
            # duplicate build keys / sort core: fall back to the
            # materialized pair emission + the standard fused kernel
            state = core.probe(pb, join.right_keys)
            pb = state[1]
            out_cols, valid, pair_cap, _mp = core.emit_pairs(
                state, list(build.columns), list(pb.columns),
                build_first=True,
            )
            cb = ColumnBatch(join.schema, out_cols, pair_cap, valid)
            return self._run_agg(
                ("fusedagg", cb.layout()),
                lambda fl, gc, layout=cb.layout():
                    self._build_kernel(
                        layout, force_lexsort=fl, group_cap=gc
                    ),
                (cb.device_buffers(), cb.selection,
                 None if cb.num_rows == cb.capacity
                 else cb.num_rows),
                cb.layout()[0],
                first,
            )
        _pb, unified_b, unified_p, tab, mode = tstate
        p_layout = pb.layout()
        b_layout = build.layout()
        b_eq_layout = _eq_layout(unified_b)
        p_eq_layout = _eq_layout(unified_p)
        return self._run_agg(
            ("fusedagg_join", mode, p_layout, b_layout,
             b_eq_layout, p_eq_layout),
            lambda fl, gc: self._build_join_kernel(
                mode, p_layout, b_layout, b_eq_layout,
                p_eq_layout, force_lexsort=fl, group_cap=gc,
            ),
            (build.device_buffers(), pb.device_buffers(),
             _flatten_cols(unified_b),
             _flatten_cols(unified_p),
             tab,
             None if pb.num_rows == p_layout[0]
             else pb.num_rows),
            p_layout[0],
            first,
            span="join_dispatch",
        )

    def _run_agg(self, key_suffix, build_kernel, args, cap: int,
                 first: bool, span: str = "group_dispatch"):
        """Shared per-batch aggregate dispatch: run under the retry
        ladder, fetch per the host-finalize policy, wrap the output.
        Returns (ColumnBatch | None, first)."""
        from blaze_tpu.runtime.dispatch import host_int

        from blaze_tpu.config import get_config
        from blaze_tpu.ops.hash_aggregate import (
            _group_core_choice,
            run_grouped_kernel,
        )
        from blaze_tpu.runtime.pack import get_packed

        base_key = (
            key_suffix, self.pipeline.structure_key(),
            tuple((e, n) for e, n in self.agg.keys),
            tuple((a.fn, a.child) for a, _ in self.agg.aggs),
            _group_core_choice(),
        )
        # the fused kernel's dominant cost is the grouping core's
        # scatters (plus, on the join path, the in-kernel table gather)
        # - route scatter-core variants to the scatter-friendly CPU
        # runtime (runtime/dispatch.py)
        scatter = self.agg._scatter_core_hint(
            self.agg.children[0].schema,
            [e for e, _ in self.agg.keys],
        )

        def fetch(outs, n_groups):
            # the single-batch-per-partition hot path: states + count
            # in ONE packed transfer (a single device round trip
            # however many state columns). Later batches (multi-batch
            # stream headed for the device FINAL merge) stay
            # device-resident and pay only the scalar sync. `first`
            # stays set until a NON-EMPTY batch was host-fetched, so a
            # filtered-out leading batch doesn't push the sole
            # survivor onto the per-column-fetch path.
            if self.fetch_host and first:
                flat = [n_groups]
                for v, m in outs:
                    flat.append(v)
                    flat.append(m)
                host = get_packed(flat)
                host_outs = [
                    (host[1 + 2 * i], host[2 + 2 * i])
                    for i in range(len(outs))
                ]
                return host_outs, int(host[0])
            if not self.agg.keys:
                # keyless partial: exactly one group, no collision /
                # overflow retry possible - skip the per-batch
                # blocking scalar sync (each one is a full tunnel
                # round trip on a network-attached chip)
                return outs, 1
            return outs, host_int(n_groups)

        # group-capacity slicing: state arrays leave the kernel cut
        # to a static slot count so a small grouped result never
        # crosses the wire (or feeds downstream kernels) at input
        # capacity. Overflow / hash-collision sentinels re-dispatch
        # (run_grouped_kernel owns the shared retry ladder).
        gcap = (1 if not self.agg.keys
                else min(cap, get_config().agg_group_capacity))
        if gcap >= cap:
            gcap = None
        host_outs, n = run_grouped_kernel(
            base_key, build_kernel, args, fetch, gcap,
            scatter_class=scatter, span=span,
        )
        if self.fetch_host and first and n > 0:
            first = False
        if n == 0:
            return None, first
        cols = [
            Column(f.dtype, v, m, None)
            for f, (v, m) in zip(self._schema.fields, host_outs)
        ]
        return ColumnBatch(self._schema, cols, n), first

    def _build_join_kernel(self, mode, p_layout, b_layout, b_eq_layout,
                           p_eq_layout, force_lexsort: bool = False,
                           group_cap=None):
        """Fused INNER-join feed, lookup included: hash the probe keys,
        walk the build hash table, gather the build side at the match
        indices, splice probe buffers through untouched, then run the
        standard stage+aggregate composition over the joined column
        view (selection = the matched flags). One kernel covers
        lookup+join+stages+aggregate; build columns nothing downstream
        reads are dead code XLA eliminates - column pruning for free."""
        from blaze_tpu.ops.joins import _table_lookup, _unflatten_eq

        joined_layout = (
            p_layout[0], tuple(b_layout[1]) + tuple(p_layout[1])
        )
        inner = self._build_kernel(
            joined_layout, force_lexsort=force_lexsort,
            group_cap=group_cap,
        )
        pcap = p_layout[0]
        bcap = b_layout[0]
        b_cols_desc = b_layout[1]

        def kernel(b_bufs, p_bufs, b_eq, p_eq, tab, num_rows):
            # num_rows=None: full probe batch; the constant mask folds
            live = (
                jnp.ones(pcap, dtype=jnp.bool_) if num_rows is None
                else jnp.arange(pcap, dtype=jnp.int32) < num_rows
            )
            pkeys = _unflatten_eq(p_eq_layout, p_eq)
            for _, m in pkeys:
                if m is not None:
                    live = live & m  # NULL join keys never match
            match_idx, matched = _table_lookup(
                mode, tab, pkeys, _unflatten_eq(b_eq_layout, b_eq),
                live, bcap,
            )
            g = jnp.clip(match_idx, 0, bcap - 1)
            joined = []
            it = iter(b_bufs)
            for _tid, _prec, _scale, has_mask in b_cols_desc:
                joined.append(jnp.take(next(it), g, axis=0))
                if has_mask:
                    joined.append(jnp.take(next(it), g, axis=0))
            joined.extend(p_bufs)
            return inner(tuple(joined), matched, num_rows)

        return kernel

    def _build_join_probe_kernel(self, mode, p_layout, b_layout,
                                 b_eq_layout, probe_keys, probe_pipe,
                                 force_lexsort: bool = False,
                                 group_cap=None):
        """Deepest fusion tier: the probe side's OWN stage chain folds
        in ahead of the table walk, so scan -> filter -> project ->
        probe -> build gather -> aggregate stages run as ONE program
        over the RAW probe leaf batch - the probe relation never
        materializes at all. Probe join keys come out of the in-kernel
        stage evaluation; filtered-out rows drop via the stage
        selection before the lookup, and NULL keys never match via the
        evaluated masks."""
        from blaze_tpu.ops.joins import _table_lookup, _unflatten_eq

        pipe_kernel = probe_pipe._build_kernel(p_layout)
        mid_schema = probe_pipe.schema
        pcap = p_layout[0]
        bcap = b_layout[0]
        b_cols_desc = b_layout[1]
        joined_layout = (
            pcap,
            tuple(b_cols_desc) + tuple(
                (f.dtype.id.value, f.dtype.precision, f.dtype.scale,
                 True)
                for f in mid_schema
            ),
        )
        inner = self._build_kernel(
            joined_layout, force_lexsort=force_lexsort,
            group_cap=group_cap,
        )
        expect = tuple(
            np.dtype(mid_schema.fields[i].dtype.physical_dtype())
            for i in probe_keys
        )

        def kernel(b_bufs, p_bufs, b_eq, tab, selection, num_rows):
            mid_bufs, sel = pipe_kernel(p_bufs, selection)
            live = (
                jnp.ones(pcap, dtype=jnp.bool_) if num_rows is None
                else jnp.arange(pcap, dtype=jnp.int32) < num_rows
            )
            if sel is not None:
                live = live & sel
            pkeys = [
                (mid_bufs[2 * i], mid_bufs[2 * i + 1])
                for i in probe_keys
            ]
            # table_state_static decided the mode from the fields'
            # physical dtypes; hold the evaluator to that contract
            assert tuple(k.dtype for k, _ in pkeys) == expect, (
                [k.dtype for k, _ in pkeys], expect)
            for _, m in pkeys:
                live = live & m  # NULL join keys never match
            match_idx, matched = _table_lookup(
                mode, tab, pkeys, _unflatten_eq(b_eq_layout, b_eq),
                live, bcap,
            )
            g = jnp.clip(match_idx, 0, bcap - 1)
            joined = []
            it = iter(b_bufs)
            for _tid, _prec, _scale, has_mask in b_cols_desc:
                joined.append(jnp.take(next(it), g, axis=0))
                if has_mask:
                    joined.append(jnp.take(next(it), g, axis=0))
            joined.extend(mid_bufs)
            return inner(tuple(joined), matched, num_rows)

        return kernel

    def _build_join_probe_kernel_packed(self, pv, mode, b_layout,
                                        b_eq_layout, probe_keys,
                                        probe_pipe,
                                        force_lexsort: bool = False,
                                        group_cap=None):
        """Packed-probe-input variant of the folded join: H2D wire
        buffer split + probe stages + table walk + build gather +
        aggregate, ONE traced program."""
        unflatten = pv.build_unflatten()
        inner = self._build_join_probe_kernel(
            mode, pv.layout, b_layout, b_eq_layout, probe_keys,
            probe_pipe, force_lexsort=force_lexsort,
            group_cap=group_cap,
        )

        def kernel(b_bufs, buf, b_eq, tab, selection, num_rows):
            return inner(
                b_bufs, unflatten(buf), b_eq, tab, selection, num_rows
            )

        return kernel

    def _build_kernel_packed(self, pv, force_lexsort: bool = False,
                             group_cap=None):
        """Packed-input variant: H2D wire-buffer split + stage chain +
        partial aggregate in ONE traced program."""
        unflatten = pv.build_unflatten()
        inner = self._build_kernel(
            pv.layout, force_lexsort=force_lexsort, group_cap=group_cap
        )

        def kernel(buf, selection, num_rows):
            return inner(unflatten(buf), selection, num_rows)

        return kernel

    def _build_kernel(self, layout, force_lexsort: bool = False,
                      group_cap=None):
        pipe_kernel = self.pipeline._build_kernel(layout)
        mid_schema = self.pipeline.schema
        cap = layout[0]
        mid_layout = (
            cap,
            tuple(
                (f.dtype.id.value, f.dtype.precision, f.dtype.scale, True)
                for f in mid_schema
            ),
        )
        agg = self.agg
        key_exprs = [e for e, _ in agg.keys]
        child_map = {
            i: a.child
            for i, (a, _) in enumerate(agg.aggs)
            if a.child is not None
        }
        agg_kernel = agg._build_kernel(
            mid_schema, cap, key_exprs, child_map, False, mid_layout,
            force_lexsort=force_lexsort, group_cap=group_cap,
        )

        def kernel(bufs, selection, num_rows):
            mid_bufs, sel = pipe_kernel(bufs, selection)
            return agg_kernel(mid_bufs, sel, num_rows)

        return kernel


def _fetch_packed_states(states, packed, schema: Schema) -> ColumnBatch:
    """Turn a kernel's (state cols, in-kernel-packed u8) pair into a
    host-resident single-row state batch: ONE plain fetch, no pack
    dispatch (the kernel already packed)."""
    from blaze_tpu.runtime.dispatch import record
    from blaze_tpu.runtime.pack import unpack_host

    specs = []
    for v, m in states:
        specs.append((str(np.dtype(v.dtype)), tuple(v.shape)))
        if m is not None:
            specs.append((str(np.dtype(m.dtype)), tuple(m.shape)))
    record("d2h_fetches")
    host = iter(unpack_host(np.asarray(packed), specs))
    cols: List[Column] = []
    for (v, m), field in zip(states, schema.fields):
        hv = next(host)
        hm = next(host) if m is not None else None
        cols.append(Column(field.dtype, hv, hm, None))
    return ColumnBatch(schema, cols, len(cols[0].values) if cols else 1)


class FusedWindowAggExec(PhysicalOp):
    """Whole-task fusion of a KEYLESS aggregate over a window: folded
    stage chain + the shared (partition, order) argsort + gather + every
    frame pass + the keyless partial aggregate + state packing, ONE
    program per partition.

    Beyond the dispatch count, the fusion lets XLA dead-code the sorted
    gather of every window column the aggregate never reads - the
    dominant cost of a checksum/rollup consumer over a wide window. The
    sort permutation rides the window's cross-execution cache
    (WindowExec._sort_cache), so repeated queries over the same staged
    table skip the argsort entirely. Emits one single-row partial-state
    batch per partition for HostFinalAggExec."""

    def __init__(self, window, agg):
        self.window = window
        self.children = list(window.children)
        self.agg = agg  # keyless PARTIAL HashAggregateExec
        self._schema = agg.schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return "FusedWindowAggExec[window -> keyless partial]"

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.config import get_config, resolve_core_choice
        from blaze_tpu.ops.sort import SortKey
        from blaze_tpu.ops.util import concat_batches

        win = self.window
        src = self.children[0]
        cb = concat_batches(
            list(src.execute(partition, ctx)), schema=src.schema,
        )
        if cb.num_rows == 0:
            return  # HostFinalAggExec emits the keyless global row
        keys = [
            SortKey(e, True, True) for e in win.partition_by
        ] + list(win.order_by)
        core = resolve_core_choice(
            "BLAZE_SORT_CORE", get_config().sort_core
        )
        layout = cb.layout()
        bufs = cb.device_buffers()
        pipe = win._fused_pipeline
        base = ("fusedwinagg",
                pipe.structure_key() if pipe is not None else None,
                tuple(win.partition_by),
                tuple((k.expr, k.ascending, k.nulls_first)
                      for k in win.order_by),
                tuple((f.kind, f.source, f.offset, f.frame)
                      for f in win.functions),
                tuple((a.fn, a.child) for a, _ in self.agg.aggs),
                layout, core)
        # full batch: a constant row count lets every live-mask fold
        num_rows = (
            None if cb.num_rows == cb.capacity else cb.num_rows
        )
        idx = win._cached_sort_idx(bufs, cb.num_rows)
        if idx is None:
            fn = cached_kernel(
                base + ("sort", num_rows is None),
                lambda: self._build_kernel(layout, keys, with_idx=False),
            )
            idx, outs, packed = fn(bufs, num_rows)
            win._store_sort_idx(bufs, cb.num_rows, idx)
        else:
            fn = cached_kernel(
                base + ("reuse", num_rows is None),
                lambda: self._build_kernel(layout, keys, with_idx=True),
            )
            outs, packed = fn(bufs, num_rows, idx)
        yield _fetch_packed_states(outs, packed, self._schema)

    def _build_kernel(self, layout, keys, with_idx: bool):
        from blaze_tpu.runtime.pack import pack_in_kernel

        win = self.window
        body, mid_layout = win._fused_body(
            layout, keys, win._fused_pipeline
        )
        win_schema = win.schema
        cap = layout[0]
        win_layout = (
            cap,
            tuple(
                (f.dtype.id.value, f.dtype.precision, f.dtype.scale,
                 True)
                for f in win_schema
            ),
        )
        agg = self.agg
        child_map = {
            i: a.child
            for i, (a, _) in enumerate(agg.aggs)
            if a.child is not None
        }
        agg_kernel = agg._build_kernel(
            win_schema, cap, [], child_map, False, win_layout,
            group_cap=1,
        )

        def run(bufs, num_rows, idx):
            if num_rows is None:
                num_rows = cap  # python constant: live masks fold
            idx, sorted_bufs, outs = body(bufs, num_rows, idx)
            flat = []
            it = iter(sorted_bufs)
            for _tid, _p, _s, has_m in mid_layout[1]:
                flat.append(next(it))
                flat.append(
                    next(it) if has_m
                    else jnp.ones(cap, dtype=jnp.bool_)
                )
            for v, m in outs:
                flat.append(v)
                flat.append(
                    m if m is not None
                    else jnp.ones(cap, dtype=jnp.bool_)
                )
            states, _n = agg_kernel(flat, None, num_rows)
            pk = []
            for v, m in states:
                pk.append(v)
                if m is not None:
                    pk.append(m)
            return idx, states, pack_in_kernel(pk)

        if with_idx:
            def kernel(bufs, num_rows, idx):
                _, states, packed = run(bufs, num_rows, idx)
                return states, packed

            return kernel

        def kernel(bufs, num_rows):
            return run(bufs, num_rows, None)

        return kernel


def _keyless_merge_plan(aggs, partial_fields):
    """Per-state-column merge ops for the keyless streaming carry, or
    None when an aggregate's partial state cannot be merged by a pure
    elementwise combine (FIRST/LAST: their (value, validity) state
    cannot distinguish "no rows yet" from "first value was NULL").

    Ops: "add" (sums/counts/moments/decimal chunks - an empty state
    holds 0, the additive neutral), "min"/"max" (an empty state holds
    the respective neutral: +-inf or the integer extreme). Validity
    merges as OR on every masked state column."""
    from blaze_tpu.ops.hash_aggregate import (
        _parse_dsum_scale,
        _state_width,
    )

    plan: List[str] = []
    pos = 0
    for a, _ in aggs:
        dscale = _parse_dsum_scale(partial_fields[pos].name)
        w = _state_width(a.fn, dscale is not None)
        fn = a.fn
        if fn in (AggFn.COUNT, AggFn.COUNT_STAR, AggFn.SUM, AggFn.AVG,
                  AggFn.VAR_SAMP, AggFn.VAR_POP, AggFn.STDDEV_SAMP,
                  AggFn.STDDEV_POP):
            plan.extend(["add"] * w)
        elif fn is AggFn.MIN:
            plan.append("min")
        elif fn is AggFn.MAX:
            plan.append("max")
        else:  # FIRST/LAST (order-sensitive) or unknown
            return None
        pos += w
    return plan


def _build_carry_kernel(inner, plan, with_carry: bool):
    """Wrap a keyless fused-aggregate kernel with carry merging and
    in-kernel state packing (see _execute_keyless_carry)."""
    from blaze_tpu.runtime.pack import pack_in_kernel

    def merge(carry, outs):
        merged = []
        for op, (cv, cm), (nv, nm) in zip(plan, carry, outs):
            if op == "min":
                v = jnp.minimum(cv, nv)
            elif op == "max":
                v = jnp.maximum(cv, nv)
            else:
                v = cv + nv
            m = None if cm is None else (cm | nm)
            merged.append((v, m))
        return merged

    def finish(outs):
        flat = []
        for v, m in outs:
            flat.append(v)
            if m is not None:
                flat.append(m)
        return outs, pack_in_kernel(flat)

    if not with_carry:
        def kernel(bufs, selection, num_rows):
            outs, _n = inner(bufs, selection, num_rows)
            return finish(outs)

        return kernel

    def kernel(bufs, selection, num_rows, carry):
        outs, _n = inner(bufs, selection, num_rows)
        return finish(merge(carry, outs))

    return kernel


class _IterChild(PhysicalOp):
    """Single-partition, single-shot child that replays a batch head plus
    a live stream (feeds the device-FINAL fallback of HostFinalAggExec
    without materializing the stream)."""

    def __init__(self, batches: List[ColumnBatch], schema: Schema,
                 rest=None):
        self.children = []
        self.batches = batches
        self.rest = rest
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return 1

    def execute(self, partition: int, ctx: ExecContext):
        yield from self.batches
        if self.rest is not None:
            yield from self.rest


class HostFinalAggExec(PhysicalOp):
    """Finalize a stream of device-produced PARTIAL aggregate states on
    the HOST - the other half of the COMPLETE-mode rewrite.

    Rationale: after the fused device partial, the state is one row per
    group per batch - orders of magnitude smaller than the input. When a
    partition produced exactly ONE partial batch (the common case with
    large shape buckets), groups are already unique, so finalization is a
    pure vectorized numpy pass: no dispatch, no transfer (the states
    arrived host-resident from FusedAggregateExec's batched fetch). With
    multiple partial batches the proven device FINAL kernel merges them
    (one extra dispatch). Mirrors the reference's partial/final split
    (NativeHashAggregateExec.scala:98-161) with the final leg moved off
    the critical dispatch path."""

    def __init__(self, child: PhysicalOp, template):
        # template: the original COMPLETE HashAggregateExec (carries the
        # final schema, bound keys and agg fns)
        self.children = [child]
        self.template = template
        self._schema = template.schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return "HostFinalAggExec"

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.ops.hash_aggregate import (
            AggMode,
            HashAggregateExec,
            _SchemaStub,
            _empty_global_row,
        )

        stream = (
            cb for cb in self.children[0].execute(partition, ctx)
            if cb.num_rows > 0
        )
        first = next(stream, None)
        if first is None:
            if not self.template.keys:
                yield _empty_global_row(self.template)
            return
        second = next(stream, None)
        if second is None:
            yield self._finalize_host(first)
            return
        # multi-batch: hand the STREAM to the device FINAL kernel, whose
        # execute() owns the max_materialize_rows cap and grace-spill
        # ladder - partials are not accumulated here
        partial_schema = self.children[0].schema
        final = HashAggregateExec(
            _SchemaStub(partial_schema),
            keys=[
                (ir.BoundCol(i, partial_schema.fields[i].dtype), name)
                for i, (_, name) in enumerate(self.template.keys)
            ],
            aggs=[(a, n) for a, n in self.template.aggs],
            mode=AggMode.FINAL,
        )
        final.children = [
            _IterChild([first, second], partial_schema, rest=stream)
        ]
        yield from final.execute(0, ctx)

    # ------------------------------------------------------------------
    # number of live state rows flows into the decimal reassembly so the
    # bigint work is O(groups), not O(capacity)
    def _finalize_host(self, cb: ColumnBatch) -> ColumnBatch:
        """Vectorized numpy finalization of one unique-group state batch."""
        from blaze_tpu.ops.hash_aggregate import (
            _parse_dsum_scale,
            _state_width,
        )

        n = cb.num_rows
        n_keys = len(self.template.keys)
        partial_fields = self.children[0].schema.fields
        host = [
            (np.asarray(c.values),
             np.asarray(c.validity) if c.validity is not None else None)
            for c in cb.columns
        ]
        out_cols: List[Column] = []
        for i in range(n_keys):
            field = self._schema.fields[i]
            v, m = host[i]
            out_cols.append(
                Column(field.dtype, v, m, cb.columns[i].dictionary)
            )
        pos = n_keys
        for (a, name), field in zip(
            self.template.aggs, self._schema.fields[n_keys:]
        ):
            dscale = _parse_dsum_scale(partial_fields[pos].name)
            w = _state_width(a.fn, dscale is not None)
            states = host[pos: pos + w]
            pos += w
            out_cols.append(
                Column(
                    field.dtype,
                    *self._finalize_agg(a, field, states, dscale, n),
                )
            )
        return ColumnBatch(self._schema, out_cols, n)

    @staticmethod
    def _finalize_agg(a: AggExpr, field, states, dscale=None,
                      n_live=None):
        from blaze_tpu.ops.hash_aggregate import _reassemble_decimal

        fn = a.fn
        if dscale is not None and fn in (AggFn.SUM, AggFn.AVG):
            chunks = [v for v, _ in states[:4]]
            any_v = states[0][1]
            count = states[4][0] if fn is AggFn.AVG else None
            limbs, mask, dt = _reassemble_decimal(
                chunks, any_v, count, dscale, fn is AggFn.AVG,
                n_live=n_live,
            )
            assert dt == field.dtype, (dt, field.dtype)
            return limbs, mask
        if fn in (AggFn.COUNT, AggFn.COUNT_STAR):
            return states[0][0], None
        if fn in (AggFn.SUM, AggFn.MIN, AggFn.MAX, AggFn.FIRST,
                  AggFn.LAST):
            return states[0]
        if fn is AggFn.AVG:
            (s, sm), (c, _) = states
            safe = np.maximum(c, 1)
            valid = c > 0 if sm is None else (sm & (c > 0))
            return (
                s.astype(np.float64) / safe.astype(np.float64), valid
            )
        # var/stddev family from (n, s1, s2) moments
        (nv, _), (s1, _), (s2, _) = states
        mean = s1 / np.maximum(nv, 1.0)
        m2 = s2 - s1 * mean
        pop = fn in (AggFn.VAR_POP, AggFn.STDDEV_POP)
        denom = np.maximum(nv if pop else nv - 1.0, 1.0)
        var = np.maximum(m2, 0.0) / denom
        valid = nv > (0.0 if pop else 1.0)
        out = var
        if fn in (AggFn.STDDEV_SAMP, AggFn.STDDEV_POP):
            out = np.sqrt(var)
        return out, valid


def fuse_pipelines(op: PhysicalOp) -> PhysicalOp:
    """The plan-level fusion pass - moved to planner/fuse.py (this
    re-export keeps the historical entry point working)."""
    from blaze_tpu.planner.fuse import fuse_pipelines as _pass

    return _pass(op)
