"""Pipeline fusion: collapse operator chains into ONE device program.

SURVEY 7 design stance: "operators are pure functions composed and jit'd
per (plan-fingerprint, batch-shape-bucket)". Unfused, each operator in a
scan->filter->project chain dispatches its own device program per batch;
through this harness's network-tunneled chip a dispatch costs ~70ms, and
even on directly-attached hardware it forfeits XLA's cross-op fusion. The
`fuse_pipelines` pass rewrites maximal stateless chains into a
FusedPipelineExec whose whole chain traces into a single program; the
deferred selection vector (batch.ColumnBatch.selection) carries filter
results through without any host sync.

Aggregate folding goes further (the reference's one-native-call-per-task
model, exec.rs:196-255): a PARTIAL aggregate fuses into the producing
chain (FusedAggregateExec - one dispatch per input batch), and a COMPLETE
aggregate is rewritten as device-PARTIAL + host-FINAL: the per-batch heavy
reduction happens on device inside the fused program, its tiny
grouped-state output comes back in ONE batched D2H, and finalization
(AVG division, variance, multi-batch merge) runs in numpy on the host -
zero additional device round trips. Per single-batch aggregation query the
device cost is exactly 1 dispatch + 1 fetch.

Stages whose expressions need the host string tier are left unfused (the
per-op path handles their per-batch host lowering).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.types import DataType, Schema, TypeId
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr, AggFn
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.project import ProjectExec, _unflatten_cvs
from blaze_tpu.ops.rename import RenameColumnsExec
from blaze_tpu.runtime.dispatch import cached_kernel


def _expr_needs_host(e: ir.Expr, schema: Schema) -> bool:
    """True when any non-passthrough node has a direct string input (the
    host_lower hoisting condition)."""
    if isinstance(e, (ir.BoundCol, ir.Col, ir.Literal)):
        return False
    for c in ir.children(e):
        if _expr_needs_host(c, schema):
            return True
        try:
            if infer_dtype(c, schema).is_string_like:
                return True
        except Exception:
            return True
    return False


def _stage_fusable(op: PhysicalOp) -> bool:
    if isinstance(op, RenameColumnsExec):
        return True
    if isinstance(op, FilterExec):
        return not _expr_needs_host(op.predicate, op.children[0].schema)
    if isinstance(op, ProjectExec):
        child_schema = op.children[0].schema
        return not any(
            _expr_needs_host(e, child_schema) for e, _ in op.exprs
        )
    return False


def _stage_key(st: PhysicalOp) -> Tuple:
    """Structural descriptor of one fused stage (global kernel-cache key
    component; two plans with equal descriptors trace identically)."""
    if isinstance(st, FilterExec):
        return ("F", st.predicate)
    if isinstance(st, ProjectExec):
        return ("P", tuple(e for e, _ in st.exprs))
    return ("R",)


class FusedPipelineExec(PhysicalOp):
    """A chain of stateless stages compiled as one device program.

    An empty stage list is allowed (identity pipeline) - used when an
    aggregate fuses directly over a non-chain child such as a join."""

    def __init__(self, leaf: PhysicalOp, stages: Sequence[PhysicalOp]):
        self.children = [leaf]
        self.stages = list(stages)  # bottom-up; stage i's child is i-1
        self._schema = (
            self.stages[-1].schema if self.stages else leaf.schema
        )

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        inner = " -> ".join(type(s).__name__ for s in self.stages)
        return f"FusedPipelineExec[{inner}]"

    def structure_key(self) -> Tuple:
        return tuple(_stage_key(s) for s in self.stages)

    def execute(self, partition: int, ctx: ExecContext):
        for cb in self.children[0].execute(partition, ctx):
            yield self._run(cb)

    def _run(self, cb: ColumnBatch) -> ColumnBatch:
        layout = cb.layout()
        fn = cached_kernel(
            ("fusedpipe", self.structure_key(), layout),
            lambda: self._build_kernel(layout),
        )
        out_bufs, sel = fn(cb.device_buffers(), cb.selection)
        # dictionaries for passthrough string columns
        dicts = self._out_dictionaries(cb)
        cols: List[Column] = []
        it = iter(out_bufs)
        for field, d in zip(self._schema, dicts):
            v = next(it)
            m = next(it)
            cols.append(Column(field.dtype, v, m, d))
        return ColumnBatch(self._schema, cols, cb.num_rows, sel)

    def _build_kernel(self, layout):
        stages = self.stages
        leaf_schema = self.children[0].schema

        def kernel(bufs, selection):
            cols = _unflatten_cvs(layout, bufs)
            schema = leaf_schema
            cap = layout[0]
            sel = selection
            for st in stages:
                ev = DeviceEvaluator(schema, cols, cap)
                if isinstance(st, FilterExec):
                    keep = ev.evaluate_predicate(st.predicate)
                    sel = keep if sel is None else (sel & keep)
                elif isinstance(st, ProjectExec):
                    cols = [ev.evaluate(e) for e, _ in st.exprs]
                    schema = st.schema
                else:  # Rename
                    schema = st.schema
            out = []
            for v, m in cols:
                out.append(v)
                out.append(
                    m if m is not None
                    else jnp.ones(cap, dtype=jnp.bool_)
                )
            return out, sel

        return kernel

    def _out_dictionaries(self, cb: ColumnBatch):
        """Track dictionaries of string columns through the stage chain
        (only passthrough BoundCol survives fusion for strings)."""
        dicts = [c.dictionary for c in cb.columns]
        for st in self.stages:
            if isinstance(st, ProjectExec):
                new = []
                for e, _ in st.exprs:
                    if isinstance(e, ir.BoundCol) and \
                            e.dtype.is_dictionary_encoded:
                        new.append(dicts[e.index])
                    else:
                        new.append(None)
                dicts = new
        return dicts


class FusedAggregateExec(PhysicalOp):
    """A stateless chain + a streaming PARTIAL aggregate in ONE program.

    Each input batch flows scan -> filter/project stages -> sort-based
    partial aggregation without leaving the device or re-dispatching:
    stage evaluation and the aggregate kernel trace into a single jit.
    With fetch_host=True (the COMPLETE/host-finalize rewrite) the
    grouped state of the first non-empty batch returns in ONE batched
    D2H together with the group count; otherwise (standalone PARTIAL
    feeding a device consumer) states stay device-resident and only the
    group-count scalar syncs."""

    def __init__(self, pipeline: FusedPipelineExec, agg,
                 fetch_host: bool = False):
        self.children = [pipeline.children[0]]
        self.pipeline = pipeline
        self.agg = agg
        # fetch_host: the consumer finalizes on the host (COMPLETE
        # rewrite) - fold the state fetch into one batched D2H. A
        # standalone PARTIAL (feeding a device shuffle writer) keeps
        # states device-resident and pays only the scalar sync.
        self.fetch_host = fetch_host
        self._schema = agg.schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return f"FusedAggregateExec[{self.pipeline.describe()} -> partial]"

    def execute(self, partition: int, ctx: ExecContext):
        from blaze_tpu.ops.joins import HashJoinExec, JoinType

        leaf = self.children[0]
        if (
            isinstance(leaf, HashJoinExec)
            and leaf.join_type is JoinType.INNER
        ):
            # INNER join below the fused aggregate: probe per batch and
            # gather the build side INSIDE the fused kernel, so the
            # joined batch never materializes and XLA dead-codes build
            # columns no stage/aggregate references
            yield from self._execute_join_fused(leaf, partition, ctx)
            return
        first = True
        for cb in leaf.execute(partition, ctx):
            out, first = self._run_agg(
                ("fusedagg", cb.layout()),
                lambda fl, gc, layout=cb.layout(): self._build_kernel(
                    layout, force_lexsort=fl, group_cap=gc
                ),
                (cb.device_buffers(), cb.selection,
                 None if cb.num_rows == cb.capacity else cb.num_rows),
                cb.layout()[0],
                first,
            )
            if out is not None:
                yield out

    def _execute_join_fused(self, join, partition: int,
                            ctx: ExecContext):
        from blaze_tpu.ops.joins import _JoinCore, _flatten_cols

        build = join._collect_build(ctx)
        # the build INDEX is as probe-invariant as the build relation
        # itself: share one core across partitions/executions (the
        # reference equivalently caches broadcast build relations) so
        # repeated probes don't re-pay the insert + blocking dup sync
        with join._build_lock:
            core = getattr(join, "_fused_core", None)
            if core is None or core.build is not build:
                core = _JoinCore(build, join.left_keys)
                join._fused_core = core
        first = True
        for pb in join.children[1].execute(partition, ctx):
            tstate, pb = core.table_state(pb, join.right_keys)
            if tstate is None:
                # duplicate build keys / sort core: fall back to the
                # materialized pair emission + the standard fused kernel
                state = core.probe(pb, join.right_keys)
                pb = state[1]
                out_cols, valid, pair_cap, _mp = core.emit_pairs(
                    state, list(build.columns), list(pb.columns),
                    build_first=True,
                )
                cb = ColumnBatch(join.schema, out_cols, pair_cap, valid)
                out, first = self._run_agg(
                    ("fusedagg", cb.layout()),
                    lambda fl, gc, layout=cb.layout():
                        self._build_kernel(
                            layout, force_lexsort=fl, group_cap=gc
                        ),
                    (cb.device_buffers(), cb.selection,
                     None if cb.num_rows == cb.capacity
                     else cb.num_rows),
                    cb.layout()[0],
                    first,
                )
            else:
                _pb, unified_b, unified_p, tab, mode = tstate
                p_layout = pb.layout()
                b_layout = build.layout()
                from blaze_tpu.ops.joins import _eq_layout

                b_eq_layout = _eq_layout(unified_b)
                p_eq_layout = _eq_layout(unified_p)
                out, first = self._run_agg(
                    ("fusedagg_join", mode, p_layout, b_layout,
                     b_eq_layout, p_eq_layout),
                    lambda fl, gc: self._build_join_kernel(
                        mode, p_layout, b_layout, b_eq_layout,
                        p_eq_layout, force_lexsort=fl, group_cap=gc,
                    ),
                    (build.device_buffers(), pb.device_buffers(),
                     _flatten_cols(unified_b),
                     _flatten_cols(unified_p),
                     tab,
                     None if pb.num_rows == p_layout[0]
                     else pb.num_rows),
                    p_layout[0],
                    first,
                )
            if out is not None:
                yield out

    def _run_agg(self, key_suffix, build_kernel, args, cap: int,
                 first: bool):
        """Shared per-batch aggregate dispatch: run under the retry
        ladder, fetch per the host-finalize policy, wrap the output.
        Returns (ColumnBatch | None, first)."""
        from blaze_tpu.runtime.dispatch import host_int

        from blaze_tpu.config import get_config
        from blaze_tpu.ops.hash_aggregate import (
            _group_core_choice,
            run_grouped_kernel,
        )
        from blaze_tpu.runtime.pack import get_packed

        base_key = (
            key_suffix, self.pipeline.structure_key(),
            tuple((e, n) for e, n in self.agg.keys),
            tuple((a.fn, a.child) for a, _ in self.agg.aggs),
            _group_core_choice(),
        )

        def fetch(outs, n_groups):
            # the single-batch-per-partition hot path: states + count
            # in ONE packed transfer (a single device round trip
            # however many state columns). Later batches (multi-batch
            # stream headed for the device FINAL merge) stay
            # device-resident and pay only the scalar sync. `first`
            # stays set until a NON-EMPTY batch was host-fetched, so a
            # filtered-out leading batch doesn't push the sole
            # survivor onto the per-column-fetch path.
            if self.fetch_host and first:
                flat = [n_groups]
                for v, m in outs:
                    flat.append(v)
                    flat.append(m)
                host = get_packed(flat)
                host_outs = [
                    (host[1 + 2 * i], host[2 + 2 * i])
                    for i in range(len(outs))
                ]
                return host_outs, int(host[0])
            if not self.agg.keys:
                # keyless partial: exactly one group, no collision /
                # overflow retry possible - skip the per-batch
                # blocking scalar sync (each one is a full tunnel
                # round trip on a network-attached chip)
                return outs, 1
            return outs, host_int(n_groups)

        # group-capacity slicing: state arrays leave the kernel cut
        # to a static slot count so a small grouped result never
        # crosses the wire (or feeds downstream kernels) at input
        # capacity. Overflow / hash-collision sentinels re-dispatch
        # (run_grouped_kernel owns the shared retry ladder).
        gcap = (1 if not self.agg.keys
                else min(cap, get_config().agg_group_capacity))
        if gcap >= cap:
            gcap = None
        host_outs, n = run_grouped_kernel(
            base_key, build_kernel, args, fetch, gcap,
        )
        if self.fetch_host and first and n > 0:
            first = False
        if n == 0:
            return None, first
        cols = [
            Column(f.dtype, v, m, None)
            for f, (v, m) in zip(self._schema.fields, host_outs)
        ]
        return ColumnBatch(self._schema, cols, n), first

    def _build_join_kernel(self, mode, p_layout, b_layout, b_eq_layout,
                           p_eq_layout, force_lexsort: bool = False,
                           group_cap=None):
        """Fused INNER-join feed, lookup included: hash the probe keys,
        walk the build hash table, gather the build side at the match
        indices, splice probe buffers through untouched, then run the
        standard stage+aggregate composition over the joined column
        view (selection = the matched flags). One kernel covers
        lookup+join+stages+aggregate; build columns nothing downstream
        reads are dead code XLA eliminates - column pruning for free."""
        from blaze_tpu.ops.joins import _table_lookup, _unflatten_eq

        joined_layout = (
            p_layout[0], tuple(b_layout[1]) + tuple(p_layout[1])
        )
        inner = self._build_kernel(
            joined_layout, force_lexsort=force_lexsort,
            group_cap=group_cap,
        )
        pcap = p_layout[0]
        bcap = b_layout[0]
        b_cols_desc = b_layout[1]

        def kernel(b_bufs, p_bufs, b_eq, p_eq, tab, num_rows):
            # num_rows=None: full probe batch; the constant mask folds
            live = (
                jnp.ones(pcap, dtype=jnp.bool_) if num_rows is None
                else jnp.arange(pcap, dtype=jnp.int32) < num_rows
            )
            pkeys = _unflatten_eq(p_eq_layout, p_eq)
            for _, m in pkeys:
                if m is not None:
                    live = live & m  # NULL join keys never match
            match_idx, matched = _table_lookup(
                mode, tab, pkeys, _unflatten_eq(b_eq_layout, b_eq),
                live, bcap,
            )
            g = jnp.clip(match_idx, 0, bcap - 1)
            joined = []
            it = iter(b_bufs)
            for _tid, _prec, _scale, has_mask in b_cols_desc:
                joined.append(jnp.take(next(it), g, axis=0))
                if has_mask:
                    joined.append(jnp.take(next(it), g, axis=0))
            joined.extend(p_bufs)
            return inner(tuple(joined), matched, num_rows)

        return kernel

    def _build_kernel(self, layout, force_lexsort: bool = False,
                      group_cap=None):
        pipe_kernel = self.pipeline._build_kernel(layout)
        mid_schema = self.pipeline.schema
        cap = layout[0]
        mid_layout = (
            cap,
            tuple(
                (f.dtype.id.value, f.dtype.precision, f.dtype.scale, True)
                for f in mid_schema
            ),
        )
        agg = self.agg
        key_exprs = [e for e, _ in agg.keys]
        child_map = {
            i: a.child
            for i, (a, _) in enumerate(agg.aggs)
            if a.child is not None
        }
        agg_kernel = agg._build_kernel(
            mid_schema, cap, key_exprs, child_map, False, mid_layout,
            force_lexsort=force_lexsort, group_cap=group_cap,
        )

        def kernel(bufs, selection, num_rows):
            mid_bufs, sel = pipe_kernel(bufs, selection)
            return agg_kernel(mid_bufs, sel, num_rows)

        return kernel


class _IterChild(PhysicalOp):
    """Single-partition, single-shot child that replays a batch head plus
    a live stream (feeds the device-FINAL fallback of HostFinalAggExec
    without materializing the stream)."""

    def __init__(self, batches: List[ColumnBatch], schema: Schema,
                 rest=None):
        self.children = []
        self.batches = batches
        self.rest = rest
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return 1

    def execute(self, partition: int, ctx: ExecContext):
        yield from self.batches
        if self.rest is not None:
            yield from self.rest


class HostFinalAggExec(PhysicalOp):
    """Finalize a stream of device-produced PARTIAL aggregate states on
    the HOST - the other half of the COMPLETE-mode rewrite.

    Rationale: after the fused device partial, the state is one row per
    group per batch - orders of magnitude smaller than the input. When a
    partition produced exactly ONE partial batch (the common case with
    large shape buckets), groups are already unique, so finalization is a
    pure vectorized numpy pass: no dispatch, no transfer (the states
    arrived host-resident from FusedAggregateExec's batched fetch). With
    multiple partial batches the proven device FINAL kernel merges them
    (one extra dispatch). Mirrors the reference's partial/final split
    (NativeHashAggregateExec.scala:98-161) with the final leg moved off
    the critical dispatch path."""

    def __init__(self, child: PhysicalOp, template):
        # template: the original COMPLETE HashAggregateExec (carries the
        # final schema, bound keys and agg fns)
        self.children = [child]
        self.template = template
        self._schema = template.schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return "HostFinalAggExec"

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.ops.hash_aggregate import (
            AggMode,
            HashAggregateExec,
            _SchemaStub,
            _empty_global_row,
        )

        stream = (
            cb for cb in self.children[0].execute(partition, ctx)
            if cb.num_rows > 0
        )
        first = next(stream, None)
        if first is None:
            if not self.template.keys:
                yield _empty_global_row(self.template)
            return
        second = next(stream, None)
        if second is None:
            yield self._finalize_host(first)
            return
        # multi-batch: hand the STREAM to the device FINAL kernel, whose
        # execute() owns the max_materialize_rows cap and grace-spill
        # ladder - partials are not accumulated here
        partial_schema = self.children[0].schema
        final = HashAggregateExec(
            _SchemaStub(partial_schema),
            keys=[
                (ir.BoundCol(i, partial_schema.fields[i].dtype), name)
                for i, (_, name) in enumerate(self.template.keys)
            ],
            aggs=[(a, n) for a, n in self.template.aggs],
            mode=AggMode.FINAL,
        )
        final.children = [
            _IterChild([first, second], partial_schema, rest=stream)
        ]
        yield from final.execute(0, ctx)

    # ------------------------------------------------------------------
    # number of live state rows flows into the decimal reassembly so the
    # bigint work is O(groups), not O(capacity)
    def _finalize_host(self, cb: ColumnBatch) -> ColumnBatch:
        """Vectorized numpy finalization of one unique-group state batch."""
        from blaze_tpu.ops.hash_aggregate import (
            _parse_dsum_scale,
            _state_width,
        )

        n = cb.num_rows
        n_keys = len(self.template.keys)
        partial_fields = self.children[0].schema.fields
        host = [
            (np.asarray(c.values),
             np.asarray(c.validity) if c.validity is not None else None)
            for c in cb.columns
        ]
        out_cols: List[Column] = []
        for i in range(n_keys):
            field = self._schema.fields[i]
            v, m = host[i]
            out_cols.append(
                Column(field.dtype, v, m, cb.columns[i].dictionary)
            )
        pos = n_keys
        for (a, name), field in zip(
            self.template.aggs, self._schema.fields[n_keys:]
        ):
            dscale = _parse_dsum_scale(partial_fields[pos].name)
            w = _state_width(a.fn, dscale is not None)
            states = host[pos: pos + w]
            pos += w
            out_cols.append(
                Column(
                    field.dtype,
                    *self._finalize_agg(a, field, states, dscale, n),
                )
            )
        return ColumnBatch(self._schema, out_cols, n)

    @staticmethod
    def _finalize_agg(a: AggExpr, field, states, dscale=None,
                      n_live=None):
        from blaze_tpu.ops.hash_aggregate import _reassemble_decimal

        fn = a.fn
        if dscale is not None and fn in (AggFn.SUM, AggFn.AVG):
            chunks = [v for v, _ in states[:4]]
            any_v = states[0][1]
            count = states[4][0] if fn is AggFn.AVG else None
            limbs, mask, dt = _reassemble_decimal(
                chunks, any_v, count, dscale, fn is AggFn.AVG,
                n_live=n_live,
            )
            assert dt == field.dtype, (dt, field.dtype)
            return limbs, mask
        if fn in (AggFn.COUNT, AggFn.COUNT_STAR):
            return states[0][0], None
        if fn in (AggFn.SUM, AggFn.MIN, AggFn.MAX, AggFn.FIRST,
                  AggFn.LAST):
            return states[0]
        if fn is AggFn.AVG:
            (s, sm), (c, _) = states
            safe = np.maximum(c, 1)
            valid = c > 0 if sm is None else (sm & (c > 0))
            return (
                s.astype(np.float64) / safe.astype(np.float64), valid
            )
        # var/stddev family from (n, s1, s2) moments
        (nv, _), (s1, _), (s2, _) = states
        mean = s1 / np.maximum(nv, 1.0)
        m2 = s2 - s1 * mean
        pop = fn in (AggFn.VAR_POP, AggFn.STDDEV_POP)
        denom = np.maximum(nv if pop else nv - 1.0, 1.0)
        var = np.maximum(m2, 0.0) / denom
        valid = nv > (0.0 if pop else 1.0)
        out = var
        if fn in (AggFn.STDDEV_SAMP, AggFn.STDDEV_POP):
            out = np.sqrt(var)
        return out, valid


def _agg_exprs_fusable(agg) -> bool:
    child_schema = agg.children[0].schema
    exprs = [e for e, _ in agg.keys] + [
        a.child for a, _ in agg.aggs if a.child is not None
    ]
    for e in exprs:
        if _expr_needs_host(e, child_schema):
            return False
        try:
            if infer_dtype(e, child_schema).is_string_like:
                return False
        except Exception:
            return False
    return True


def _collect_chain(op: PhysicalOp):
    """Peel the maximal fusable stateless chain below `op`'s child."""
    chain: List[PhysicalOp] = []
    t = op
    while (
        isinstance(t, (FilterExec, ProjectExec, RenameColumnsExec))
        and len(t.children) == 1
        and _stage_fusable(t)
    ):
        chain.append(t)
        t = t.children[0]
    return chain, t


def fuse_pipelines(op: PhysicalOp) -> PhysicalOp:
    """Top-down rewrite collapsing maximal fusable chains (>= 2 stages),
    folding PARTIAL aggregates into the chain below them, and rewriting
    COMPLETE aggregates into device-PARTIAL + host-FINAL."""
    from blaze_tpu.ops.hash_aggregate import AggMode, HashAggregateExec

    if (
        isinstance(op, HashAggregateExec)
        and len(op.children) == 1
        and op.mode in (AggMode.PARTIAL, AggMode.COMPLETE)
        and _agg_exprs_fusable(op)
    ):
        chain, leaf = _collect_chain(op.children[0])
        if op.mode is AggMode.PARTIAL:
            if chain:
                pipeline = FusedPipelineExec(
                    fuse_pipelines(leaf), list(reversed(chain))
                )
                return FusedAggregateExec(pipeline, op)
            # no chain to fold - leave the plain streaming partial
        else:  # COMPLETE -> fused device PARTIAL + host FINAL
            pipeline = FusedPipelineExec(
                fuse_pipelines(leaf), list(reversed(chain))
            )
            partial = HashAggregateExec(
                pipeline,
                keys=[(e, n) for e, n in op.keys],
                aggs=[(a, n) for a, n in op.aggs],
                mode=AggMode.PARTIAL,
            )
            return HostFinalAggExec(
                FusedAggregateExec(pipeline, partial, fetch_host=True),
                op,
            )
    chain, t = _collect_chain(op)
    if len(chain) >= 2:
        return FusedPipelineExec(fuse_pipelines(t), list(reversed(chain)))
    op.children = [fuse_pipelines(c) for c in op.children]
    return op
