"""IPC reader: the exchange-consuming leaf.

Reference counterpart: IpcReaderExec (ipc_reader_exec.rs, 384 LoC) with its
three modes (rs:83-93): CHANNEL_UNCOMPRESSED (row-conversion input), CHANNEL
(broadcast bytes), CHANNEL_AND_FILE_SEGMENT (shuffle read - local segments
read straight from the .data file by (path, offset, length), remote ones
streamed). Sources are handed over through the context resource registry,
the analog of the reference's JniBridge resource map (JniBridge.java:31).
"""

from __future__ import annotations

import enum
from typing import Callable, Iterator, List, Union

import pyarrow as pa

from blaze_tpu.types import Schema, from_arrow_schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.io.ipc import decode_ipc_parts, read_file_segment
from blaze_tpu.ops.base import ExecContext, PhysicalOp


class IpcReadMode(enum.Enum):
    CHANNEL_UNCOMPRESSED = "channel_uncompressed"
    CHANNEL = "channel"
    CHANNEL_AND_FILE_SEGMENT = "channel_and_file_segment"


class FileSegment:
    def __init__(self, path: str, offset: int, length: int):
        self.path = path
        self.offset = offset
        self.length = length


Source = Union[bytes, FileSegment, pa.RecordBatch]


class IpcReaderExec(PhysicalOp):
    """Leaf reading IPC sources for each partition.

    `ctx.resources[resource_id]` must hold either a list-of-lists (sources
    per partition) or a callable partition -> list of sources. A source is
    compressed part bytes, a FileSegment, or an already-decoded
    RecordBatch (uncompressed channel)."""

    def __init__(self, resource_id: str, schema: Schema,
                 num_partitions: int,
                 mode: IpcReadMode = IpcReadMode.CHANNEL):
        self.children = []
        self.resource_id = resource_id
        self._schema = schema
        self._n = num_partitions
        self.mode = mode

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return self._n

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        provider = ctx.resources[self.resource_id]
        sources = (
            provider(partition) if callable(provider)
            else provider[partition]
        )
        from blaze_tpu.runtime.prefetch import prefetch
        from blaze_tpu.runtime.transport import (
            RemoteSegment,
            iter_remote_batches,
        )

        def batches() -> Iterator[ColumnBatch]:
            rows = 0
            for src in sources:
                if isinstance(src, RemoteSegment):
                    # remote block streamed off another host's
                    # BlockServer (reference remote-fetch path,
                    # ipc_reader_exec.rs:283-326)
                    for rb in iter_remote_batches(src):
                        rows += rb.num_rows
                        yield ColumnBatch.from_arrow(rb)
                    continue
                if isinstance(src, FileSegment):
                    it = read_file_segment(
                        src.path, src.offset, src.length
                    )
                elif isinstance(src, (bytes, bytearray, memoryview)):
                    it = decode_ipc_parts(bytes(src))
                elif isinstance(src, pa.RecordBatch):
                    it = iter((src,))
                elif hasattr(src, "read"):
                    # remote stream (the reference's
                    # ReadableByteChannel path)
                    from blaze_tpu.io.ipc import decode_ipc_stream

                    it = decode_ipc_stream(src)
                else:
                    raise TypeError(f"bad IPC source {type(src)}")
                for rb in it:
                    rows += rb.num_rows
                    yield ColumnBatch.from_arrow(rb)
            ctx.metrics.add("ipc_rows_read", rows)

        # overlap zstd decode + H2D of segment i+1 with downstream
        # device compute on segment i - the reduce-side counterpart of
        # the scan's double-buffered pipeline (reference: the tokio
        # pump, exec.rs:196-255)
        yield from prefetch(batches(), depth=2)
