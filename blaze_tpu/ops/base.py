"""Operator base: execution context, metrics, the PhysicalOp protocol."""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Iterator, List, Optional

from blaze_tpu.config import EngineConfig, get_config
from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch


class MetricNode:
    """Per-operator metric tree mirroring the plan, like the reference's
    MetricNode mirrored into Spark SQLMetrics (NativeSupports.scala:215-228,
    native side metrics.rs:32-56). Collected after a partition's stream is
    drained."""

    def __init__(self, name: str, children: Optional[List["MetricNode"]] = None):
        self.name = name
        self.children = children or []
        self.counters: Dict[str, int] = {}

    def add(self, key: str, value: int) -> None:
        self.counters[key] = self.counters.get(key, 0) + int(value)

    def child(self, i: int) -> "MetricNode":
        while len(self.children) <= i:
            self.children.append(MetricNode(f"{self.name}.{len(self.children)}"))
        return self.children[i]

    def flatten(self) -> Dict[str, Dict[str, int]]:
        out = {self.name: dict(self.counters)}
        for c in self.children:
            out.update(c.flatten())
        return out


@dataclasses.dataclass
class ExecContext:
    """Per-task execution context (the reference's TaskDefinition partition
    context + SessionContext config, exec.rs:137-165)."""

    partition_id: int = 0
    num_partitions: int = 1
    task_id: str = "task-0"
    config: EngineConfig = dataclasses.field(default_factory=get_config)
    metrics: MetricNode = dataclasses.field(
        default_factory=lambda: MetricNode("root")
    )
    # resource registry: shuffle readers/writers, broadcast values, etc.
    # (the reference's JniBridge.resourcesMap, JniBridge.java:31)
    resources: Dict[str, object] = dataclasses.field(default_factory=dict)
    # per-query TraceRecorder (obs/trace.py) when tracing is on; the
    # executor/scheduler seams check `trace.ACTIVE` before touching it
    tracer: Optional[object] = None
    # mesh execution mode for this task ("auto"|"on"|"off"); None
    # defers to the BLAZE_MESH_LOWERING env
    # (planner/distribute.resolve_mesh_mode) - the serving tier's
    # mesh_mode knob threads through here
    mesh_mode: Optional[str] = None


class PhysicalOp:
    """A node in the physical plan.

    `execute(partition, ctx)` yields ColumnBatches for one partition -
    the host-side analog of DataFusion's ExecutionPlan::execute returning a
    RecordBatch stream (reference from_proto.rs:162-560 builds these).
    """

    children: List["PhysicalOp"] = []

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def partition_count(self) -> int:
        if self.children:
            return self.children[0].partition_count
        return 1

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        raise NotImplementedError

    # ------------------------------------------------------------------
    def describe(self) -> str:
        """One-line operator description for plan display."""
        return type(self).__name__

    def display(self, indent: int = 0) -> str:
        """Indented plan tree (the reference logs the same shape at task
        start: displayable(...).indent(), exec.rs:154-158)."""
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.display(indent + 1))
        return "\n".join(lines)

    # ------------------------------------------------------------------
    def fingerprint(self) -> str:
        """Content-addressed plan identity: operator name + parameter
        digest + children, recursively. Two independently-built (or
        independently-decoded) plans that compute the same thing get the
        SAME fingerprint, which is what keys the serving tier's result
        cache (service/cache.py) and jit-cache lookups.

        Ops that cannot prove stable identity (in-memory scans over
        arbitrary buffers, resource-registry readers) keep the default
        `@id` param digest, valid only for THIS plan object; stability
        is reported out-of-band by `fingerprint_is_stable` (a class
        flag, not a content inspection - parameter digests may contain
        any characters), so result reuse across submissions is refused
        rather than silently wrong."""
        me = f"{type(self).__name__}({self._fingerprint_params()})"
        if not self.children:
            return me
        kids = ",".join(c.fingerprint() for c in self.children)
        return f"{me}[{kids}]"

    # set True by subclasses whose _fingerprint_params covers EVERY
    # execution-relevant parameter (content identity, not object
    # identity)
    _FINGERPRINT_STABLE = False

    def _fingerprint_params(self) -> str:
        """Parameter digest for fingerprint(). Subclasses with full
        parameter coverage return a deterministic content string and
        set _FINGERPRINT_STABLE; the default is object identity."""
        return f"@{id(self):x}"

    def fingerprint_is_stable(self) -> bool:
        """True iff the fingerprint survives re-building the plan:
        every op in the tree declares content-complete parameter
        coverage. Only stable fingerprints may key results shared
        across query submissions (the serving tier's result cache)."""
        return self._FINGERPRINT_STABLE and all(
            c.fingerprint_is_stable() for c in self.children
        )

    def timed(self, metrics: MetricNode, it: Iterator[ColumnBatch]
              ) -> Iterator[ColumnBatch]:
        """Wrap a batch stream with elapsed_compute / row metrics (the
        reference's BaselineMetrics, SURVEY 5.1)."""
        while True:
            t0 = time.perf_counter_ns()
            try:
                b = next(it)
            except StopIteration:
                return
            metrics.add("elapsed_compute", time.perf_counter_ns() - t0)
            metrics.add("output_rows", b.num_rows)
            metrics.add("output_batches", 1)
            yield b
