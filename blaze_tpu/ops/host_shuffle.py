"""Host-tier shuffle writer: same files, no device.

Reference counterpart: the JVM fallback row-shuffle writers
(ArrowShuffleWriter301.java:74, ArrowBypassMergeSortShuffleWriter301.
java:81) - when a shuffle's input was never native, rows are serialized
host-side into the SAME segmented-IPC `.data`/`.index` format the native
writer produces, so the read side never knows which tier wrote a block.
This module is that second producer: pyarrow batches in, bit-exact
Spark murmur3/pmod partition ids computed with the numpy/C++ host
hashing tier (no HBM touch), per-partition zstd IPC segments assembled
through the shared PartitionBuffers spill ladder.

Used by host-fallback subtrees feeding an exchange, and as the format
witness: tests assert host-written and device-written shuffles are
interchangeable under the native readers.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from blaze_tpu.types import from_arrow_schema
from blaze_tpu.io.ipc import encode_ipc_segment
from blaze_tpu.ops.shuffle_writer import PartitionBuffers, _chain_fixed
from blaze_tpu.runtime import native


def host_partition_ids(rb: pa.RecordBatch,
                       key_names: Sequence[str],
                       num_partitions: int) -> np.ndarray:
    """Bit-exact Spark murmur3(seed 42)/pmod ids for one host batch -
    the same chain the device/C++ tiers compute (spark_hash.rs:221
    semantics), evaluated with numpy + the C++ string kernel only."""
    schema = from_arrow_schema(rb.schema)
    h = np.full(rb.num_rows, 42, dtype=np.uint32)
    for name in key_names:
        idx = rb.schema.get_field_index(name)
        col = rb.column(idx)
        dt = schema.fields[idx].dtype
        if pa.types.is_dictionary(col.type):
            col = col.cast(col.type.value_type)
        if pa.types.is_string(col.type) or pa.types.is_large_string(
            col.type
        ):
            h = native.murmur3_strings_chain(col, h)
        else:
            validity = (
                np.asarray(col.is_valid())
                if col.null_count else None
            )
            vals = col.to_numpy(zero_copy_only=False)
            h = _chain_fixed(vals, validity, dt, h)
    return native.pmod_np(h, num_partitions)


def host_shuffle_write(batches: Iterable[pa.RecordBatch],
                       key_names: Sequence[str],
                       num_partitions: int,
                       data_file: str,
                       index_file: str,
                       spill_dir: Optional[str] = None,
                       compression_level: int = 1) -> List[int]:
    """Hash-partition host batches and write one map output in the
    shared shuffle format. Returns per-partition byte lengths (what the
    index file records; the reference's writeIndexFileAndCommit input,
    ArrowShuffleExchangeExec301.scala:572-585)."""
    import tempfile

    bufs = PartitionBuffers(
        num_partitions, spill_dir or tempfile.gettempdir()
    )
    for rb in batches:
        if rb.num_rows == 0:
            continue
        if num_partitions == 1:
            bufs.append(0, encode_ipc_segment(rb, compression_level))
            continue
        pids = host_partition_ids(rb, key_names, num_partitions)
        order = np.argsort(pids, kind="stable")
        rb_sorted = rb.take(pa.array(order))
        sorted_pids = pids[order]
        counts = np.bincount(sorted_pids, minlength=num_partitions)
        start = 0
        for p in range(num_partitions):
            c = int(counts[p])
            if c == 0:
                continue
            bufs.append(
                p,
                encode_ipc_segment(
                    rb_sorted.slice(start, c), compression_level
                ),
            )
            start += c
    return bufs.finalize(data_file, index_file)
