"""Filter: predicate -> deferred selection vector.

Reference counterpart: DataFusion FilterExec (from_proto.rs:193-201; wrapper
NativeFilterExec.scala). TPU-first difference (SURVEY 7): instead of eagerly
compacting (dynamic output shape -> recompile), the predicate result is
ANDed into the batch's selection mask and compaction is deferred to the next
pipeline breaker, so shapes stay static and no host sync occurs per batch.
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.host_lower import lower_strings_host
from blaze_tpu.ops.project import _unflatten_cvs
from blaze_tpu.runtime.dispatch import cached_kernel


class FilterExec(PhysicalOp):
    def __init__(self, child: PhysicalOp, predicate: ir.Expr):
        from blaze_tpu.exprs.typing import expr_computes_wide_decimal

        self.children = [child]
        self.predicate = bind_opt(predicate, child.schema)
        if expr_computes_wide_decimal(self.predicate, child.schema):
            raise NotImplementedError(
                "predicates on decimal(>18) are host-tier work"
            )

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    _FINGERPRINT_STABLE = True

    def _fingerprint_params(self) -> str:
        return repr(self.predicate)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        for cb in self.children[0].execute(partition, ctx):
            yield self._filter(cb)

    def _filter(self, cb: ColumnBatch) -> ColumnBatch:
        exprs, _, aug = lower_strings_host([self.predicate], cb)
        pred = exprs[0]
        in_schema = aug.schema
        cap = aug.capacity
        layout = aug.layout()

        def build():
            def run(bufs, sel):
                cols = _unflatten_cvs(layout, bufs)
                ev = DeviceEvaluator(in_schema, cols, cap)
                keep = ev.evaluate_predicate(pred)
                if sel is not None:
                    keep = keep & sel
                return keep

            return run

        fn = cached_kernel(("filter", pred, layout), build)
        sel = fn(aug.device_buffers(), aug.selection)
        return ColumnBatch(cb.schema, cb.columns, cb.num_rows, sel)
