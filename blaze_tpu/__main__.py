"""CLI entry points: `python -m blaze_tpu <command>`.

  run-task FILE   execute a serialized TaskDefinition protobuf and print
                  the resulting Arrow batches (the embedder-facing boundary,
                  reference callNative)
  query SQL-ish   tiny demo runner: scan a parquet file with filter/limit
  info            engine / device / native-runtime status
  gateway         legacy one-shot task gateway (one task per connection)
  serve           multi-query serving tier: the gateway listener with a
                  QueryService attached (admission control, priorities,
                  deadlines, cancellation, plan-fingerprint result cache,
                  query-lifecycle tracing)
  trace QUERY_ID  export one query's span tree from a running server as
                  Chrome-trace-event JSON (load in ui.perfetto.dev or
                  chrome://tracing)
  metrics         print the server's Prometheus text exposition
                  (dispatch.*, admission, cache, query counters)
  route           replica router: front N `serve` instances behind one
                  service endpoint (fingerprint-affinity placement,
                  headroom-aware load balancing, class-aware failover,
                  elastic JOIN/LEAVE membership + hot-result
                  replication; blaze_tpu/router/, docs/ROUTER.md)
  mesh-dryrun     versioned multichip artifact generator: run the full
                  distributed query step on an n-device virtual CPU
                  mesh and emit the MULTICHIP_r*.json shape
                  ({n_devices, rc, ok, skipped, tail})
  profile         contention profiler (obs/contention.py + sampler.py):
                  drive the serving workload at increasing concurrency
                  with lock-wait accounting + the stack sampler hot,
                  and emit one JSON report attributing where the c16
                  collapse goes (top blocking locks with wait:hold
                  ratios, top sampled stacks per thread role, per-verb
                  wire latencies) - in-process by default, or against
                  a live serve/route via --host/--port and the
                  PROFILE verb
  regress         per-phase regression check (obs/phases.py): run the
                  fixed probe workload and diff its per-phase p50s
                  against a checked-in baseline (--against), emit a
                  fresh baseline (--emit-baseline), or diff the phase
                  rollups of two BENCH_r*.json rounds (--bench A B).
                  Exits nonzero on per-phase p50 creep beyond the
                  noise band - a decode regression hiding under a
                  flat e2e median fails here, not in production
"""

from __future__ import annotations

import argparse
import json
import sys


def cmd_info(args) -> int:
    import jax

    jax.config.update("jax_enable_x64", True)
    from blaze_tpu.runtime import native

    lib = native.get_lib()
    info = {
        "version": __import__("blaze_tpu").__version__,
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "native_host_lib": bool(lib),
        "x64": bool(jax.config.jax_enable_x64),
    }
    print(json.dumps(info, indent=2))
    return 0


def cmd_run_task(args) -> int:
    from blaze_tpu.ops.base import ExecContext, MetricNode
    from blaze_tpu.runtime.executor import decode_task, execute_partition
    from blaze_tpu.runtime.instrument import instrument, render_metrics

    with open(args.file, "rb") as f:
        blob = f.read()
    ctx = ExecContext()
    total = 0
    # ONE production decode path; --metrics only adds the mirrored
    # metric tree (the reference's Spark-UI panel, metrics.rs:32-56)
    op, partition = decode_task(blob, ctx)
    root = MetricNode("root")
    if args.metrics:
        op = instrument(op, root)
    for rb in execute_partition(op, partition, ctx):
        total += rb.num_rows
        if not args.quiet:
            print(rb.to_pandas().to_string(max_rows=20))
    if args.metrics:
        print(render_metrics(root), file=sys.stderr)
    # metrics push after stream end (reference metrics.rs:32-56)
    print(f"-- {total} rows", file=sys.stderr)
    print(json.dumps(ctx.metrics.flatten()), file=sys.stderr)
    return 0


def cmd_scan(args) -> int:
    from blaze_tpu.exprs import Col
    from blaze_tpu.ops import LimitExec
    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.runtime.executor import run_plan

    plan = ParquetScanExec(
        [[FileRange(args.file)]],
        projection=args.columns.split(",") if args.columns else None,
    )
    op = LimitExec(plan, args.limit) if args.limit else plan
    tbl = run_plan(op)
    print(tbl.to_pandas().to_string(max_rows=args.limit or 50))
    return 0


def cmd_gateway(args) -> int:
    from blaze_tpu.runtime.gateway import serve_forever

    serve_forever(args.host, args.port)
    return 0


def cmd_serve(args) -> int:
    import signal
    import threading
    import time

    from blaze_tpu.runtime.gateway import TaskGatewayServer
    from blaze_tpu.service import QueryService, ResultCache

    cache = None
    if not args.no_cache:
        cache = ResultCache(
            max_bytes=args.cache_bytes, ttl_s=args.cache_ttl
        )
    service = QueryService(
        max_concurrency=args.max_concurrency,
        max_queue_depth=args.max_queue_depth,
        cache=cache,
        enable_cache=not args.no_cache,
        default_deadline_s=args.deadline or None,
        enable_trace=not args.no_trace,
        slow_query_s=args.slow_query_s,
        mesh_mode=("on" if args.mesh else args.mesh_mode),
        orphan_ttl_s=args.orphan_ttl,
        stream_buffer_bytes=args.stream_buffer_bytes,
        stream_stall_s=args.stream_stall_s,
        plan_cache_entries=args.plan_cache_entries,
        arena_bytes=(0 if args.no_arena else args.arena_bytes),
        arena_dir=args.arena_dir,
        tenant_config=(
            json.loads(args.tenant_config)
            if args.tenant_config else None
        ),
        fleet_peers=(args.fleet_peer or None),
        fleet_router=(args.fleet_router or args.router),
        fleet_devices=args.fleet_devices,
    )
    if args.profile_hz > 0:
        # whole-lifetime profiling: contention accounting + stack
        # sampler armed for the process (the PROFILE verb can also
        # arm a running tier without this flag)
        from blaze_tpu.obs import contention, sampler

        contention.enable()
        sampler.start(hz=args.profile_hz)
    # serve_blocking (NOT start()): the main thread is the only
    # accept loop - see TaskGatewayServer.serve_blocking
    srv = TaskGatewayServer(
        args.host, args.port, service=service, wire=args.wire
    )
    print(f"blaze_tpu gateway listening on {srv.address}", flush=True)
    announcer = None
    if args.router:
        # elastic membership (docs/ROUTER.md): JOIN the router now and
        # re-announce periodically, so a restarted router re-learns
        # this replica without anyone editing a --replica list
        from blaze_tpu.router.membership import (
            MembershipAnnouncer,
            parse_advertise,
        )

        adv_devices = args.fleet_devices
        if adv_devices is None:
            try:
                import jax

                adv_devices = jax.local_device_count()
            except Exception:  # noqa: BLE001 - advertise the floor
                adv_devices = None
        announcer = MembershipAnnouncer(
            args.router,
            parse_advertise(args.advertise, srv.address),
            devices=adv_devices,
        ).start()
    draining = threading.Event()

    def _drain_and_exit() -> None:
        # the listener stays up through the drain: in-flight queries
        # finish and their results stay FETCHable; only new SUBMITs
        # are refused (classified DRAINING rejection)
        print("SIGTERM: draining (refusing new submits)", flush=True)
        service.drain(timeout_s=args.drain_grace or None)
        # short linger: a router that saw the last query finish still
        # needs a beat to FETCH the result before the listener dies
        time.sleep(0.25)
        if announcer is not None:
            announcer.leave()
            announcer.close()
        print("drained; leaving", flush=True)
        srv.shutdown()

    def _on_sigterm(signum, frame) -> None:
        if not draining.is_set():
            draining.set()
            threading.Thread(
                target=_drain_and_exit, daemon=True,
                name="blaze-serve-drain",
            ).start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        srv.serve_blocking()
    except KeyboardInterrupt:
        pass
    finally:
        try:
            srv.stop()
        except OSError:
            pass
        if announcer is not None:
            announcer.close()
        service.close()
    return 0


def cmd_trace(args) -> int:
    """Fetch one query's trace over the REPORT verb and write the
    Perfetto-loadable Chrome-trace-event JSON."""
    from blaze_tpu.obs.trace import validate_chrome
    from blaze_tpu.service.wire import ServiceClient

    with ServiceClient(args.host, args.port) as c:
        data = c.report_full(args.query_id)
    if data.get("error"):
        # in-band server error (unknown query id, protocol problem):
        # surface the real cause, not a tracing diagnosis
        print(data["error"], file=sys.stderr)
        return 1
    doc = data.get("trace")
    if not doc:
        print(
            f"no trace recorded for {args.query_id} "
            "(server tracing disabled, or query evicted)",
            file=sys.stderr,
        )
        return 1
    problems = validate_chrome(doc)
    if args.out == "-":
        json.dump(doc, sys.stdout)
        print()
    else:
        out = args.out or f"{args.query_id}.trace.json"
        with open(out, "w") as f:
            json.dump(doc, f)
        print(
            f"{out}: {len(doc['traceEvents'])} events"
            + (f" ({len(problems)} schema problems)" if problems
               else " (valid)")
            + " - load in ui.perfetto.dev or chrome://tracing",
            file=sys.stderr,
        )
    return 0 if not problems else 2


def cmd_metrics(args) -> int:
    from blaze_tpu.service.wire import ServiceClient

    with ServiceClient(args.host, args.port) as c:
        sys.stdout.write(c.metrics())
    return 0


def cmd_route(args) -> int:
    from blaze_tpu.router.proxy import route_forever

    if args.profile_hz > 0:
        from blaze_tpu.obs import contention, sampler

        contention.enable()
        sampler.start(hz=args.profile_hz)

    # --replica is only a BOOTSTRAP hint since the JOIN/LEAVE
    # protocol landed: an empty router waits for replicas to announce
    # themselves (serve --router HOST:PORT)
    if not args.replica:
        print("route: no --replica bootstrap hints; waiting for "
              "replicas to JOIN (serve --router ...)",
              file=sys.stderr)
    route_forever(
        args.host,
        args.port,
        args.replica,
        placement=args.placement,
        poll_interval_s=args.poll_interval,
        heartbeat_timeout_s=args.heartbeat_timeout,
        quarantine_s=args.quarantine,
        breaker_threshold=args.breaker_threshold,
        max_resubmits=args.max_resubmits,
        enable_trace=not args.no_trace,
        conn_pool_size=args.conn_pool,
        replicate_hot_k=args.replicate_hot,
        replicate_interval_s=args.replicate_interval,
        journal_path=args.journal,
        recover_timeout_s=args.recover_timeout,
        stream_window=args.stream_window,
        stream_stall_s=args.stream_stall_s,
        stream_total_bytes=args.stream_total_bytes,
        tenant_rate=args.tenant_rate,
        tenant_burst=args.tenant_burst,
        tenant_retry_budget=args.tenant_retry_budget,
        tenant_retry_window_s=args.tenant_retry_window,
        tenant_config=(
            json.loads(args.tenant_config)
            if args.tenant_config else None
        ),
        wire=args.wire,
    )
    return 0


def cmd_mesh_dryrun(args) -> int:
    """Versioned generator for the MULTICHIP_r*.json artifact shape:
    compile + run the full distributed query step (group-by all_to_all
    exchange, broadcast join, slack repartition + skew retry, decoded-
    TaskDefinition differential) on an n-device virtual CPU mesh in a
    FRESH subprocess (the platform choice freezes at first backend
    init), and emit {n_devices, rc, ok, skipped, tail} JSON. Skips
    cleanly (skipped=true, rc 0) when jax lacks shard_map or the
    repo-root driver entry is not importable."""
    import os
    import subprocess

    n = args.devices
    root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    doc = {"n_devices": n, "rc": 0, "ok": False, "skipped": False,
           "tail": ""}

    def emit() -> int:
        text = json.dumps(doc, indent=2)
        if args.out and args.out != "-":
            with open(args.out, "w") as f:
                f.write(text + "\n")
            print(f"wrote {args.out}", file=sys.stderr)
        else:
            print(text)
        return 0 if (doc["ok"] or doc["skipped"]) else 1

    try:
        from jax import shard_map  # noqa: F401
    except ImportError:
        try:
            from jax.experimental.shard_map import (  # noqa: F401
                shard_map,
            )
        except ImportError:
            doc.update(skipped=True,
                       tail="jax lacks shard_map; mesh tier skipped\n")
            return emit()
    if not os.path.exists(os.path.join(root, "__graft_entry__.py")):
        doc.update(skipped=True,
                   tail="__graft_entry__.py not found at repo root\n")
        return emit()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import __graft_entry__; "
             f"__graft_entry__.dryrun_multichip({n})"],
            cwd=root, env=env, capture_output=True, text=True,
            timeout=args.timeout,
        )
        tail_lines = (
            (p.stdout or "") + (p.stderr or "")
        ).splitlines()[-20:]
        doc.update(
            rc=p.returncode, ok=p.returncode == 0,
            tail="\n".join(tail_lines) + "\n",
        )
    except subprocess.TimeoutExpired:
        doc.update(rc=124, ok=False,
                   tail=f"mesh dryrun timed out after "
                        f"{args.timeout:.0f}s\n")
    return emit()


def cmd_mesh_attr(args) -> int:
    """Mesh stage anatomy driver (ISSUE 19 / ROADMAP item 2): run the
    `mesh_groupby` shape at 1 device and at --devices in FRESH
    subprocesses (the virtual device count freezes at first backend
    init), collect each side's per-sub-phase rollup via
    obs/meshprof.run_attr_probe, and emit the versioned
    MESHATTR_r*.json artifact: per-sub-phase p50s that reconcile to
    the measured stage wall, the (dN - d1) gap attribution, and the
    written verdict (staging vs trace vs lock vs launch). `--child`
    is the in-subprocess half: probe at the CURRENT device count and
    print one JSON line."""
    import os
    import subprocess

    from blaze_tpu.obs import meshprof

    if args.child:
        if args.fleet:
            from blaze_tpu.fleet.attr import run_fleet_attr_probe

            doc = run_fleet_attr_probe(
                args.devices, rows=args.rows, iters=args.iters
            )
        else:
            doc = meshprof.run_attr_probe(
                args.devices, rows=args.rows, iters=args.iters
            )
        print(json.dumps(doc))
        return 0

    n = args.devices
    root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    skip = None
    try:
        from jax import shard_map  # noqa: F401
    except ImportError:
        try:
            from jax.experimental.shard_map import (  # noqa: F401
                shard_map,
            )
        except ImportError:
            skip = "jax lacks shard_map; mesh tier skipped"

    def emit(doc) -> int:
        text = json.dumps(doc, indent=2)
        out = args.out
        if out is None:
            out = meshprof.next_round_path(os.getcwd())
        if out != "-":
            with open(out, "w") as f:
                f.write(text + "\n")
            print(f"wrote {out}", file=sys.stderr)
        else:
            print(text)
        return 0 if (doc.get("ok", True) or doc.get("skipped")) else 1

    if skip is not None:
        return emit({"format": "blaze-meshattr-v1", "ok": False,
                     "skipped": True, "tail": skip})

    def child(n_dev: int) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_dev}"
        ).strip()
        env["PYTHONPATH"] = (
            root + os.pathsep + env.get("PYTHONPATH", "")
        )
        p = subprocess.run(
            [sys.executable, "-m", "blaze_tpu", "mesh-attr",
             "--child", "--devices", str(n_dev),
             "--rows", str(args.rows), "--iters", str(args.iters)]
            + (["--fleet"] if args.fleet else []),
            cwd=root, env=env, capture_output=True, text=True,
            timeout=args.timeout,
        )
        if p.returncode != 0:
            tail = ((p.stdout or "") + (p.stderr or ""))
            raise RuntimeError(
                f"mesh-attr child (d{n_dev}) rc={p.returncode}: "
                + "\n".join(tail.splitlines()[-10:])
            )
        for line in reversed((p.stdout or "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError(
            f"mesh-attr child (d{n_dev}) produced no JSON line"
        )

    if args.fleet:
        # fleet anatomy: ONE measurement (2 emulated hosts inside the
        # child), mesh_dcn attributed next to the single-host phases,
        # and the attribution must cover >= 0.95 of the stage wall
        try:
            dn = child(n)
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            return emit({"format": "blaze-meshattr-fleet-v1",
                         "ok": False, "skipped": False,
                         "tail": str(e)})
        dn["format"] = "blaze-meshattr-fleet-v1"
        cov = (dn.get("reconcile") or {}).get("coverage", 0.0)
        dn["ok"] = bool(dn.get("fleet_lowered")) and cov >= 0.95
        if not dn["ok"]:
            print(f"fleet attr coverage {cov} < 0.95 "
                  f"(lowered={dn.get('fleet_lowered')})",
                  file=sys.stderr)
        if args.out is None:
            args.out = "-"
        return emit(dn)

    try:
        d1 = child(1)
        dn = child(n)
    except (RuntimeError, subprocess.TimeoutExpired) as e:
        return emit({"format": "blaze-meshattr-v1", "ok": False,
                     "skipped": False, "tail": str(e)})
    doc = meshprof.build_doc(d1, dn)
    doc["ok"] = bool(dn.get("mesh_lowered"))
    if doc.get("verdict"):
        print(f"verdict: {doc['verdict']}", file=sys.stderr)
    return emit(doc)


def cmd_profile(args) -> int:
    """Contention profiler: drive the serving workload at each
    --concurrency level with lock-wait accounting + the stack sampler
    hot, and emit ONE JSON report attributing where the time goes -
    top blocking locks with wait:hold ratios, top sampled stacks per
    thread role, per-verb wire latencies. This is the artifact the
    ROADMAP item-2 wire-loop refactor is judged against."""
    import os
    import statistics
    import tempfile
    import threading
    import time

    from blaze_tpu.service.wire import ServiceClient

    levels = [max(1, int(tok)) for tok in
              str(args.concurrency).split(",") if tok.strip()]
    if not levels:
        print("profile: empty --concurrency list", file=sys.stderr)
        return 2

    def workload_blob(rows: int) -> bytes:
        # the phase probe's keyless-aggregate shape (obs/phases.py):
        # cheap kernel, so the levels measure SERVING contention,
        # not XLA compilation
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        from blaze_tpu.exprs import AggExpr, AggFn, Col
        from blaze_tpu.ops import (
            AggMode,
            FilterExec,
            HashAggregateExec,
        )
        from blaze_tpu.ops.parquet_scan import (
            FileRange,
            ParquetScanExec,
        )
        from blaze_tpu.plan.serde import task_to_proto

        path = os.path.join(
            tempfile.gettempdir(), f"blaze_profile_{rows}.parquet"
        )
        if not os.path.exists(path):
            rng = np.random.default_rng(7)
            pq.write_table(
                pa.table({
                    "k": pa.array(
                        rng.integers(0, 64, rows), pa.int32()
                    ),
                    "v": pa.array(rng.random(rows), pa.float64()),
                }),
                path, compression="zstd",
            )
        plan = HashAggregateExec(
            FilterExec(ParquetScanExec([[FileRange(path)]]),
                       Col("v") > 0.25),
            keys=[],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                  (AggExpr(AggFn.COUNT_STAR, None), "n")],
            mode=AggMode.COMPLETE,
        )
        return task_to_proto(plan, 0)

    blob = workload_blob(args.rows)
    per_client = max(1, args.per_client)

    def drive(host, port, conc):
        errs = []

        def client():
            try:
                with ServiceClient(host, port) as cl:
                    for _ in range(per_client):
                        cl.run(blob)
            except Exception as e:  # noqa: BLE001 - reported once
                errs.append(repr(e))

        ts = [threading.Thread(target=client,
                               name=f"blaze-profile-client-{i}")
              for i in range(conc)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            raise RuntimeError(errs[0])

    # target: a live tier (--port: the PROFILE verb arms and samples
    # it remotely - the workload parquet must be visible to it, i.e.
    # same host) or an in-process stack built here (default; --router
    # fronts the service with a real Router so the router-tier locks
    # and relay threads show up too)
    remote = args.port is not None
    teardown = []  # LIFO
    try:
        if remote:
            host, port = args.host, args.port
        else:
            from blaze_tpu.runtime.gateway import TaskGatewayServer
            from blaze_tpu.service import QueryService

            svc = QueryService(
                max_concurrency=args.max_concurrency,
                enable_cache=not args.no_cache,
            )
            teardown.append(svc.close)
            srv = TaskGatewayServer(service=svc).start()
            teardown.append(srv.stop)
            host, port = srv.address
            if args.router:
                from blaze_tpu.router.proxy import (
                    Router,
                    RouterServer,
                )

                router = Router([f"{host}:{port}"],
                                poll_interval_s=0.2)
                teardown.append(router.close)
                router.registry.poll_now()
                rsrv = RouterServer(router).start()
                teardown.append(rsrv.stop)
                host, port = rsrv.address

        def pctl(payload):
            with ServiceClient(host, port) as c:
                out = c.profile(payload)
            if out.get("error"):
                raise RuntimeError(f"PROFILE: {out['error']}")
            return out

        started = pctl({"op": "start", "hz": args.hz})
        teardown.append(lambda: pctl({"op": "stop"}))
        tier = started.get("tier", "service")
        drive(host, port, 1)  # warmup: kernel compile, cache prime

        report_levels = []
        last_snap = {}
        for i, conc in enumerate(levels):
            pctl({"op": "reset"})
            times = []
            for _ in range(max(1, args.rounds)):
                t0 = time.perf_counter()
                drive(host, port, conc)
                times.append(time.perf_counter() - t0)
            # collapsed stacks only for the LAST (max-pressure)
            # window: they dominate the report's size
            last = i == len(levels) - 1
            snap = pctl({"op": "snapshot", "collapsed": last,
                         "top_locks": 3})
            med = statistics.median(times)
            entry = {
                "concurrency": conc,
                "rounds": len(times),
                "median_s": round(med, 4),
                "spread": round(
                    (max(times) / med - 1.0) if med else 0.0, 3
                ),
                "qps": round(conc * per_client / med, 1)
                if med else 0.0,
                "top_locks": snap.get("top_locks", []),
                "contention": snap.get("contention", {}),
                "stacks": {
                    k: snap.get("profile", {}).get(k)
                    for k in ("samples", "distinct_stacks", "top")
                },
            }
            report_levels.append(entry)
            last_snap = snap
            locks = entry["top_locks"]
            print(
                f"profile: c{conc} qps={entry['qps']} "
                f"median={entry['median_s']}s top_lock="
                + (f"{locks[0]['lock']} "
                   f"(wait {locks[0]['wait_s']}s)" if locks
                   else "none"),
                file=sys.stderr, flush=True,
            )
        collapsed = last_snap.get("profile", {}).get("collapsed", "")
        report = {
            "format": "blaze-profile-v1",
            "tier": tier,
            "mode": "remote" if remote else "in-process",
            "router": bool(args.router) or tier == "router",
            "hz": args.hz,
            "per_client": per_client,
            "rows_per_query": args.rows,
            "result_cache": not args.no_cache,
            "levels": report_levels,
            # headline attribution: the max-concurrency window
            "top_locks": report_levels[-1]["top_locks"],
            "per_verb_seconds": last_snap.get("verbs", {}),
            "collapsed": collapsed,
            "roles": sorted({
                ln.split(";", 1)[0]
                for ln in collapsed.splitlines() if ln
            }),
        }
    finally:
        for fn in reversed(teardown):
            try:
                fn()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
    text = json.dumps(report, indent=1, sort_keys=True)
    if args.out and args.out != "-":
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    return 0


def cmd_regress(args) -> int:
    """Per-phase regression detection (obs/phases.py): probe-vs-
    baseline or bench-round-vs-bench-round. Exit codes: 0 clean,
    1 regression(s) detected, 2 usage/input problem."""
    from blaze_tpu.obs import phases

    if args.bench:
        try:
            base = phases.phases_from_bench(args.bench[0])
            live = phases.phases_from_bench(args.bench[1])
        except (OSError, json.JSONDecodeError) as e:
            # input problems exit 2, never 1: automation must be able
            # to tell "phase regression" from "bad artifact path"
            print(f"regress: cannot read bench artifact: {e}",
                  file=sys.stderr)
            return 2
        missing = [p for p, s in zip(args.bench, (base, live))
                   if s is None]
        if missing:
            print(f"no phase rollup recorded in {missing} "
                  "(round predates phase recording?)",
                  file=sys.stderr)
            return 2
        source = f"{args.bench[1]} vs {args.bench[0]}"
        if args.emit_baseline:
            # refresh the baseline from the NEWER round's rollup
            phases.save_baseline(
                args.emit_baseline, live,
                meta={"source": args.bench[1]},
            )
            print(f"wrote {args.emit_baseline}", file=sys.stderr)
    else:
        live = phases.run_probe(rounds=args.rounds, rows=args.rows)
        source = f"probe({args.rounds}x{args.rows} rows)"
        if args.emit_baseline:
            phases.save_baseline(
                args.emit_baseline, live,
                meta={"rounds": args.rounds, "rows": args.rows},
            )
            print(f"wrote {args.emit_baseline}", file=sys.stderr)
            if not args.against:
                return 0
        if not args.against:
            print(json.dumps(live, indent=1, sort_keys=True))
            return 0
        try:
            base = phases.load_baseline(args.against)
        except (OSError, json.JSONDecodeError) as e:
            print(f"regress: cannot read baseline "
                  f"{args.against}: {e}", file=sys.stderr)
            return 2
        source += f" vs {args.against}"
    regressions = phases.compare(
        live, base,
        rel_band=args.noise,
        abs_floor_s=args.abs_floor,
        min_samples=args.min_samples,
    )
    print(json.dumps({
        "source": source,
        "noise_band": {"rel": args.noise,
                       "abs_floor_s": args.abs_floor},
        "regressions": regressions,
        "live": live if args.verbose else
        {k: v for k, v in live.items() if k == "_all"},
    }, indent=1, sort_keys=True))
    if regressions:
        worst = regressions[0]
        print(
            f"REGRESSION: {len(regressions)} phase(s) crept - worst "
            f"{worst['class']}/{worst['phase']} p50 "
            f"{worst['base_p50']}s -> {worst['live_p50']}s "
            f"({worst['ratio']}x, limit {worst['limit']}s)",
            file=sys.stderr,
        )
        return 1
    print("per-phase p50s within the noise band", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="blaze_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("info")
    rt = sub.add_parser("run-task")
    rt.add_argument("file")
    rt.add_argument("--quiet", action="store_true")
    rt.add_argument("--metrics", action="store_true",
                    help="print the per-operator metric tree")
    sc = sub.add_parser("scan")
    sc.add_argument("file")
    sc.add_argument("--columns", default=None)
    sc.add_argument("--limit", type=int, default=20)
    gw = sub.add_parser("gateway")
    gw.add_argument("--host", default="127.0.0.1")
    gw.add_argument("--port", type=int, default=8484)
    sv = sub.add_parser("serve")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8484)
    sv.add_argument("--max-concurrency", type=int, default=2)
    sv.add_argument("--max-queue-depth", type=int, default=64)
    sv.add_argument("--deadline", type=float, default=0.0,
                    help="default per-query deadline seconds (0 = none)")
    sv.add_argument("--no-cache", action="store_true",
                    help="disable the plan-fingerprint result cache")
    sv.add_argument("--cache-bytes", type=int, default=256 << 20)
    sv.add_argument("--cache-ttl", type=float, default=300.0)
    sv.add_argument("--no-trace", action="store_true",
                    help="disable query-lifecycle tracing (obs/)")
    sv.add_argument("--slow-query-s", type=float, default=None,
                    help="structured slow-query log threshold "
                         "(default 5s or BLAZE_SLOW_QUERY_S; "
                         "<= 0 disables)")
    sv.add_argument("--mesh", action="store_true",
                    help="force the mesh execution tier for every "
                         "eligible query (mesh_mode=on; docs/MESH.md)")
    sv.add_argument("--mesh-mode", default=None,
                    choices=("auto", "on", "off"),
                    help="mesh execution mode (default: defer to "
                         "BLAZE_MESH_LOWERING / auto)")
    sv.add_argument("--router", default=None, metavar="HOST:PORT",
                    help="router to JOIN (elastic membership: "
                         "announced at startup and re-announced "
                         "periodically; LEAVE is sent after a "
                         "SIGTERM drain)")
    sv.add_argument("--advertise", default=None, metavar="HOST:PORT",
                    help="address announced to the router (default: "
                         "the listener's bound address)")
    sv.add_argument("--orphan-ttl", type=float, default=900.0,
                    help="reap terminal, never-fetched queries with "
                         "no POLL activity for this many seconds - "
                         "a permanently-dead router cannot pin "
                         "replica retention forever (0 disables)")
    sv.add_argument("--drain-grace", type=float, default=30.0,
                    help="SIGTERM drain: max seconds to wait for "
                         "in-flight queries before leaving anyway "
                         "(0 = wait forever; open result streams "
                         "count as in-flight)")
    sv.add_argument("--stream-buffer-bytes", type=int,
                    default=32 << 20,
                    help="per-query bounded ring for incremental "
                         "FETCH-while-RUNNING delivery: the executor "
                         "blocks once this many produced-but-"
                         "undelivered bytes pile up (0 = legacy "
                         "materialize-then-stream)")
    sv.add_argument("--stream-stall-s", type=float, default=30.0,
                    help="slow-consumer budget: a FETCHing client "
                         "that accepts no bytes for this long while "
                         "the stream buffer sits at its cap gets the "
                         "query aborted STREAM_STALLED (CANCELLED-"
                         "class - never a breaker strike), freeing "
                         "buffer and reservation (0 disables)")
    sv.add_argument("--profile-hz", type=float, default=0.0,
                    help="arm lock-wait accounting and run the "
                         "thread-stack sampler at this Hz for the "
                         "process lifetime (0 = off; the PROFILE "
                         "verb can arm a live server without it)")
    sv.add_argument("--wire", default=None,
                    choices=("async", "threaded"),
                    help="wire data plane: event-loop verb serving "
                         "(async, the default) or the legacy thread-"
                         "per-connection tier (threaded); default "
                         "honors BLAZE_WIRE")
    sv.add_argument("--plan-cache-entries", type=int, default=256,
                    help="decoded-plan cache (zerocopy/): repeat "
                         "SUBMITs of a byte-identical blob skip the "
                         "protobuf decode entirely (0 disables)")
    sv.add_argument("--arena-bytes", type=int, default=256 << 20,
                    help="shared-memory Arrow arena budget: finalized "
                         "results are published once as mmap'd wire "
                         "frames and FETCHes are served zero-copy "
                         "(scatter-gather or a leased handle for "
                         "co-located clients)")
    sv.add_argument("--no-arena", action="store_true",
                    help="disable the arena: every FETCH re-encodes "
                         "and streams over the socket byte path")
    sv.add_argument("--arena-dir", default=None,
                    help="arena segment directory (default: a "
                         "private temp dir, removed at close)")
    sv.add_argument("--fleet-peer", action="append", default=[],
                    metavar="HOST:PORT",
                    help="peer serve host for the fleet mesh tier "
                    "(repeatable); large queries execute across this "
                    "host plus every peer over the MESH_EXCHANGE "
                    "DCN plane (docs/MESH.md, fleet tier)")
    sv.add_argument("--fleet-router", default=None,
                    metavar="HOST:PORT",
                    help="router arbitrating fleet device claims "
                    "(defaults to --router when set; omit both for "
                    "a host-local device ledger)")
    sv.add_argument("--fleet-devices", type=int, default=None,
                    help="accelerator count this host contributes to "
                    "the fleet device pool (announced on JOIN; "
                    "default: the local device count)")
    sv.add_argument("--tenant-config", default=None, metavar="JSON",
                    help="per-tenant admission budgets, e.g. "
                         '\'{"acme": {"max_queued": 8, '
                         '"max_running": 1, "weight": 2.0}, '
                         '"*": {"max_queued": 32}}\' - enables '
                         "weighted-fair (DRR) ordering across "
                         "tenants; omit for tenant-unaware admission "
                         "(docs/SERVICE.md)")
    tr = sub.add_parser("trace")
    tr.add_argument("query_id")
    tr.add_argument("--host", default="127.0.0.1")
    tr.add_argument("--port", type=int, default=8484)
    tr.add_argument("-o", "--out", default=None,
                    help="output path ('-' = stdout; default "
                         "<query_id>.trace.json)")
    mt = sub.add_parser("metrics")
    mt.add_argument("--host", default="127.0.0.1")
    mt.add_argument("--port", type=int, default=8484)
    rr = sub.add_parser("route")
    rr.add_argument("--host", default="127.0.0.1")
    rr.add_argument("--port", type=int, default=8485)
    rr.add_argument("--replica", action="append", default=[],
                    metavar="HOST:PORT",
                    help="a serve instance to front (repeatable; a "
                         "BOOTSTRAP hint only - replicas join and "
                         "leave dynamically via the MEMBER verb)")
    rr.add_argument("--placement", default="affinity",
                    choices=("affinity", "random"),
                    help="placement policy (random = baseline for "
                         "the bench comparison)")
    rr.add_argument("--poll-interval", type=float, default=0.5,
                    help="STATS heartbeat poll period seconds")
    rr.add_argument("--heartbeat-timeout", type=float, default=3.0,
                    help="no successful poll for this long = dead")
    rr.add_argument("--quarantine", type=float, default=15.0,
                    help="quarantine cool-off seconds")
    rr.add_argument("--breaker-threshold", type=int, default=3,
                    help="consecutive fatal-class failures that open "
                         "a replica's circuit breaker")
    rr.add_argument("--max-resubmits", type=int, default=2,
                    help="TRANSIENT same-replica re-submissions per "
                         "query")
    rr.add_argument("--no-trace", action="store_true",
                    help="disable router-hop tracing (obs/)")
    rr.add_argument("--conn-pool", type=int, default=4,
                    help="verb connections pooled per replica (one "
                         "slow RPC can't serialize sibling verbs)")
    rr.add_argument("--replicate-hot", type=int, default=4,
                    metavar="K",
                    help="double-place the top-K hot fingerprints on "
                         "a second replica (0 disables hot-result "
                         "replication)")
    rr.add_argument("--replicate-interval", type=float, default=2.0,
                    help="hot-replication pass period seconds")
    rr.add_argument("--journal", default=None, metavar="PATH",
                    help="durable routing journal: record every "
                         "routed query's lifecycle so a restarted "
                         "router (same --journal) replays its table "
                         "and reconciles in-flight queries against "
                         "the re-JOINing fleet instead of forgetting "
                         "them (docs/ROUTER.md 'Router recovery')")
    rr.add_argument("--recover-timeout", type=float, default=30.0,
                    help="recovery window seconds: journaled "
                         "placements whose replica has not re-JOINed "
                         "by then are re-placed on the live fleet "
                         "(or stranded when none is routable)")
    rr.add_argument("--stream-window", type=int, default=4,
                    help="streaming relay credit window: raw result "
                         "parts in flight between the downstream "
                         "reader and the client-facing writer "
                         "(1 = strictly serial relay)")
    rr.add_argument("--stream-stall-s", type=float, default=30.0,
                    help="relay slow-consumer budget: a client that "
                         "accepts no bytes for this long gets its "
                         "relay aborted (downstream keeps the parts; "
                         "a re-FETCH resumes; never a breaker "
                         "strike; 0 disables)")
    rr.add_argument("--stream-total-bytes", type=int,
                    default=256 << 20,
                    help="fleet-wide relay memory cap: total parked "
                         "(read-from-replica, not-yet-delivered) "
                         "bytes across ALL concurrent relay streams; "
                         "an over-budget stream's reader waits "
                         "(stream_total_waits counts them) until "
                         "siblings drain (0 disables)")
    rr.add_argument("--profile-hz", type=float, default=0.0,
                    help="arm lock-wait accounting and run the "
                         "thread-stack sampler at this Hz for the "
                         "router's lifetime (0 = off)")
    rr.add_argument("--wire", default=None,
                    choices=("async", "threaded"),
                    help="wire data plane: event-loop relay (async, "
                         "the default) or the legacy thread-per-"
                         "connection front (threaded); default "
                         "honors BLAZE_WIRE")
    rr.add_argument("--tenant-rate", type=float, default=0.0,
                    help="fleet-level per-tenant SUBMIT rate limit "
                         "(queries/sec, token bucket); over-rate "
                         "submits are rejected REJECTED_TENANT_BUDGET "
                         "before journaling, zero breaker strikes "
                         "(0 = off; docs/ROUTER.md)")
    rr.add_argument("--tenant-burst", type=int, default=None,
                    help="token-bucket burst size (default "
                         "2x --tenant-rate, min 1)")
    rr.add_argument("--tenant-retry-budget", type=int, default=0,
                    help="per-tenant failover/retry re-submits "
                         "allowed per trailing window; an exhausted "
                         "budget surfaces the original classified "
                         "error instead of re-submitting (0 = "
                         "unlimited)")
    rr.add_argument("--tenant-retry-window", type=float,
                    default=30.0,
                    help="trailing window seconds for "
                         "--tenant-retry-budget")
    rr.add_argument("--tenant-config", default=None, metavar="JSON",
                    help="per-tenant overrides, e.g. "
                         '\'{"acme": {"rate": 5, "burst": 10, '
                         '"retry_budget": 4}, "*": {"rate": 50}}\'')
    md = sub.add_parser("mesh-dryrun")
    md.add_argument("--devices", type=int, default=8,
                    help="virtual device count for the forced host "
                         "mesh")
    md.add_argument("-o", "--out", default=None,
                    help="output path for the MULTICHIP-shaped JSON "
                         "('-'/default = stdout)")
    md.add_argument("--timeout", type=float, default=600.0,
                    help="dryrun subprocess wall-clock bound seconds")
    ma = sub.add_parser("mesh-attr")
    ma.add_argument("--devices", type=int, default=8,
                    help="virtual device count for the dN side of "
                         "the attribution (d1 always runs too)")
    ma.add_argument("--rows", type=int, default=1 << 20,
                    help="input rows for the mesh_groupby shape")
    ma.add_argument("--iters", type=int, default=4,
                    help="warm measurement rounds per device count")
    ma.add_argument("-o", "--out", default=None,
                    help="output path for MESHATTR JSON (default: "
                         "next MESHATTR_rNN.json in cwd; '-' = "
                         "stdout)")
    ma.add_argument("--timeout", type=float, default=600.0,
                    help="per-child subprocess wall-clock bound "
                         "seconds")
    ma.add_argument("--fleet", action="store_true",
                    help="attribute the FLEET tier instead: 2 "
                    "emulated hosts in one probe process, mesh_dcn "
                    "(the DCN exchange rounds) next to the "
                    "single-host sub-phases; fails unless the "
                    "attribution covers >= 0.95 of the stage wall")
    ma.add_argument("--child", action="store_true",
                    help=argparse.SUPPRESS)
    pf = sub.add_parser("profile")
    pf.add_argument("--concurrency", default="1,4,16",
                    help="comma list of client concurrency levels "
                         "to drive and attribute (default 1,4,16)")
    pf.add_argument("--router", action="store_true",
                    help="front the in-process service with a real "
                         "Router so router-tier locks and relay "
                         "threads are attributed too")
    pf.add_argument("--host", default="127.0.0.1")
    pf.add_argument("--port", type=int, default=None,
                    help="profile a LIVE serve/route at host:port "
                         "via the PROFILE verb instead of building "
                         "an in-process stack (same host: the "
                         "workload parquet path must be visible "
                         "to it)")
    pf.add_argument("--hz", type=float, default=67.0,
                    help="stack sampler frequency")
    pf.add_argument("--rounds", type=int, default=3,
                    help="timed workload rounds per level")
    pf.add_argument("--per-client", type=int, default=4,
                    help="queries each client thread runs per round")
    pf.add_argument("--rows", type=int, default=1 << 16,
                    help="workload dataset rows (small: the levels "
                         "measure serving contention, not kernels)")
    pf.add_argument("--max-concurrency", type=int, default=16,
                    help="in-process service executor slots")
    pf.add_argument("--no-cache", action="store_true",
                    help="disable the result cache (default on: the "
                         "cached path IS the c16 collapse case)")
    pf.add_argument("-o", "--out", default=None,
                    help="report path ('-'/default = stdout)")
    rg = sub.add_parser("regress")
    rg.add_argument("--against", default=None, metavar="BASELINE",
                    help="phase baseline JSON to diff the probe "
                         "against (PHASE_BASELINE.json)")
    rg.add_argument("--emit-baseline", default=None, metavar="PATH",
                    help="write the probe's rollup as a fresh "
                         "baseline")
    rg.add_argument("--bench", nargs=2, default=None,
                    metavar=("OLD", "NEW"),
                    help="diff the phase rollups of two BENCH_r*.json "
                         "artifacts instead of probing")
    rg.add_argument("--rounds", type=int, default=6,
                    help="probe repetitions (post-warmup)")
    rg.add_argument("--rows", type=int, default=1 << 18,
                    help="probe dataset rows")
    rg.add_argument("--noise", type=float, default=0.75,
                    help="relative noise band: regress when live p50 "
                         "> base p50 * (1 + noise) + abs-floor")
    rg.add_argument("--abs-floor", type=float, default=0.05,
                    help="absolute noise floor seconds")
    rg.add_argument("--min-samples", type=int, default=3,
                    help="ignore (class, phase) cells with fewer "
                         "samples on either side")
    rg.add_argument("-v", "--verbose", action="store_true",
                    help="include every class in the report, not "
                         "just _all")
    args = p.parse_args(argv)
    return {
        "info": cmd_info,
        "run-task": cmd_run_task,
        "scan": cmd_scan,
        "gateway": cmd_gateway,
        "serve": cmd_serve,
        "trace": cmd_trace,
        "metrics": cmd_metrics,
        "route": cmd_route,
        "mesh-dryrun": cmd_mesh_dryrun,
        "mesh-attr": cmd_mesh_attr,
        "profile": cmd_profile,
        "regress": cmd_regress,
    }[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())
