"""Failure taxonomy: every error the engine surfaces has a class.

The reference engine inherits failure semantics from Spark: a task
failure is retried by the scheduler, a plan the converter cannot handle
falls back to the JVM row engine, and OOM triggers the spill ladder
(SURVEY 5.3). A standalone serving tier must make those distinctions
explicit, because the right reaction differs per class:

  TRANSIENT           retry (bounded, exponential backoff + jitter) -
                      H2D hiccups, socket drops, spill-file IO races.
  RESOURCE_EXHAUSTED  do NOT retry the same way; degrade - re-execute
                      the partition through the host engine
                      (planner/host_engine.py), the native->Spark
                      fallback analog.
  PLAN_INVALID        fail fast, zero retries - re-running a malformed
                      plan burns retry budget for a deterministic
                      failure.
  CANCELLED           not a failure at all - cooperative unwind
                      (deadline, client disconnect, sibling fail-fast).
  INTERNAL            unclassified; treated as fatal (no retry) so an
                      engine bug is loud instead of masked by retries.

Raise sites either throw a `BlazeError` subclass directly or raise
whatever is natural and let `classify()` map it; classification walks
the `__cause__` chain so wrappers (TaskExecutionError) stay
transparent. The class travels the wire as a plain string
(`ErrorClass.value`) in query status frames.
"""

from __future__ import annotations

import enum
from typing import Optional


class ErrorClass(enum.Enum):
    TRANSIENT = "TRANSIENT"
    RESOURCE_EXHAUSTED = "RESOURCE_EXHAUSTED"
    PLAN_INVALID = "PLAN_INVALID"
    CANCELLED = "CANCELLED"
    INTERNAL = "INTERNAL"


#: classes for which a retry of the SAME work can possibly succeed
RETRYABLE = frozenset({ErrorClass.TRANSIENT})
#: classes that indicate the WORKER (not the task) is suspect - the
#: cluster driver quarantines a worker slot after N of these
FATAL_FOR_WORKER = frozenset(
    {ErrorClass.INTERNAL, ErrorClass.RESOURCE_EXHAUSTED}
)
#: same signal one tier up: a service replica repeatedly failing with
#: these classes is sick - the router's circuit breaker counts them
#: (PLAN_INVALID deliberately absent: the PLAN is bad, not the replica,
#: and re-routing a malformed plan would poison every breaker in turn)
FATAL_FOR_REPLICA = FATAL_FOR_WORKER


class BlazeError(RuntimeError):
    """Base of the classified error hierarchy."""

    error_class: ErrorClass = ErrorClass.INTERNAL


class TransientError(BlazeError):
    error_class = ErrorClass.TRANSIENT


class ResourceExhaustedError(BlazeError):
    error_class = ErrorClass.RESOURCE_EXHAUSTED


class PlanInvalidError(BlazeError):
    error_class = ErrorClass.PLAN_INVALID


class CancelledError(BlazeError):
    error_class = ErrorClass.CANCELLED


class ReplicaUnavailableError(TransientError):
    """Router-tier: no routable replica (all dead/quarantined, or the
    fleet is empty). TRANSIENT by design - capacity comes back when a
    replica revives or rejoins, so the client's correct reaction is
    retry-with-backoff, not abandon."""


class ReplicaDrainingError(TransientError):
    """A replica refused new work because it is DRAINING (SIGTERM
    rolling restart: finish in-flight, reject new, LEAVE when empty).
    TRANSIENT by design - the replica (or its replacement) comes back
    within one restart, so a bare client's correct reaction is the
    same retry-with-backoff it already applies to dropped
    connections; a router treats it as a placement miss and spills to
    the next replica with zero breaker strikes."""


class TenantBudgetError(TransientError):
    """The submitting tenant is over its configured budget (queued
    entries, RUNNING slots, reserved bytes) or router-tier rate
    limit. TRANSIENT by design - the budget frees as the tenant's own
    in-flight work drains (or the rate-limit window refills), so a
    bare client's correct reaction is the same retry-with-backoff it
    applies to DRAINING; a router treats a replica-side budget
    rejection as a placement miss and spills to the next replica with
    zero breaker strikes (the replica is healthy - the TENANT is
    over budget)."""


# exception type names that mean "cooperative cancellation" - matched by
# name to avoid importing the scheduler/service from this leaf module
_CANCEL_NAMES = frozenset({"PlanCancelled", "QueryCancelled"})


def _classify_one(e: BaseException) -> Optional[ErrorClass]:
    if isinstance(e, BlazeError):
        return e.error_class
    name = type(e).__name__
    # match the whole MRO, not just the leaf name: subclasses of the
    # cancel types (e.g. StreamStalled(QueryCancelled)) are
    # cooperative cancellations too
    if (
        name in _CANCEL_NAMES
        or any(c.__name__ in _CANCEL_NAMES for c in type(e).__mro__)
        or isinstance(e, (GeneratorExit, KeyboardInterrupt))
    ):
        return ErrorClass.CANCELLED
    if isinstance(e, MemoryError):
        return ErrorClass.RESOURCE_EXHAUSTED
    if name == "XlaRuntimeError" and "RESOURCE_EXHAUSTED" in str(e):
        # jax surfaces device-OOM as XlaRuntimeError with the XLA
        # status code in the message
        return ErrorClass.RESOURCE_EXHAUSTED
    if isinstance(
        e, (FileNotFoundError, PermissionError, IsADirectoryError,
            NotADirectoryError)
    ):
        # deterministic path problems (a plan naming a missing file):
        # retrying - or re-spooling to another worker - cannot help
        return ErrorClass.PLAN_INVALID
    if isinstance(
        e, (ConnectionError, TimeoutError, EOFError, OSError)
    ):
        # IOError is an alias of OSError; socket drops, spill-file IO
        # races, NFS hiccups - all plausibly recoverable on re-run
        return ErrorClass.TRANSIENT
    if isinstance(
        e,
        (ValueError, TypeError, KeyError, IndexError,
         NotImplementedError, AssertionError),
    ):
        # deterministic plan/shape problems: re-running cannot help
        return ErrorClass.PLAN_INVALID
    return None


def retry_action(ec: ErrorClass, attempt: int, max_attempts: int,
                 can_degrade: bool) -> str:
    """THE failure policy, in one place (both executors consult it -
    runtime/scheduler.py and service/service.py - so the taxonomy
    reactions cannot drift between them):

      'cancel'  - cooperative unwind, not a failure
      'degrade' - re-run the partition on the host engine
      'retry'   - back off and re-attempt (TRANSIENT with budget left)
      'fail'    - propagate now (deterministic error, or budget spent)
    """
    if ec is ErrorClass.CANCELLED:
        return "cancel"
    if ec is ErrorClass.RESOURCE_EXHAUSTED and can_degrade:
        return "degrade"
    if ec in RETRYABLE and attempt + 1 < max_attempts:
        return "retry"
    return "fail"


def classify(exc: Optional[BaseException]) -> ErrorClass:
    """Map an arbitrary exception to its ErrorClass.

    Walks the `__cause__` chain (wrappers like TaskExecutionError keep
    their cause there) and returns the first classifiable link;
    anything unrecognized is INTERNAL (fatal, never retried)."""
    seen = set()
    e = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        c = _classify_one(e)
        if c is not None:
            return c
        e = e.__cause__
    return ErrorClass.INTERNAL
