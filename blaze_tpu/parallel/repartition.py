"""Intra-slice hash repartition: the ICI tier of the shuffle.

Where the reference always spills shuffle data through segmented-IPC files
(shuffle_writer_exec.rs), HBM-resident batches inside one TPU slice can be
re-bucketed with a single `lax.all_to_all` over ICI - no host round trip,
no compression, no disk (SURVEY 2.4 TPU mapping). The inter-node tier
(parallel/exchange.ShuffleExchangeExec) still uses the reference-compatible
file format.

Shape discipline: each shard sorts its rows by target device (one stable
argsort - the same counting-sort-as-sort trick as the file shuffle writer),
scatters them into per-target buckets of a fixed size, and all_to_all
exchanges the bucket axis. Bucket capacity defaults to the EXPECTED
per-target share times a slack factor (uniform hash spread), cutting the
bytes over ICI by ~n_dev/slack versus worst-case sizing; per-bucket
overflow is detected on device (one scalar readback) and the exchange
retries once with worst-case capacity, so pathological skew stays
correct.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax exposes it under experimental
    from jax.experimental.shard_map import shard_map

from blaze_tpu.types import DataType
from blaze_tpu.exprs.hashing import hash_columns_device, pmod


def partition_ids_for_devices(key_cols, capacity: int, num_devices: int
                              ) -> jax.Array:
    """Spark-murmur3 pmod over num_devices (per shard)."""
    h = hash_columns_device(key_cols, capacity)
    return pmod(h, num_devices)


def _bucketize(values: jax.Array, target: jax.Array, live: jax.Array,
               num_devices: int, cap: int) -> jax.Array:
    """Scatter one shard's rows into [num_devices, cap] padded buckets.

    target/live: per-row device id and liveness. Rows are stably sorted by
    target so each bucket is contiguous; then every bucket is shifted to
    its own fixed-size slot."""
    t = jnp.where(live, target, num_devices)  # dead rows sort last
    order = jnp.argsort(t, stable=True)
    sv = jnp.take(values, order, axis=0)
    st = jnp.take(t, order)
    # row index within its bucket
    ones = jnp.ones_like(st)
    idx_in_bucket = jnp.cumsum(ones) - 1
    bucket_start = jnp.searchsorted(st, jnp.arange(num_devices + 1, dtype=jnp.int32))
    within = idx_in_bucket - jnp.take(bucket_start, st)
    # scatter into [num_devices * cap]
    flat_pos = jnp.where(
        st < num_devices, st * cap + within, num_devices * cap
    )
    out = jnp.zeros((num_devices * cap + 1,) + values.shape[1:],
                    dtype=values.dtype)
    out = out.at[flat_pos].set(sv)
    return out[:-1].reshape((num_devices, cap) + values.shape[1:])


def _bucket_live(target: jax.Array, live: jax.Array, num_devices: int,
                 cap: int) -> jax.Array:
    t = jnp.where(live, target, num_devices)
    order = jnp.argsort(t, stable=True)
    st = jnp.take(t, order)
    bucket_start = jnp.searchsorted(st, jnp.arange(num_devices + 1, dtype=jnp.int32))
    counts = bucket_start[1:] - bucket_start[:-1]  # rows per target
    return jnp.arange(cap, dtype=jnp.int32)[None, :] < counts[:, None]


def _exchange(mesh: Mesh, arrays, target, live, axis: str,
              bucket_cap: int):
    """One all_to_all pass at a fixed per-target bucket capacity.
    Returns (arrays', live', max_bucket_count) - the count lets the
    caller detect overflow without any per-row host traffic."""
    n_dev = mesh.shape[axis]

    def per_shard(target_s, live_s, *arr_s):
        target_s = target_s[0]
        live_s = live_s[0]
        outs = []
        for a in arr_s:
            b = _bucketize(a[0], target_s, live_s, n_dev, bucket_cap)
            # all_to_all: split axis 0 (targets), concat received buckets
            ex = lax.all_to_all(
                b[None], axis, split_axis=1, concat_axis=0,
                tiled=False,
            )
            outs.append(
                ex.reshape((n_dev * bucket_cap,) + a.shape[2:])[None]
            )
        lv = _bucket_live(target_s, live_s, n_dev, bucket_cap)
        lx = lax.all_to_all(
            lv[None], axis, split_axis=1, concat_axis=0, tiled=False
        )
        # rows per target bucket on this shard (before clipping to
        # bucket_cap); global max detects overflow
        t = jnp.where(live_s, target_s, n_dev)
        counts = jax.ops.segment_sum(
            jnp.ones_like(t), jnp.clip(t, 0, n_dev),
            num_segments=n_dev + 1,
        )[:n_dev]
        max_count = lax.pmax(jnp.max(counts), axis)
        return tuple(outs) + (
            lx.reshape(n_dev * bucket_cap)[None],
            max_count[None],
        )

    out_specs = tuple([P(axis)] * (len(arrays) + 1)) + (P(axis),)
    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(axis), P(axis)) + tuple(P(axis) for _ in arrays),
        out_specs=out_specs,
    )
    res = fn(target, live, *arrays)
    return list(res[:-2]), res[-2], res[-1]


def all_to_all_repartition(
    mesh: Mesh,
    arrays: Sequence[jax.Array],  # each [n_dev, cap, ...] sharded on axis 0
    target: jax.Array,  # [n_dev, cap] device ids
    live: jax.Array,  # [n_dev, cap]
    axis: str = "data",
    slack: float = 1.5,
):
    """Exchange rows so row r of shard d moves to device target[d, r].

    Returns (arrays', live') with shapes [n_dev, n_dev*bucket_cap, ...]:
    each shard's new rows are the concatenation of what every peer sent
    it; live' marks real rows.

    Buckets are sized to the expected per-target share times `slack`
    (bytes over ICI drop ~n_dev/slack vs worst-case). If any shard's
    per-target count exceeds that (skew), ONE retry runs at worst-case
    capacity - always correct, never silently lossy. slack <= 0 forces
    worst-case sizing directly."""
    n_dev = mesh.shape[axis]
    cap = target.shape[-1]
    bucket_cap = cap
    if slack > 0 and n_dev > 1:
        bucket_cap = min(
            cap, max(1, int(np.ceil(cap * slack / n_dev)))
        )
    outs, lv, max_count = _exchange(
        mesh, arrays, target, live, axis, bucket_cap
    )
    if bucket_cap < cap and int(np.max(np.asarray(max_count))) > \
            bucket_cap:
        # skew overflow: retry once at worst-case capacity
        outs, lv, _ = _exchange(mesh, arrays, target, live, axis, cap)
    return outs, lv
