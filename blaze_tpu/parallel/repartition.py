"""Intra-slice hash repartition: the ICI tier of the shuffle.

Where the reference always spills shuffle data through segmented-IPC files
(shuffle_writer_exec.rs), HBM-resident batches inside one TPU slice can be
re-bucketed with a single `lax.all_to_all` over ICI - no host round trip,
no compression, no disk (SURVEY 2.4 TPU mapping). The inter-node tier
(parallel/exchange.ShuffleExchangeExec) still uses the reference-compatible
file format.

Shape discipline: each shard sorts its rows by target device (one stable
argsort - the same counting-sort-as-sort trick as the file shuffle writer),
scatters them into per-target buckets of a fixed size, and all_to_all
exchanges the bucket axis. Bucket capacity is the full per-shard capacity
(worst case all rows target one device), which keeps the exchange correct
for any skew; a slack-factor capacity with overflow retry is the planned
optimization.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from blaze_tpu.types import DataType
from blaze_tpu.exprs.hashing import hash_columns_device, pmod


def partition_ids_for_devices(key_cols, capacity: int, num_devices: int
                              ) -> jax.Array:
    """Spark-murmur3 pmod over num_devices (per shard)."""
    h = hash_columns_device(key_cols, capacity)
    return pmod(h, num_devices)


def _bucketize(values: jax.Array, target: jax.Array, live: jax.Array,
               num_devices: int, cap: int) -> jax.Array:
    """Scatter one shard's rows into [num_devices, cap] padded buckets.

    target/live: per-row device id and liveness. Rows are stably sorted by
    target so each bucket is contiguous; then every bucket is shifted to
    its own fixed-size slot."""
    t = jnp.where(live, target, num_devices)  # dead rows sort last
    order = jnp.argsort(t, stable=True)
    sv = jnp.take(values, order, axis=0)
    st = jnp.take(t, order)
    # row index within its bucket
    ones = jnp.ones_like(st)
    idx_in_bucket = jnp.cumsum(ones) - 1
    bucket_start = jnp.searchsorted(st, jnp.arange(num_devices + 1, dtype=jnp.int32))
    within = idx_in_bucket - jnp.take(bucket_start, st)
    # scatter into [num_devices * cap]
    flat_pos = jnp.where(
        st < num_devices, st * cap + within, num_devices * cap
    )
    out = jnp.zeros((num_devices * cap + 1,) + values.shape[1:],
                    dtype=values.dtype)
    out = out.at[flat_pos].set(sv)
    return out[:-1].reshape((num_devices, cap) + values.shape[1:])


def _bucket_live(target: jax.Array, live: jax.Array, num_devices: int,
                 cap: int) -> jax.Array:
    t = jnp.where(live, target, num_devices)
    order = jnp.argsort(t, stable=True)
    st = jnp.take(t, order)
    bucket_start = jnp.searchsorted(st, jnp.arange(num_devices + 1, dtype=jnp.int32))
    counts = bucket_start[1:] - bucket_start[:-1]  # rows per target
    return jnp.arange(cap, dtype=jnp.int32)[None, :] < counts[:, None]


def all_to_all_repartition(
    mesh: Mesh,
    arrays: Sequence[jax.Array],  # each [n_dev, cap, ...] sharded on axis 0
    target: jax.Array,  # [n_dev, cap] device ids
    live: jax.Array,  # [n_dev, cap]
    axis: str = "data",
):
    """Exchange rows so row r of shard d moves to device target[d, r].

    Returns (arrays', live') with shapes [n_dev, n_dev*cap, ...]: each
    shard's new rows are the concatenation of what every peer sent it;
    live' marks real rows. One collective on ICI."""
    n_dev = mesh.shape[axis]
    cap = target.shape[-1]

    def per_shard(target_s, live_s, *arr_s):
        target_s = target_s[0]
        live_s = live_s[0]
        outs = []
        for a in arr_s:
            b = _bucketize(a[0], target_s, live_s, n_dev, cap)
            # all_to_all: split axis 0 (targets), concat received buckets
            ex = lax.all_to_all(
                b[None], axis, split_axis=1, concat_axis=0,
                tiled=False,
            )
            outs.append(ex.reshape((n_dev * cap,) + a.shape[2:])[None])
        lv = _bucket_live(target_s, live_s, n_dev, cap)
        lx = lax.all_to_all(
            lv[None], axis, split_axis=1, concat_axis=0, tiled=False
        )
        return tuple(outs) + (lx.reshape(n_dev * cap)[None],)

    in_specs = tuple([P(axis)] * (2 + len(arrays)))
    out_specs = tuple([P(axis)] * (len(arrays) + 1))
    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(axis), P(axis)) + tuple(P(axis) for _ in arrays),
        out_specs=out_specs,
    )
    res = fn(target, live, *arrays)
    return list(res[:-1]), res[-1]
