"""Host-level exchange operators: the inter-node (DCN/disk) shuffle tier.

These play Spark's orchestration role locally, the way the reference's
local-mode TPC-DS CI exercises its full shuffle path in one process
(SURVEY 4): a ShuffleExchange lazily runs the map stage (one
ShuffleWriterExec per input partition -> reference-format .data/.index
files), then serves reduce partitions as FileSegment reads; a
BroadcastExchange collects the child once as compressed IPC parts and
replays them to every consumer partition (reference
ArrowBroadcastExchangeExec.scala:139-256).

CoalescedShuffleReader maps AQE-style partition specs (coalesced ranges)
onto the same files (reference NativeSupports.scala:131-212).
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.io.ipc import partition_ranges
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.ipc_reader import FileSegment, IpcReaderExec, IpcReadMode
from blaze_tpu.ops.ipc_writer import collect_ipc
from blaze_tpu.ops.shuffle_writer import ShuffleWriterExec


class ShuffleExchangeExec(PhysicalOp):
    """Full repartitioning exchange (reference
    ArrowShuffleExchangeExec301.scala): hash / single / round_robin."""

    def __init__(self, child: PhysicalOp, keys: Sequence[ir.Expr],
                 num_partitions: int, mode: str = "hash",
                 shuffle_dir: Optional[str] = None):
        self.children = [child]
        self.keys = list(keys)
        self.num_partitions = num_partitions
        self.mode = mode
        self.shuffle_dir = shuffle_dir
        self._map_outputs: Optional[List[Tuple[str, str]]] = None
        self._lock = threading.Lock()

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return self.num_partitions

    # ------------------------------------------------------------------
    def _run_map_stage(self, ctx: ExecContext) -> List[Tuple[str, str]]:
        with self._lock:
            if self._map_outputs is not None:
                return self._map_outputs
            child = self.children[0]
            d = self.shuffle_dir or tempfile.mkdtemp(prefix="blz-shuffle-")
            os.makedirs(d, exist_ok=True)
            outputs = []
            for map_id in range(child.partition_count):
                data = os.path.join(d, f"shuffle_{id(self):x}_{map_id}_0.data")
                index = os.path.join(
                    d, f"shuffle_{id(self):x}_{map_id}_0.index"
                )
                writer = ShuffleWriterExec(
                    child, self.keys, self.num_partitions, data, index,
                    self.mode,
                )
                for _ in writer.execute(map_id, ctx):
                    pass
                outputs.append((data, index))
            self._map_outputs = outputs
            return outputs

    def segments_for(self, partition_range: Tuple[int, int],
                     ctx: ExecContext) -> List[FileSegment]:
        """FileSegments covering [start, end) reduce partitions across all
        map outputs (AQE coalesced reads use ranges > 1 wide)."""
        start, end = partition_range
        segs = []
        for data, index in self._run_map_stage(ctx):
            ranges = partition_ranges(index)
            for p in range(start, end):
                off, length = ranges[p]
                if length > 0:
                    segs.append(FileSegment(data, off, length))
        return segs

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.io.ipc import read_file_segment

        for seg in self.segments_for((partition, partition + 1), ctx):
            for rb in read_file_segment(seg.path, seg.offset, seg.length):
                yield ColumnBatch.from_arrow(rb)


class CoalescedShuffleReader(PhysicalOp):
    """AQE-style reader over a ShuffleExchange: each output partition maps
    to a contiguous range of reduce partitions (reference
    CustomShuffleReaderExec handling, NativeSupports.scala:131-212)."""

    def __init__(self, exchange: ShuffleExchangeExec,
                 partition_ranges_: Sequence[Tuple[int, int]]):
        self.children = [exchange]
        self.ranges = list(partition_ranges_)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return len(self.ranges)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.io.ipc import read_file_segment

        ex: ShuffleExchangeExec = self.children[0]
        for seg in ex.segments_for(self.ranges[partition], ctx):
            for rb in read_file_segment(seg.path, seg.offset, seg.length):
                yield ColumnBatch.from_arrow(rb)


class BroadcastExchangeExec(PhysicalOp):
    """Collect-once, replay-everywhere broadcast (reference
    ArrowBroadcastExchangeExec: native IPC collect -> spark broadcast ->
    per-task CHANNEL reads)."""

    def __init__(self, child: PhysicalOp,
                 num_partitions: Optional[int] = None):
        self.children = [child]
        self._parts: Optional[List[bytes]] = None
        self._n = num_partitions
        self._lock = threading.Lock()

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return self._n or self.children[0].partition_count

    def broadcast_bytes(self, ctx: ExecContext) -> List[bytes]:
        with self._lock:
            if self._parts is None:
                self._parts = collect_ipc(self.children[0], ctx)
            return self._parts

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.io.ipc import decode_ipc_parts

        for part in self.broadcast_bytes(ctx):
            for rb in decode_ipc_parts(part):
                yield ColumnBatch.from_arrow(rb)
