"""Host-level exchange operators: the inter-node (DCN/disk) shuffle tier.

These play Spark's orchestration role locally, the way the reference's
local-mode TPC-DS CI exercises its full shuffle path in one process
(SURVEY 4): a ShuffleExchange lazily runs the map stage (one
ShuffleWriterExec per input partition -> reference-format .data/.index
files), then serves reduce partitions as FileSegment reads; a
BroadcastExchange collects the child once as compressed IPC parts and
replays them to every consumer partition (reference
ArrowBroadcastExchangeExec.scala:139-256).

CoalescedShuffleReader maps AQE-style partition specs (coalesced ranges)
onto the same files (reference NativeSupports.scala:131-212).
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from blaze_tpu.types import Schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.io.ipc import partition_ranges
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.ipc_reader import FileSegment, IpcReaderExec, IpcReadMode
from blaze_tpu.ops.ipc_writer import collect_ipc
from blaze_tpu.ops.shuffle_writer import ShuffleWriterExec


class _SampledReplay(PhysicalOp):
    """One-shot child stand-in for a range map task: yields the batches
    the sampling pass already pulled, then resumes the same iterator -
    so the child subtree runs once overall instead of once for the
    sample and once for the map."""

    def __init__(self, child: PhysicalOp, partition: int,
                 consumed: list, it):
        self.children = [child]
        self._partition = partition
        self._consumed = consumed
        self._it = it

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return self.children[0].partition_count

    def execute(self, partition: int, ctx: ExecContext):
        assert partition == self._partition
        yield from self._consumed
        yield from self._it


class ShuffleExchangeExec(PhysicalOp):
    """Full repartitioning exchange (reference
    ArrowShuffleExchangeExec301.scala): hash / single / round_robin."""

    SAMPLE_ROWS_PER_PARTITION = 10_000

    def __init__(self, child: PhysicalOp, keys: Sequence[ir.Expr],
                 num_partitions: int, mode: str = "hash",
                 shuffle_dir: Optional[str] = None,
                 sort_ascending: Optional[Sequence[bool]] = None):
        self.children = [child]
        self.keys = list(keys)
        self.num_partitions = num_partitions
        self.mode = mode
        self.shuffle_dir = shuffle_dir
        self.sort_ascending = list(
            sort_ascending
            if sort_ascending is not None
            else [True] * len(keys)
        )
        self._map_outputs: Optional[List[Tuple[str, str]]] = None
        self._range_bounds: Optional[List[Tuple]] = None
        self._sample_replay: dict = {}
        self._lock = threading.Lock()

    def _compute_range_bounds(self, ctx: ExecContext) -> List[Tuple]:
        """Driver-side sampling pass (Spark runs a sample job the same
        way for RangePartitioning): pull up to SAMPLE_ROWS_PER_PARTITION
        key rows from each child partition, derive quantile bounds."""
        if self._range_bounds is not None:
            return self._range_bounds
        import pandas as pd

        from blaze_tpu.ops.shuffle_writer import (
            _key_array_for_range,
            compute_range_bounds,
        )

        child = self.children[0]
        frames = []
        self._sample_replay = {}
        for p in range(child.partition_count):
            taken = 0
            consumed = []
            it = child.execute(p, ctx)
            for cb in it:
                from blaze_tpu.ops.util import ensure_compacted

                cb = ensure_compacted(cb)
                consumed.append(cb)
                if cb.num_rows == 0:
                    continue
                rb = cb.to_arrow()
                cols = {
                    f"k{i}": _key_array_for_range(rb, cb, e)
                    for i, e in enumerate(self.keys)
                }
                frames.append(pd.DataFrame(cols))
                taken += cb.num_rows
                if taken >= self.SAMPLE_ROWS_PER_PARTITION:
                    break
            # the map stage replays what the sample pass already pulled
            # and continues the same iterator - the child subtree is
            # executed ONCE, not twice (Spark re-runs the scan for its
            # sample job; we keep the batches, they are already here)
            self._sample_replay[p] = (consumed, it)
        sample = (
            pd.concat(frames, ignore_index=True)
            if frames
            else pd.DataFrame(
                {f"k{i}": [] for i in range(len(self.keys))}
            )
        )
        self._range_bounds = compute_range_bounds(
            sample, self.num_partitions, self.sort_ascending
        )
        return self._range_bounds

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return self.num_partitions

    # ------------------------------------------------------------------
    MAX_TASK_ATTEMPTS = 3  # Spark-style task retry (SURVEY 5.3: the
    # reference leans on Spark's task/stage retry as its recovery layer)

    def _run_map_stage(self, ctx: ExecContext) -> List[Tuple[str, str]]:
        with self._lock:
            if self._map_outputs is not None:
                return self._map_outputs
            import concurrent.futures as cf

            child = self.children[0]
            d = self.shuffle_dir or tempfile.mkdtemp(prefix="blz-shuffle-")
            os.makedirs(d, exist_ok=True)
            bounds = (
                self._compute_range_bounds(ctx)
                if self.mode == "range"
                else None
            )

            def run_map(map_id: int) -> Tuple[str, str]:
                data = os.path.join(
                    d, f"shuffle_{id(self):x}_{map_id}_0.data"
                )
                index = os.path.join(
                    d, f"shuffle_{id(self):x}_{map_id}_0.index"
                )
                last_err = None
                for attempt in range(self.MAX_TASK_ATTEMPTS):
                    try:
                        # first attempt of a range map task resumes the
                        # sample pass's iterator (one child execution
                        # total); a retry pops nothing and re-executes
                        # the child from scratch
                        src = child
                        replay = self._sample_replay.pop(map_id, None)
                        if replay is not None:
                            src = _SampledReplay(child, map_id, *replay)
                        writer = ShuffleWriterExec(
                            src, self.keys, self.num_partitions,
                            data, index, self.mode,
                            range_bounds=bounds,
                            sort_ascending=self.sort_ascending,
                        )
                        for _ in writer.execute(map_id, ctx):
                            pass
                        return (data, index)
                    except Exception as e:  # retry like a Spark task
                        last_err = e
                        ctx.metrics.add("task_retries", 1)
                raise last_err  # type: ignore[misc]

            # map tasks run concurrently like Spark executor threads
            # (device dispatch is async; host encode/IO overlaps)
            n = child.partition_count
            from blaze_tpu.runtime.dispatch import task_threads

            with cf.ThreadPoolExecutor(
                max_workers=task_threads(n)
            ) as pool:
                outputs = list(pool.map(run_map, range(n)))
            self._map_outputs = outputs
            return outputs

    def map_output_statistics(self, ctx: ExecContext) -> List[int]:
        """Bytes per reduce partition, summed over map outputs - what the
        reference feeds AQE through mapOutputStatisticsFuture
        (ArrowShuffleExchangeExec301.scala:104-130)."""
        sizes = [0] * self.num_partitions
        for _, index in self._run_map_stage(ctx):
            for p, (_, length) in enumerate(partition_ranges(index)):
                sizes[p] += length
        return sizes

    def segments_for(self, partition_range: Tuple[int, int],
                     ctx: ExecContext,
                     map_range: Optional[Tuple[int, int]] = None
                     ) -> List[FileSegment]:
        """FileSegments covering [start, end) reduce partitions across the
        given range of map outputs (all by default). Reduce-range > 1 wide
        = AQE CoalescedPartitionSpec; map-range narrower than all maps =
        PartialReducerPartitionSpec (skew split) / PartialMapper
        (NativeSupports.scala:131-212 spec handling)."""
        start, end = partition_range
        outputs = self._run_map_stage(ctx)
        if map_range is not None:
            outputs = outputs[map_range[0]: map_range[1]]
        segs = []
        for data, index in outputs:
            ranges = partition_ranges(index)
            for p in range(start, end):
                off, length = ranges[p]
                if length > 0:
                    segs.append(FileSegment(data, off, length))
        return segs

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.io.ipc import read_file_segment

        for seg in self.segments_for((partition, partition + 1), ctx):
            for rb in read_file_segment(seg.path, seg.offset, seg.length):
                yield ColumnBatch.from_arrow(rb)


class ClusterShuffleExchangeExec(ShuffleExchangeExec):
    """ShuffleExchange whose map stage runs on a MiniCluster: map tasks
    ship as serialized TaskDefinitions to worker processes (the Spark-
    driver role for multi-host runs); the reduce side reads the same
    .data/.index files. The child subtree must be serializable
    (plan/serde surface)."""

    def __init__(self, child: PhysicalOp, keys, num_partitions: int,
                 cluster, mode: str = "hash",
                 shuffle_dir: Optional[str] = None):
        super().__init__(child, keys, num_partitions, mode, shuffle_dir)
        self.cluster = cluster

    def _run_map_stage(self, ctx: ExecContext):
        with self._lock:
            if self._map_outputs is not None:
                return self._map_outputs
            from blaze_tpu.ops.shuffle_writer import ShuffleWriterExec
            from blaze_tpu.plan.serde import task_to_proto

            child = self.children[0]
            d = self.shuffle_dir or tempfile.mkdtemp(
                prefix="blz-cshuffle-"
            )
            os.makedirs(d, exist_ok=True)
            bounds = (
                self._compute_range_bounds(ctx)
                if self.mode == "range"
                else None
            )
            tasks = []
            outputs = []
            for map_id in range(child.partition_count):
                data = os.path.join(d, f"cm{map_id}.data")
                index = os.path.join(d, f"cm{map_id}.index")
                outputs.append((data, index))
                plan = ShuffleWriterExec(
                    child, self.keys, self.num_partitions, data, index,
                    self.mode,
                    range_bounds=bounds,
                    sort_ascending=self.sort_ascending,
                )
                tasks.append(
                    task_to_proto(plan, map_id, f"map-{map_id}")
                )
            self.cluster.run_tasks(tasks)
            self._map_outputs = outputs
            return outputs


class RemoteClusterShuffleExchangeExec(ClusterShuffleExchangeExec):
    """Cluster exchange over DISJOINT per-worker data directories: the
    driver does not know (or share) where map outputs land. Map tasks
    carry __WORKER_LOCAL__ shuffle paths that the claiming worker
    rewrites into its private directory; its completion metadata reports
    (host, port, path, per-partition ranges), and reduce reads stream
    every block over the workers' BlockServers - the reference's
    netty remote-fetch path (ArrowBlockStoreShuffleReader301.scala:
    83-123) rather than its local-FileSegment shortcut."""

    def _run_map_stage(self, ctx: ExecContext):
        with self._lock:
            if self._map_outputs is not None:
                return self._map_outputs
            from blaze_tpu.ops.shuffle_writer import ShuffleWriterExec
            from blaze_tpu.plan.serde import task_to_proto
            from blaze_tpu.runtime.cluster import WORKER_LOCAL_PREFIX

            child = self.children[0]
            bounds = (
                self._compute_range_bounds(ctx)
                if self.mode == "range"
                else None
            )
            tasks = []
            tag = f"{id(self):x}"
            for map_id in range(child.partition_count):
                plan = ShuffleWriterExec(
                    child, self.keys, self.num_partitions,
                    f"{WORKER_LOCAL_PREFIX}/ex{tag}_m{map_id}.data",
                    f"{WORKER_LOCAL_PREFIX}/ex{tag}_m{map_id}.index",
                    self.mode,
                    range_bounds=bounds,
                    sort_ascending=self.sort_ascending,
                )
                tasks.append(
                    task_to_proto(plan, map_id, f"map-{map_id}")
                )
            _, metas = self.cluster.run_tasks(tasks, return_metas=True)
            self._map_outputs = metas
            return metas

    def segments_for(self, partition_range: Tuple[int, int],
                     ctx: ExecContext,
                     map_range: Optional[Tuple[int, int]] = None):
        from blaze_tpu.runtime.transport import RemoteSegment

        start, end = partition_range
        metas = self._run_map_stage(ctx)
        if map_range is not None:
            metas = metas[map_range[0]: map_range[1]]
        segs = []
        for meta in metas:
            for out in meta["outputs"]:
                for p in range(start, end):
                    off, length = out["ranges"][p]
                    if length > 0:
                        segs.append(
                            RemoteSegment(
                                meta["host"], meta["port"],
                                out["data"], off, length,
                            )
                        )
        return segs

    def map_output_statistics(self, ctx: ExecContext) -> List[int]:
        sizes = [0] * self.num_partitions
        for meta in self._run_map_stage(ctx):
            for out in meta["outputs"]:
                for p, (_, length) in enumerate(out["ranges"]):
                    sizes[p] += length
        return sizes

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.runtime.transport import iter_remote_batches

        for seg in self.segments_for((partition, partition + 1), ctx):
            for rb in iter_remote_batches(seg):
                yield ColumnBatch.from_arrow(rb)


class CoalescedShuffleReader(PhysicalOp):
    """AQE-style reader over a ShuffleExchange: each output partition maps
    to a (reduce-range, map-range) spec (reference CustomShuffleReaderExec
    handling, NativeSupports.scala:131-212):
    - (start, end) with full map range  = CoalescedPartitionSpec
    - single reduce + partial map range = PartialReducerPartitionSpec
      (skew-join split)
    """

    def __init__(self, exchange: ShuffleExchangeExec,
                 partition_ranges_: Sequence[Tuple[int, int]],
                 map_ranges: Optional[Sequence[Optional[Tuple[int, int]]]]
                 = None):
        self.children = [exchange]
        self.ranges = list(partition_ranges_)
        self.map_ranges = (
            list(map_ranges) if map_ranges is not None
            else [None] * len(self.ranges)
        )

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return len(self.ranges)

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.io.ipc import read_file_segment
        from blaze_tpu.runtime.transport import (
            RemoteSegment,
            iter_remote_batches,
        )

        ex: ShuffleExchangeExec = self.children[0]
        for seg in ex.segments_for(
            self.ranges[partition], ctx, self.map_ranges[partition]
        ):
            if isinstance(seg, RemoteSegment):
                # remote-exchange segments stream over the BlockServer;
                # their paths live in another process's private dir
                for rb in iter_remote_batches(seg):
                    yield ColumnBatch.from_arrow(rb)
            else:
                for rb in read_file_segment(
                    seg.path, seg.offset, seg.length
                ):
                    yield ColumnBatch.from_arrow(rb)


def plan_coalesced_partitions(sizes: Sequence[int], target_bytes: int
                              ) -> List[Tuple[int, int]]:
    """AQE partition coalescing: greedily pack adjacent reduce partitions
    up to ~target_bytes (what Spark's CoalesceShufflePartitions does with
    the stats the exchange reports)."""
    ranges: List[Tuple[int, int]] = []
    start = 0
    acc = 0
    for i, s in enumerate(sizes):
        if acc > 0 and acc + s > target_bytes:
            ranges.append((start, i))
            start = i
            acc = 0
        acc += s
    if start < len(sizes):
        ranges.append((start, len(sizes)))
    return ranges


class BroadcastExchangeExec(PhysicalOp):
    """Collect-once, replay-everywhere broadcast (reference
    ArrowBroadcastExchangeExec: native IPC collect -> spark broadcast ->
    per-task CHANNEL reads)."""

    is_broadcast = True  # every partition replays the full relation

    def __init__(self, child: PhysicalOp,
                 num_partitions: Optional[int] = None):
        self.children = [child]
        self._parts: Optional[List[bytes]] = None
        self._n = num_partitions
        self._lock = threading.Lock()

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def partition_count(self) -> int:
        return self._n or self.children[0].partition_count

    def broadcast_bytes(self, ctx: ExecContext) -> List[bytes]:
        with self._lock:
            if self._parts is None:
                self._parts = collect_ipc(self.children[0], ctx)
            return self._parts

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        from blaze_tpu.io.ipc import decode_ipc_parts

        for part in self.broadcast_bytes(ctx):
            for rb in decode_ipc_parts(part):
                yield ColumnBatch.from_arrow(rb)
