"""Mesh-sharded query execution: whole pipeline stages as ONE pjit'd
program over the device mesh.

This is the intra-slice fast path (SURVEY 2.4 TPU mapping): N query
partitions execute simultaneously, one per device on the mesh 'data' axis,
inside a single XLA program; the repartitioning exchange between a partial
and a final aggregate is a `lax.all_to_all` on ICI instead of the
segmented-IPC file shuffle. The file tier (parallel/exchange) remains the
fabric between hosts - this module replaces it only within a slice.

`DistributedGroupBy` is the flagship distributed step: per-shard
filter -> project -> partial sort-based aggregate, hash repartition of the
partial states by group key over ICI, per-shard final merge. One jit, no
host round-trips - the engine's equivalent of a "training step" for
__graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax exposes it under experimental
    from jax.experimental.shard_map import shard_map

from blaze_tpu.types import DataType, Schema, TypeId
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.optimize import bind_opt
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.exprs.hashing import hash_columns_device, pmod
from blaze_tpu.exprs.ir import AggFn
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.parallel.repartition import _bucket_live, _bucketize


@dataclasses.dataclass(frozen=True)
class DistAgg:
    fn: AggFn  # SUM / COUNT / COUNT_STAR / MIN / MAX / AVG
    expr: Optional[ir.Expr]  # bound against input schema; None for COUNT_*


class DistributedGroupBy:
    """filter -> project-keys -> partial agg -> ICI repartition -> final.

    All group-key dtypes must be device-hashable (ints/dates/f32/bool);
    string keys go through the file-shuffle tier instead (host hashing).
    """

    def __init__(self, mesh: Mesh, schema: Schema,
                 keys: Sequence[ir.Expr],
                 aggs: Sequence[DistAgg],
                 filter_pred: Optional[ir.Expr] = None,
                 axis: str = "data"):
        self.mesh = mesh
        self.axis = axis
        self.schema = schema
        self.keys = [bind_opt(k, schema) for k in keys]
        self.aggs = [
            DistAgg(a.fn, bind_opt(a.expr, schema)
                    if a.expr is not None else None)
            for a in aggs
        ]
        self.filter_pred = (
            bind_opt(filter_pred, schema) if filter_pred is not None else None
        )
        self._fn = None
        self._exec = None  # AOT-compiled executable (prepare())
        self._exec_sig = None
        self._traced_sigs = set()

    # ------------------------------------------------------------------
    def _sig(self, stacked_cols, num_rows) -> Tuple:
        return (
            tuple((tuple(c.shape), str(c.dtype)) for c in stacked_cols),
            (tuple(num_rows.shape), str(num_rows.dtype)),
        )

    def prepare(self, stacked_cols: Sequence[jax.Array],
                num_rows: jax.Array) -> bool:
        """Trace + compile ahead of the launch (jax AOT `lower().compile()`)
        so the caller can time the trace as its own sub-phase. Returns True
        iff a trace actually ran (first time this instance sees this arg
        signature); a warm repeat is a no-op returning False. Where the
        installed jax lacks the AOT path the jitted function stays in
        place and the first launch folds the trace (mesh_trace ~ 0)."""
        sig = self._sig(stacked_cols, num_rows)
        if self._fn is None:
            self._fn = self._compile(
                tuple(c.shape for c in stacked_cols),
                tuple(c.dtype for c in stacked_cols),
            )
        if sig in self._traced_sigs:
            return False
        self._traced_sigs.add(sig)
        try:
            self._exec = self._fn.lower(
                *stacked_cols, num_rows
            ).compile()
            self._exec_sig = sig
        except Exception:  # noqa: BLE001 - AOT unsupported: trace at launch
            self._exec = None
            self._exec_sig = None
        return True

    def __call__(self, stacked_cols: Sequence[jax.Array],
                 num_rows: jax.Array):
        """stacked_cols: [n_dev, cap] per input column (sharded or
        shardable on axis 0); num_rows: [n_dev] live rows per shard.
        Returns (key_out, agg_out, group_counts): stacked [n_dev, ...] with
        group_counts[d] = groups owned by device d."""
        if self._fn is None:
            self._fn = self._compile(
                tuple(c.shape for c in stacked_cols),
                tuple(c.dtype for c in stacked_cols),
            )
        if (self._exec is not None
                and self._exec_sig == self._sig(stacked_cols, num_rows)):
            return self._exec(*stacked_cols, num_rows)
        return self._fn(*stacked_cols, num_rows)

    # ------------------------------------------------------------------
    def _compile(self, shapes, dtypes):
        mesh, axis = self.mesh, self.axis
        n_dev = mesh.shape[axis]
        schema = self.schema
        keys = self.keys
        aggs = self.aggs
        pred = self.filter_pred
        n_keys = len(keys)

        def group_reduce(key_vals: List[jax.Array],
                         agg_ins: List[jax.Array],
                         live: jax.Array, cap: int):
            """Sort-based segmented reduce of one shard's rows.

            Returns (sorted key cols at boundaries, reduced states,
            n_groups, live_groups mask)."""
            pri = [jnp.where(live, 0, 1).astype(jnp.int8)]
            for k in key_vals:
                if jnp.issubdtype(k.dtype, jnp.floating):
                    pri.append(jnp.where(jnp.isnan(k), jnp.inf, k))
                    pri.append(jnp.isnan(k).astype(jnp.int8))
                else:
                    pri.append(k)
            order = jnp.lexsort(tuple(reversed(pri)))
            s_live = jnp.take(live, order)
            diff = jnp.zeros(cap, dtype=jnp.bool_)
            s_keys = []
            for k in key_vals:
                sk = jnp.take(k, order)
                s_keys.append(sk)
                if jnp.issubdtype(k.dtype, jnp.floating):
                    # NaN groups with NaN, distinct from real +inf
                    nf = jnp.take(jnp.isnan(k).astype(jnp.int8), order)
                    cv = jnp.where(jnp.isnan(sk), jnp.inf, sk)
                    diff = diff | (
                        cv != jnp.concatenate([cv[:1], cv[:-1]])
                    ) | (nf != jnp.concatenate([nf[:1], nf[:-1]]))
                else:
                    diff = diff | (
                        sk != jnp.concatenate([sk[:1], sk[:-1]])
                    )
            first = s_live & ~jnp.concatenate(
                [jnp.zeros(1, dtype=jnp.bool_), s_live[:-1]]
            )
            boundary = s_live & (diff | first)
            gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
            gid = jnp.where(s_live, gid, cap - 1)
            n_groups = jnp.sum(boundary.astype(jnp.int32))
            bpos = jnp.nonzero(boundary, size=cap, fill_value=0)[0]
            out_keys = [jnp.take(sk, bpos) for sk in s_keys]
            states = []
            for (a, x) in zip(aggs, agg_ins):
                sx = jnp.take(x, order) if x is not None else None
                if a.fn in (AggFn.COUNT, AggFn.COUNT_STAR):
                    states.append(
                        jax.ops.segment_sum(
                            s_live.astype(jnp.int64), gid,
                            num_segments=cap,
                        )
                    )
                elif a.fn in (AggFn.SUM, AggFn.AVG):
                    v = jnp.where(s_live, sx, jnp.zeros_like(sx))
                    states.append(
                        jax.ops.segment_sum(v, gid, num_segments=cap)
                    )
                    if a.fn is AggFn.AVG:
                        states.append(
                            jax.ops.segment_sum(
                                s_live.astype(jnp.int64), gid,
                                num_segments=cap,
                            )
                        )
                elif a.fn in (AggFn.MIN, AggFn.MAX):
                    if jnp.issubdtype(sx.dtype, jnp.floating):
                        neutral = jnp.inf if a.fn is AggFn.MIN else -jnp.inf
                    else:
                        info = jnp.iinfo(sx.dtype)
                        neutral = (
                            info.max if a.fn is AggFn.MIN else info.min
                        )
                    v = jnp.where(s_live, sx, jnp.asarray(neutral, sx.dtype))
                    red = (jax.ops.segment_min if a.fn is AggFn.MIN
                           else jax.ops.segment_max)
                    states.append(red(v, gid, num_segments=cap))
                else:
                    raise NotImplementedError(a.fn)
            live_groups = jnp.arange(cap, dtype=jnp.int32) < n_groups
            return out_keys, states, n_groups, live_groups

        def merge_reduce(key_vals, states_in, live, cap):
            """Final merge: same grouping, states combine by their merge op
            (sum for SUM/COUNT/AVG parts, min/max for MIN/MAX)."""
            pri = [jnp.where(live, 0, 1).astype(jnp.int8)]
            for k in key_vals:
                if jnp.issubdtype(k.dtype, jnp.floating):
                    pri.append(jnp.where(jnp.isnan(k), jnp.inf, k))
                    pri.append(jnp.isnan(k).astype(jnp.int8))
                else:
                    pri.append(k)
            order = jnp.lexsort(tuple(reversed(pri)))
            s_live = jnp.take(live, order)
            diff = jnp.zeros(cap, dtype=jnp.bool_)
            s_keys = []
            for k in key_vals:
                sk = jnp.take(k, order)
                s_keys.append(sk)
                if jnp.issubdtype(k.dtype, jnp.floating):
                    # NaN groups with NaN, distinct from real +inf
                    nf = jnp.take(jnp.isnan(k).astype(jnp.int8), order)
                    cv = jnp.where(jnp.isnan(sk), jnp.inf, sk)
                    diff = diff | (
                        cv != jnp.concatenate([cv[:1], cv[:-1]])
                    ) | (nf != jnp.concatenate([nf[:1], nf[:-1]]))
                else:
                    diff = diff | (
                        sk != jnp.concatenate([sk[:1], sk[:-1]])
                    )
            first = s_live & ~jnp.concatenate(
                [jnp.zeros(1, dtype=jnp.bool_), s_live[:-1]]
            )
            boundary = s_live & (diff | first)
            gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
            gid = jnp.where(s_live, gid, cap - 1)
            n_groups = jnp.sum(boundary.astype(jnp.int32))
            bpos = jnp.nonzero(boundary, size=cap, fill_value=0)[0]
            out_keys = [jnp.take(sk, bpos) for sk in s_keys]
            out_states = []
            si = 0
            for a in aggs:
                width = 2 if a.fn is AggFn.AVG else 1
                for w in range(width):
                    x = jnp.take(states_in[si], order)
                    if a.fn in (AggFn.MIN, AggFn.MAX) and w == 0:
                        if jnp.issubdtype(x.dtype, jnp.floating):
                            neutral = (jnp.inf if a.fn is AggFn.MIN
                                       else -jnp.inf)
                        else:
                            info = jnp.iinfo(x.dtype)
                            neutral = (info.max if a.fn is AggFn.MIN
                                       else info.min)
                        v = jnp.where(s_live, x,
                                      jnp.asarray(neutral, x.dtype))
                        red = (jax.ops.segment_min if a.fn is AggFn.MIN
                               else jax.ops.segment_max)
                        out_states.append(
                            red(v, gid, num_segments=cap)
                        )
                    else:
                        v = jnp.where(s_live, x, jnp.zeros_like(x))
                        out_states.append(
                            jax.ops.segment_sum(v, gid, num_segments=cap)
                        )
                    si += 1
            return out_keys, out_states, n_groups

        def per_shard(num_rows_s, *cols_s):
            cols = [c[0] for c in cols_s]
            nr = num_rows_s[0]
            cap = cols[0].shape[0]
            ev = DeviceEvaluator(
                schema, [(c, None) for c in cols], cap
            )
            live = jnp.arange(cap, dtype=jnp.int32) < nr
            if pred is not None:
                live = live & ev.evaluate_predicate(pred)
            key_vals = [ev.evaluate(k)[0] for k in keys]
            agg_ins = [
                ev.evaluate(a.expr)[0] if a.expr is not None else None
                for a in aggs
            ]
            out_keys, states, _, live_g = group_reduce(
                key_vals, agg_ins, live, cap
            )
            # ---- ICI repartition of partial groups by key hash ----
            kcols = [
                (k, None, _key_dtype(keys[i], schema))
                for i, k in enumerate(out_keys)
            ]
            target = pmod(hash_columns_device(kcols, cap), n_dev)
            payload = out_keys + states
            exchanged = []
            for arr in payload:
                b = _bucketize(arr, target, live_g, n_dev, cap)
                ex = lax.all_to_all(
                    b[None], axis, split_axis=1, concat_axis=0
                )
                exchanged.append(ex.reshape(n_dev * cap))
            lv = _bucket_live(target, live_g, n_dev, cap)
            lx = lax.all_to_all(
                lv[None], axis, split_axis=1, concat_axis=0
            ).reshape(n_dev * cap)
            # ---- final merge on the owning shard ----
            big = n_dev * cap
            fk, fs, ng = merge_reduce(
                exchanged[:n_keys], exchanged[n_keys:], lx, big
            )
            # finalize AVG into a float column
            final_cols = []
            si = 0
            for a in aggs:
                if a.fn is AggFn.AVG:
                    s, c = fs[si], fs[si + 1]
                    final_cols.append(
                        s.astype(jnp.float64)
                        / jnp.maximum(c, 1).astype(jnp.float64)
                    )
                    si += 2
                else:
                    final_cols.append(fs[si])
                    si += 1
            return (
                tuple(k[None, :] for k in fk)
                + tuple(c[None, :] for c in final_cols)
                + (ng[None],)
            )

        n_out = n_keys + len(aggs) + 1
        fn = shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(axis),) + tuple(P(axis) for _ in shapes),
            out_specs=tuple([P(axis)] * n_out),
        )

        @jax.jit
        def run(*args):
            num_rows = args[-1]
            cols = args[:-1]
            outs = fn(num_rows, *cols)
            return (
                list(outs[:n_keys]),
                list(outs[n_keys:-1]),
                outs[-1],
            )

        return run


class DistributedBroadcastJoin:
    """Mesh-wide broadcast equi-join against a unique-key build side.

    The intra-slice analog of the broadcast hash join (reference BHJ /
    CollectLeft): the build relation is sharded over the mesh, replicated
    to every device with ONE lax.all_gather over ICI, sorted once, and
    each shard probes its rows with searchsorted - all inside a single
    pjit program, no host round trips. Build keys must be unique (the
    dimension-table case: every probe row matches at most one build row),
    which keeps output shapes static; general many-match joins go through
    the host-tier join (ops/joins.py).
    """

    def __init__(self, mesh: Mesh, probe_schema: Schema,
                 build_schema: Schema, probe_key: ir.Expr,
                 build_key: ir.Expr, axis: str = "data"):
        self.mesh = mesh
        self.axis = axis
        self.probe_schema = probe_schema
        self.build_schema = build_schema
        self.probe_key = bind_opt(probe_key, probe_schema)
        self.build_key = bind_opt(build_key, build_schema)
        self._fn = None
        self._exec = None  # AOT-compiled executable (prepare())
        self._exec_sig = None
        self._traced_sigs = set()

    @staticmethod
    def _sig(probe_cols, probe_rows, build_cols, build_rows) -> Tuple:
        return (
            tuple((tuple(c.shape), str(c.dtype)) for c in probe_cols),
            (tuple(probe_rows.shape), str(probe_rows.dtype)),
            tuple((tuple(c.shape), str(c.dtype)) for c in build_cols),
            (tuple(build_rows.shape), str(build_rows.dtype)),
        )

    def prepare(self, probe_cols, probe_rows, build_cols,
                build_rows) -> bool:
        """AOT trace+compile (see DistributedGroupBy.prepare): True iff
        a trace actually ran for this argument signature."""
        sig = self._sig(probe_cols, probe_rows, build_cols, build_rows)
        if self._fn is None:
            self._fn = self._compile()
        if sig in self._traced_sigs:
            return False
        self._traced_sigs.add(sig)
        try:
            self._exec = self._fn.lower(
                probe_cols, probe_rows, build_cols, build_rows
            ).compile()
            self._exec_sig = sig
        except Exception:  # noqa: BLE001 - AOT unsupported: trace at launch
            self._exec = None
            self._exec_sig = None
        return True

    def __call__(self, probe_cols, probe_rows, build_cols, build_rows):
        """probe_cols/build_cols: [n_dev, cap] stacked arrays per column;
        *_rows: [n_dev] live counts. Returns (probe_cols, matched mask,
        gathered build cols) all stacked [n_dev, cap_probe]."""
        if self._fn is None:
            self._fn = self._compile()
        if (self._exec is not None and self._exec_sig == self._sig(
                probe_cols, probe_rows, build_cols, build_rows)):
            return self._exec(
                probe_cols, probe_rows, build_cols, build_rows
            )
        return self._fn(probe_cols, probe_rows, build_cols, build_rows)

    def _compile(self):
        mesh, axis = self.mesh, self.axis
        n_dev = mesh.shape[axis]
        p_schema, b_schema = self.probe_schema, self.build_schema
        p_key, b_key = self.probe_key, self.build_key

        def per_shard(p_rows_s, b_rows_s, *cols_s):
            np_cols = len(p_schema)
            p_cols = [c[0] for c in cols_s[:np_cols]]
            b_cols = [c[0] for c in cols_s[np_cols:]]
            p_cap = p_cols[0].shape[0]
            b_cap = b_cols[0].shape[0]
            # replicate the build side over ICI
            g_cols = [
                lax.all_gather(c, axis).reshape(n_dev * b_cap)
                for c in b_cols
            ]
            b_live_local = jnp.arange(b_cap, dtype=jnp.int32) < b_rows_s[0]
            g_live = lax.all_gather(b_live_local, axis).reshape(
                n_dev * b_cap
            )
            ev_b = DeviceEvaluator(
                b_schema, [(c, None) for c in g_cols], n_dev * b_cap
            )
            bk, _ = ev_b.evaluate(b_key)
            # dead rows take the dtype-max sentinel so the array stays
            # GLOBALLY sorted (searchsorted requires it; sorting dead rows
            # last by a separate rank key would break that invariant)
            if jnp.issubdtype(bk.dtype, jnp.floating):
                sentinel = jnp.asarray(jnp.inf, bk.dtype)
            else:
                sentinel = jnp.asarray(jnp.iinfo(bk.dtype).max, bk.dtype)
            bk_keyed = jnp.where(g_live, bk, sentinel)
            order = jnp.argsort(bk_keyed, stable=True)
            bk_sorted = jnp.take(bk_keyed, order)
            n_build = jnp.sum(g_live.astype(jnp.int32))
            ev_p = DeviceEvaluator(
                p_schema, [(c, None) for c in p_cols], p_cap
            )
            pk, _ = ev_p.evaluate(p_key)
            pos = jnp.searchsorted(bk_sorted, pk)
            pos = jnp.clip(pos, 0, n_dev * b_cap - 1)
            hit = (jnp.take(bk_sorted, pos) == pk) & (pos < n_build)
            p_live = jnp.arange(p_cap, dtype=jnp.int32) < p_rows_s[0]
            hit = hit & p_live
            build_idx = jnp.take(order, pos)
            out_build = [
                jnp.take(g, build_idx)[None] for g in g_cols
            ]
            return (hit[None],) + tuple(out_build)

        n_out = 1 + len(b_schema)
        fn = shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(axis), P(axis))
            + tuple(P(axis) for _ in range(len(p_schema)))
            + tuple(P(axis) for _ in range(len(b_schema))),
            out_specs=tuple([P(axis)] * n_out),
        )

        @jax.jit
        def run(probe_cols, probe_rows, build_cols, build_rows):
            outs = fn(
                probe_rows, build_rows, *probe_cols, *build_cols
            )
            return outs[0], list(outs[1:])

        return run


def _key_dtype(e: ir.Expr, schema: Schema) -> DataType:
    dt = infer_dtype(e, schema)
    if dt.is_dictionary_encoded:
        raise NotImplementedError(
            "string group keys use the file-shuffle tier"
        )
    return dt


class DistributedRepartition:
    """Hash repartition of whole rows over ICI: every live row moves to
    the device its key hash owns with one `lax.all_to_all` per column -
    the mesh-native form of the hash ShuffleExchange (what Spark plants
    under a window's PARTITION BY), carrying the FULL row instead of
    partial aggregate states. Same program-holder shape as
    DistributedGroupBy (prepare() returns True only on a real trace),
    so it plugs into the fingerprint-keyed program cache.

    Output shards are [n_dev * cap] column stacks plus a live mask per
    shard; the caller compacts live rows host-side at the mesh
    boundary. Skew bound: a device receiving more than `cap` rows from
    any single sender overflows its fixed bucket; callers size cap from
    the stacked input (every sender holds <= cap live rows), which is
    always sufficient because a sender contributes at most its own cap
    to any one destination."""

    def __init__(self, mesh: Mesh, schema: Schema,
                 keys: Sequence[ir.Expr], axis: str = "data"):
        self.mesh = mesh
        self.axis = axis
        self.schema = schema
        self.keys = [bind_opt(k, schema) for k in keys]
        for k in self.keys:
            _key_dtype(k, schema)  # raises for non-device-hashable keys
        self._fn = None
        self._exec = None
        self._exec_sig = None
        self._traced_sigs = set()

    def _sig(self, stacked_cols, num_rows) -> Tuple:
        return (
            tuple((tuple(c.shape), str(c.dtype)) for c in stacked_cols),
            (tuple(num_rows.shape), str(num_rows.dtype)),
        )

    def prepare(self, stacked_cols: Sequence[jax.Array],
                num_rows: jax.Array) -> bool:
        sig = self._sig(stacked_cols, num_rows)
        if self._fn is None:
            self._fn = self._compile()
        if sig in self._traced_sigs:
            return False
        self._traced_sigs.add(sig)
        try:
            self._exec = self._fn.lower(
                *stacked_cols, num_rows
            ).compile()
            self._exec_sig = sig
        except Exception:  # noqa: BLE001 - AOT unsupported: trace at launch
            self._exec = None
            self._exec_sig = None
        return True

    def __call__(self, stacked_cols: Sequence[jax.Array],
                 num_rows: jax.Array):
        """stacked_cols: [n_dev, cap] per column; num_rows: [n_dev].
        Returns (out_cols, live): out_cols are [n_dev, n_dev * cap]
        stacks, live the matching row mask."""
        if self._fn is None:
            self._fn = self._compile()
        if (self._exec is not None
                and self._exec_sig == self._sig(stacked_cols, num_rows)):
            return self._exec(*stacked_cols, num_rows)
        return self._fn(*stacked_cols, num_rows)

    def _compile(self):
        mesh, axis = self.mesh, self.axis
        n_dev = mesh.shape[axis]
        schema = self.schema
        keys = self.keys
        n_cols = len(schema.fields)

        def per_shard(num_rows_s, *cols_s):
            cols = [c[0] for c in cols_s]
            nr = num_rows_s[0]
            cap = cols[0].shape[0]
            live = jnp.arange(cap, dtype=jnp.int32) < nr
            ev = DeviceEvaluator(
                schema, [(c, None) for c in cols], cap
            )
            key_vals = [ev.evaluate(k)[0] for k in keys]
            kcols = [
                (v, None, _key_dtype(keys[i], schema))
                for i, v in enumerate(key_vals)
            ]
            target = pmod(hash_columns_device(kcols, cap), n_dev)
            exchanged = []
            for arr in cols:
                b = _bucketize(arr, target, live, n_dev, cap)
                ex = lax.all_to_all(
                    b[None], axis, split_axis=1, concat_axis=0
                )
                exchanged.append(ex.reshape(n_dev * cap))
            lv = _bucket_live(target, live, n_dev, cap)
            lx = lax.all_to_all(
                lv[None], axis, split_axis=1, concat_axis=0
            ).reshape(n_dev * cap)
            return (
                tuple(c[None, :] for c in exchanged) + (lx[None, :],)
            )

        fn = shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(axis),) + tuple(P(axis) for _ in range(n_cols)),
            out_specs=tuple([P(axis)] * (n_cols + 1)),
        )

        @jax.jit
        def run(*args):
            num_rows = args[-1]
            cols = args[:-1]
            outs = fn(num_rows, *cols)
            return list(outs[:-1]), outs[-1]

        return run
