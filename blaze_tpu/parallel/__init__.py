"""Distributed execution tier.

The reference's parallelism inventory (SURVEY 2.3/2.4) mapped TPU-first:

| reference mechanism                  | here                               |
|--------------------------------------|------------------------------------|
| per-partition data-parallel tasks    | mesh 'data' axis: one partition    |
|   (NativeRDD.compute per partition)  | per device via shard_map           |
| hash repartition shuffle (murmur3 +  | intra-slice: lax.all_to_all over   |
|   segmented-IPC files)               | ICI (parallel/repartition);        |
|                                      | inter-node: segmented-IPC files    |
|                                      | (ShuffleExchangeExec), same disk   |
|                                      | format as the reference            |
| broadcast replication (Torrent       | lax.all_gather over ICI /          |
|   broadcast of IPC bytes)            | BroadcastExchangeExec (IPC bytes)  |
| AQE coalesced/ranged shuffle reads   | CoalescedShuffleReader partition   |
|                                      | range mapping                      |

The two-tier design follows SURVEY 2.4's north star: XLA collectives ride
ICI inside a slice; the segmented Arrow-IPC file fabric (Spark-compatible)
spans hosts over DCN.
"""

from blaze_tpu.parallel.mesh import get_mesh, device_count
from blaze_tpu.parallel.exchange import (
    BroadcastExchangeExec,
    ClusterShuffleExchangeExec,
    CoalescedShuffleReader,
    RemoteClusterShuffleExchangeExec,
    ShuffleExchangeExec,
)
from blaze_tpu.parallel.mesh_exec import (
    MeshBroadcastJoinExec,
    MeshPipelineExec,
)
from blaze_tpu.parallel.mesh_ops import MeshGroupByExec

__all__ = [
    "get_mesh",
    "device_count",
    "ShuffleExchangeExec",
    "ClusterShuffleExchangeExec",
    "RemoteClusterShuffleExchangeExec",
    "BroadcastExchangeExec",
    "CoalescedShuffleReader",
    "MeshGroupByExec",
    "MeshPipelineExec",
    "MeshBroadcastJoinExec",
]
