"""Mesh-integrated operators: the ICI fast path as a PhysicalOp.

`MeshGroupByExec` executes an entire two-phase GROUP BY across the device
mesh in one pjit program (parallel/sharded.DistributedGroupBy): each child
partition lands on one device, partial-aggregates locally, exchanges
partial states by key hash over ICI (all_to_all), and final-merges on the
owner - replacing a ShuffleExchange(partial->final) pair with zero host
round trips for slice-resident data. The file-fabric path remains the
fallback for string keys / more partitions than devices / multi-host.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.types import DataType, Field, Schema, TypeId
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr, AggFn
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.util import concat_batches
from blaze_tpu.parallel.mesh import get_mesh
from blaze_tpu.parallel.sharded import DistAgg, DistributedGroupBy


class MeshGroupByExec(PhysicalOp):
    """GROUP BY over the whole mesh in one dispatch.

    Constraints (fall back to exchange+aggregate otherwise): fixed-width
    non-null-sensitive key/agg exprs (no strings), child partition count
    <= mesh size. Output: one partition per device (group-disjoint).
    """

    def __init__(self, child: PhysicalOp,
                 keys: Sequence[Tuple[ir.Expr, str]],
                 aggs: Sequence[Tuple[AggExpr, str]],
                 filter_pred: ir.Expr = None,
                 mesh=None,
                 fallback: PhysicalOp = None):
        # data-dependent ineligibility (nullable inputs materializing
        # actual validity masks) only surfaces at execution: `fallback`
        # is the ORIGINAL aggregate plan to run instead - the runtime
        # half of tryConvert semantics
        self.fallback = fallback
        self._use_fallback = False
        self.children = [child]
        self.mesh = mesh or get_mesh()
        in_schema = child.schema
        self.keys = list(keys)
        self.aggs = list(aggs)
        self.filter_pred = filter_pred
        for e, _ in keys:
            if infer_dtype(ir.bind(e, in_schema),
                           in_schema).is_string_like:
                raise NotImplementedError(
                    "string keys use the file-shuffle tier"
                )
        key_fields = [
            Field(n, infer_dtype(ir.bind(e, in_schema), in_schema), True)
            for e, n in keys
        ]
        agg_fields = []
        for a, n in aggs:
            if a.fn in (AggFn.COUNT, AggFn.COUNT_STAR):
                agg_fields.append(Field(n, DataType.int64(), False))
            elif a.fn is AggFn.AVG:
                agg_fields.append(Field(n, DataType.float64(), True))
            else:
                agg_fields.append(
                    Field(
                        n,
                        infer_dtype(
                            ir.bind(a.child, in_schema), in_schema
                        ),
                        True,
                    )
                )
        self._schema = Schema(key_fields + agg_fields)
        self._gb = DistributedGroupBy(
            self.mesh, in_schema,
            keys=[e for e, _ in keys],
            aggs=[DistAgg(a.fn, a.child) for a, _ in aggs],
            filter_pred=filter_pred,
        )
        self._result = None

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return int(self.mesh.shape["data"])

    def _run(self, ctx: ExecContext):
        if self._result is not None:
            return self._result
        child = self.children[0]
        n_dev = self.partition_count
        assert child.partition_count <= n_dev, (
            "more partitions than devices; use the exchange tier"
        )
        per_part = []
        for p in range(child.partition_count):
            b = concat_batches(
                list(child.execute(p, ctx)), schema=child.schema
            )
            # fail fast BEFORE materializing the remaining partitions:
            # a nullable input detected here falls back to the
            # original plan, and everything collected so far is sunk
            # cost
            for c in b.columns:
                if c.validity is not None:
                    raise NotImplementedError(
                        "mesh group-by handles non-nullable columns; "
                        "nullable inputs use the exchange tier"
                    )
            per_part.append(b)
        # pad to a common capacity and stack [n_dev, cap] per column
        cap = max(max((b.capacity for b in per_part), default=1), 1)
        ncols = len(child.schema)
        from blaze_tpu.parallel.mesh import data_sharding

        sharding = data_sharding(self.mesh)
        multi = jax.process_count() > 1

        def to_mesh(global_np):
            # single-controller: a plain device array suffices. Multi-
            # process SPMD: every rank holds the full logical value (the
            # task decodes rank-symmetrically), so build the global
            # array from each rank's addressable shards - a plain
            # jnp.asarray would be process-local and the pjit would
            # reject it
            if not multi:
                return jnp.asarray(global_np)
            return jax.make_array_from_callback(
                global_np.shape, sharding,
                lambda idx: global_np[idx],
            )

        stacked = []
        for ci in range(ncols):
            phys = child.schema.fields[ci].dtype.physical_dtype()
            rows = []
            for b in per_part:
                v = np.asarray(b.columns[ci].values)
                if len(v) < cap:
                    v = np.pad(v, (0, cap - len(v)))
                rows.append(v)
            for _ in range(n_dev - len(per_part)):
                rows.append(np.zeros(cap, dtype=phys))
            stacked.append(to_mesh(np.stack(rows)))
        num_rows = to_mesh(
            np.array(
                [b.num_rows for b in per_part]
                + [0] * (n_dev - len(per_part)),
                dtype=np.int32,
            )
        )
        key_out, agg_out, counts = self._gb(stacked, num_rows)
        if multi:
            # every rank needs every device's output slice (execute()
            # may be asked for any partition): allgather the small
            # grouped results
            from blaze_tpu.parallel.mesh import allgather_rows

            key_out = [allgather_rows(k, n_dev) for k in key_out]
            agg_out = [allgather_rows(a, n_dev) for a in agg_out]
            counts = allgather_rows(counts, n_dev, trailing=False)
        self._result = (key_out, agg_out, np.asarray(counts))
        ctx.metrics.add("mesh_groupby_groups", int(self._result[2].sum()))
        return self._result

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if self.fallback is not None and not self._use_fallback:
            try:
                self._run(ctx)
            except NotImplementedError:
                self._use_fallback = True
                self._result = None
        if self._use_fallback:
            if partition < self.fallback.partition_count:
                yield from self.fallback.execute(partition, ctx)
            return
        key_out, agg_out, counts = self._run(ctx)
        n = int(counts[partition])
        if n == 0:
            return
        cols: List[Column] = []
        for arr, f in zip(
            list(key_out) + list(agg_out), self._schema.fields
        ):
            v = arr[partition].astype(f.dtype.physical_dtype())
            cols.append(Column(f.dtype, v, None, None))
        yield ColumnBatch(self._schema, cols, n)
