"""Mesh-integrated operators: the ICI fast path as a PhysicalOp.

`MeshGroupByExec` executes an entire two-phase GROUP BY across the device
mesh in one pjit program (parallel/sharded.DistributedGroupBy): each child
partition lands on one device, partial-aggregates locally, exchanges
partial states by key hash over ICI (all_to_all), and final-merges on the
owner - replacing a ShuffleExchange(partial->final) pair with zero host
round trips for slice-resident data. The file-fabric path remains the
fallback for string keys / more partitions than devices / multi-host.
"""

from __future__ import annotations

import time
from typing import Iterator, List, Sequence, Tuple

import numpy as np

import jax

from blaze_tpu.types import DataType, Field, Schema, TypeId
from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr, AggFn
from blaze_tpu.exprs.typing import infer_dtype
from blaze_tpu.obs import contention as obs_contention
from blaze_tpu.obs import meshprof
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.parallel.mesh import get_mesh
from blaze_tpu.parallel.mesh_exec import (
    degrade_or_raise,
    mesh_chaos,
    record_exchange,
    record_mesh_run,
    stack_partitions,
)
from blaze_tpu.parallel.sharded import DistAgg, DistributedGroupBy
from blaze_tpu.runtime import dispatch


class MeshGroupByExec(PhysicalOp):
    """GROUP BY over the whole mesh in one dispatch.

    Constraints (fall back to exchange+aggregate otherwise): fixed-width
    non-null-sensitive key/agg exprs (no strings), child partition count
    <= mesh size. Output: one partition per device (group-disjoint).
    """

    def __init__(self, child: PhysicalOp,
                 keys: Sequence[Tuple[ir.Expr, str]],
                 aggs: Sequence[Tuple[AggExpr, str]],
                 filter_pred: ir.Expr = None,
                 mesh=None,
                 fallback: PhysicalOp = None):
        # data-dependent ineligibility (nullable inputs materializing
        # actual validity masks) only surfaces at execution: `fallback`
        # is the ORIGINAL aggregate plan to run instead - the runtime
        # half of tryConvert semantics
        self.fallback = fallback
        self._use_fallback = False
        self.children = [child]
        self.mesh = mesh or get_mesh()
        in_schema = child.schema
        self.keys = list(keys)
        self.aggs = list(aggs)
        self.filter_pred = filter_pred
        for e, _ in keys:
            if infer_dtype(ir.bind(e, in_schema),
                           in_schema).is_string_like:
                raise NotImplementedError(
                    "string keys use the file-shuffle tier"
                )
        key_fields = [
            Field(n, infer_dtype(ir.bind(e, in_schema), in_schema), True)
            for e, n in keys
        ]
        agg_fields = []
        for a, n in aggs:
            if a.fn in (AggFn.COUNT, AggFn.COUNT_STAR):
                agg_fields.append(Field(n, DataType.int64(), False))
            elif a.fn is AggFn.AVG:
                agg_fields.append(Field(n, DataType.float64(), True))
            else:
                agg_fields.append(
                    Field(
                        n,
                        infer_dtype(
                            ir.bind(a.child, in_schema), in_schema
                        ),
                        True,
                    )
                )
        self._schema = Schema(key_fields + agg_fields)
        # program identity is structural (fleet/program_cache): a fresh
        # lowering of the same plan shape on the same mesh reuses the
        # already-traced DistributedGroupBy instead of re-paying the
        # trace (prepare() sees a known signature -> no retrace)
        from blaze_tpu.fleet.program_cache import (
            PROGRAM_CACHE, mesh_cache_key,
        )

        cache_key = (
            "mesh.groupby",
            tuple((f.name, repr(f.dtype), f.nullable)
                  for f in in_schema.fields),
            tuple(repr(e) for e, _ in keys),
            tuple((a.fn, repr(a.child)) for a, _ in aggs),
            repr(filter_pred),
            mesh_cache_key(self.mesh),
        )
        self._gb = PROGRAM_CACHE.get_or_build(
            cache_key,
            lambda: DistributedGroupBy(
                self.mesh, in_schema,
                keys=[e for e, _ in keys],
                aggs=[DistAgg(a.fn, a.child) for a, _ in aggs],
                filter_pred=filter_pred,
            ),
        )
        self._result = None
        # single-flight: concurrent partition pulls (the parallel
        # scheduler) must compile/launch the mesh program once; named
        # so wait:hold lands in the contention report when armed
        self._lock = obs_contention.TimedLock("mesh_groupby")

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def partition_count(self) -> int:
        return int(self.mesh.shape["data"])

    def _trace_key(self, sig) -> tuple:
        """Logical program identity for re-trace accounting: op kind +
        structural key/agg/filter expressions + argument signature
        (the bound IR dataclasses repr structurally)."""
        return (
            "mesh.groupby",
            tuple(repr(k) for k in self._gb.keys),
            tuple((a.fn, repr(a.expr)) for a in self._gb.aggs),
            repr(self._gb.filter_pred),
            sig,
        )

    def _run(self, ctx: ExecContext):
        with self._lock:
            if self._result is not None:
                return self._result
            child = self.children[0]
            n_dev = self.partition_count
            st = meshprof.stage(
                "mesh.groupby", n_dev,
                lower_window=getattr(self, "_mesh_lower", None),
            )
            # HBM-resident staging: partitions land sharded over the
            # mesh and stay device-side through the whole program -
            # host spill happens only at the mesh boundary (the
            # grouped-result fetch below)
            with st.phase("mesh_stage_in"):
                stacked, num_rows, cap, total, host_cols = (
                    stack_partitions(child, ctx, self.mesh)
                )
                st.add_bytes(sum(h.nbytes for h in host_cols))
            multi = jax.process_count() > 1
            with st.phase("mesh_trace"):
                if self._gb.prepare(stacked, num_rows):
                    meshprof.note_trace(
                        "mesh.groupby",
                        self._trace_key(meshprof.arg_signature(
                            *stacked, num_rows
                        )),
                    )
            t0 = time.monotonic()
            with st.phase("mesh_launch"):
                mesh_chaos("mesh.groupby", n_dev, ctx)
                dispatch.record("dispatches")
                dispatch.record("mesh_dispatches")
                key_out, agg_out, counts = self._gb(stacked, num_rows)
            if multi:
                # every rank needs every device's output slice
                # (execute() may be asked for any partition):
                # allgather the small grouped results - the whole
                # collect lands in mesh_gather (no separate sync)
                from blaze_tpu.parallel.mesh import allgather_rows

                with st.phase("mesh_gather"):
                    key_out = [
                        allgather_rows(k, n_dev) for k in key_out
                    ]
                    agg_out = [
                        allgather_rows(a, n_dev) for a in agg_out
                    ]
                    counts = allgather_rows(
                        counts, n_dev, trailing=False
                    )
            else:
                with st.phase("mesh_sync"):
                    key_out, agg_out, counts = jax.block_until_ready(
                        (key_out, agg_out, counts)
                    )
                with st.phase("mesh_gather"):
                    key_out, agg_out, counts = dispatch.device_get(
                        (key_out, agg_out, counts)
                    )
            t1 = st.finish()
            counts = np.asarray(counts)
            # the partial-state repartition inside the program is the
            # exchange: every live input row's partial group crosses
            # ICI at most once (conservatively counted as the input
            # rows - the partial states are bounded by them)
            nbytes = total * sum(
                np.dtype(f.dtype.physical_dtype()).itemsize
                for f in self.schema.fields
            )
            record_exchange(ctx, "all_to_all", total, nbytes)
            nr_host = np.asarray(num_rows)
            record_mesh_run(
                ctx, "mesh.groupby", n_dev, t0, t1,
                [{"rows_in": int(nr_host[d]),
                  "groups_out": int(counts[d])}
                 for d in range(n_dev)],
                stage=st,
            )
            self._result = (
                [np.asarray(k) for k in key_out],
                [np.asarray(a) for a in agg_out],
                counts,
            )
            ctx.metrics.add(
                "mesh_groupby_groups", int(self._result[2].sum())
            )
            return self._result

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if self.fallback is not None and not self._use_fallback:
            try:
                self._run(ctx)
            except Exception as e:  # noqa: BLE001 - failure ladder:
                # TRANSIENT propagates (task retry re-runs the mesh),
                # everything else degrades to the single-device plan
                degrade_or_raise(self, ctx, e)
        if self._use_fallback:
            if partition < self.fallback.partition_count:
                yield from self.fallback.execute(partition, ctx)
            return
        key_out, agg_out, counts = self._run(ctx)
        n = int(counts[partition])
        if n == 0:
            return
        cols: List[Column] = []
        for arr, f in zip(
            list(key_out) + list(agg_out), self._schema.fields
        ):
            v = arr[partition].astype(f.dtype.physical_dtype())
            cols.append(Column(f.dtype, v, None, None))
        yield ColumnBatch(self._schema, cols, n)
